"""The hvd-lint engine: rule registry, parallel walk, suppressions,
dated baseline with ratchet semantics (docs/ANALYSIS.md).

Design contract (mirrors the runtime diagnosis plane's "post-hoc and
online diagnosis cannot disagree" rule): a pass that lands before the
tree is clean ships its pre-existing findings in the committed baseline
file, every baseline entry is dated, and baseline *shrinkage is a
ratchet* — when a baselined finding disappears from the tree, the stale
entry fails the run until the baseline is re-written, so the removed
defect cannot silently come back under old slack. Inline suppressions
(``# hvd-lint: disable=RULE -- justification``) require a non-empty
justification; a bare disable is itself a finding (HVD-SUPPRESS).
"""

import ast
import concurrent.futures
import dataclasses
import fnmatch
import io
import json
import os
import re
import time
import tokenize

# the rule a malformed / unjustified suppression is reported under —
# engine-level, cannot itself be suppressed
SUPPRESS_RULE = "HVD-SUPPRESS"

_SUPPRESS_RE = re.compile(
    r"#\s*hvd-lint:\s*disable=([A-Za-z0-9,\-]+)"
    r"(?:\s+--\s*(?P<why>.*\S))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect at one site. ``fingerprint`` is the stripped source
    line — line-number independent, so baselines survive unrelated
    edits above the finding."""
    rule: str
    file: str
    line: int
    col: int
    message: str
    hint: str = ""
    fingerprint: str = ""

    def format(self):
        s = f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def as_json(self):
        return dataclasses.asdict(self)


class LintError(Exception):
    """Engine-level failure (unreadable file, bad baseline, rule crash)
    — the CLI maps this to exit code 2, never to a findings exit."""


@dataclasses.dataclass
class ParsedFile:
    path: str        # as walked (absolute or as given)
    rel: str         # relative to the lint root — the baseline key
    tree: ast.AST
    source: str
    lines: list      # 1-indexed access via lines[lineno - 1]


@dataclasses.dataclass
class Rule:
    name: str
    scope: str       # "file" | "project"
    doc: str
    check: object    # file: f(ParsedFile) -> [Finding]; project: f({rel: ParsedFile}, root) -> [Finding]
    # project rules may anchor findings in files the walk never parses
    # (HVD-METRIC: the docs table). scope_files(parsed, root) names the
    # extra files the rule ACTUALLY examined this run, so baseline
    # entries for them stay matchable (and ratchetable) — without it a
    # docs-anchored entry would never spend its budget.
    scope_files: object = None


_RULES = {}


def register(name, scope="file", doc="", scope_files=None):
    """Decorator: register a pass under its HVD-* name."""
    def deco(fn):
        if name in _RULES:
            raise LintError(f"duplicate rule {name}")
        _RULES[name] = Rule(name=name, scope=scope, doc=doc or fn.__doc__
                            or "", check=fn, scope_files=scope_files)
        return fn
    return deco


def all_rules():
    return dict(_RULES)


# ---------------------------------------------------------------------------
# walk + parse


def default_targets(root):
    """The tier-1 lint surface: the package, the examples, and the
    bench drivers (ISSUE 12 acceptance)."""
    out = []
    for d in ("horovod_tpu", "examples"):
        p = os.path.join(root, d)
        if os.path.isdir(p):
            out.append(p)
    for f in sorted(os.listdir(root)):
        if fnmatch.fnmatch(f, "bench*.py"):
            out.append(os.path.join(root, f))
    return out


def _collect(paths):
    files, seen = [], set()

    def add(path):
        real = os.path.realpath(path)
        if real not in seen:  # overlapping targets: parse once
            seen.add(real)
            files.append(path)

    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for n in sorted(names):
                    if n.endswith(".py"):
                        add(os.path.join(dirpath, n))
        elif os.path.isfile(p):
            add(p)
        else:
            raise LintError(f"no such lint target: {p}")
    return files


def _parse_one(path, root):
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        raise LintError(f"cannot parse {path}: {e}")
    rel = os.path.relpath(path, root)
    if rel.startswith(".."):
        rel = path  # outside the root: keep the full path as the key
    # baseline keys and finding paths are ALWAYS forward-slash — the
    # committed ledger must match on every platform
    rel = rel.replace(os.sep, "/")
    return ParsedFile(path=path, rel=rel, tree=tree, source=source,
                      lines=source.splitlines())


# ---------------------------------------------------------------------------
# suppressions


def _comment_tokens(pf):
    """``[(lineno, col, text)]`` for real COMMENT tokens only — a
    suppression-shaped line inside a string literal or docstring (e.g.
    documentation showing the syntax) must neither suppress nor be
    flagged as malformed. Falls back to a per-line scan restricted to
    lines the tokenizer never saw if tokenization fails (it should
    not: the file already parsed)."""
    try:
        return [(tok.start[0], tok.start[1], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(pf.source).readline)
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return [(i, t.index("#"), t[t.index("#"):])
                for i, t in enumerate(pf.lines, start=1) if "#" in t]


def _suppressions(pf):
    """``({lineno: rules}, [malformed findings])``. A comment on its
    own line covers the NEXT line; a trailing comment covers its own
    line. A disable without a ``-- justification`` is itself a finding
    (HVD-SUPPRESS) — the justification is the suppression's contract."""
    covered, malformed = {}, []
    for lineno, col, text in _comment_tokens(pf):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(1).split(",")
                 if r.strip()}
        why = m.group("why")
        own_line = pf.lines[lineno - 1].lstrip().startswith("#")
        target = lineno + 1 if own_line else lineno
        if not why:
            malformed.append(Finding(
                rule=SUPPRESS_RULE, file=pf.rel, line=lineno,
                col=col + 1,
                message="suppression without a justification",
                hint="write `# hvd-lint: disable=RULE -- <why this is "
                     "safe>` — the justification is load-bearing "
                     "(docs/ANALYSIS.md)",
                fingerprint=text.strip()))
            continue
        covered.setdefault(target, set()).update(rules)
    return covered, malformed


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path):
    if path is None or not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data["entries"]
        for e in entries:
            for key in ("rule", "file", "fingerprint", "count", "date"):
                if key not in e:
                    raise KeyError(key)
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise LintError(f"bad baseline file {path}: {e!r}")
    return entries


def write_baseline(path, findings, previous=None, date=None, keep=()):
    """Serialize ``findings`` as the new baseline. Entries that already
    existed keep their original date (the date records when the debt was
    incurred, not when the file was last rewritten). ``keep`` carries
    prior entries that were OUTSIDE the producing run's scope — they
    are written back verbatim so a partial-target or rule-restricted
    ``--baseline write`` cannot delete another subtree's debt."""
    date = date or time.strftime("%Y-%m-%d")
    prev_dates = {}
    for e in previous or []:
        prev_dates[(e["rule"], e["file"], e["fingerprint"])] = e["date"]
    counts = {}
    for f in findings:
        key = (f.rule, f.file, f.fingerprint)
        counts[key] = counts.get(key, 0) + 1
    entries = [
        {"rule": rule, "file": file, "fingerprint": fp, "count": n,
         "date": prev_dates.get((rule, file, fp), date)}
        for (rule, file, fp), n in sorted(counts.items())]
    entries = sorted(
        entries + [dict(e) for e in keep],
        key=lambda e: (e["rule"], e["file"], e["fingerprint"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "comment": "hvd-lint debt ledger — shrink-only "
                              "(docs/ANALYSIS.md); regenerate with "
                              "`hvd-lint --baseline write`",
                   "entries": entries}, f, indent=1, sort_keys=False)
        f.write("\n")
    return entries


def _apply_baseline(findings, entries):
    """Split findings into (unbaselined, baselined) and compute stale
    entries (the ratchet: a baselined finding that no longer exists)."""
    budget = {}
    for e in entries:
        key = (e["rule"], e["file"], e["fingerprint"])
        budget[key] = budget.get(key, 0) + int(e["count"])
    spent = {}
    new, old = [], []
    for f in findings:
        key = (f.rule, f.file, f.fingerprint)
        if spent.get(key, 0) < budget.get(key, 0):
            spent[key] = spent.get(key, 0) + 1
            old.append(f)
        else:
            new.append(f)
    stale = []
    for e in entries:
        key = (e["rule"], e["file"], e["fingerprint"])
        used = min(spent.get(key, 0), int(e["count"]))
        spent[key] = spent.get(key, 0) - used
        if used < int(e["count"]):
            stale.append(dict(e, count=int(e["count"]) - used))
    return new, old, stale


# ---------------------------------------------------------------------------
# the run


@dataclasses.dataclass
class LintResult:
    findings: list          # unsuppressed, unbaselined — these fail the run
    suppressed: list        # (finding, justification-covered)
    baselined: list
    stale_baseline: list    # ratchet violations — these ALSO fail the run
    all_findings: list      # post-suppression, pre-baseline (--baseline write input)
    files: int = 0
    walked: frozenset = frozenset()   # rel paths parsed OR examined by
    #                                   a project rule (scope_files)
    rules: frozenset = frozenset()    # rule names this run executed

    @property
    def clean(self):
        return not self.findings and not self.stale_baseline

    def as_json(self):
        return {
            "clean": self.clean,
            "files": self.files,
            "findings": [f.as_json() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
        }


def _check_file(rule, pf):
    try:
        return list(rule.check(pf))
    except LintError:
        raise
    except Exception as e:  # hvd-lint: disable=HVD-EXCEPT -- a rule crash must surface as an engine error (exit 2) with the rule named, not kill the whole run anonymously
        raise LintError(f"rule {rule.name} crashed on {pf.rel}: {e!r}")


def run_lint(paths, root=None, rules=None, baseline_path=None,
             jobs=None):
    """Run the registered passes over ``paths``.

    ``root`` anchors the relative file keys used by baselines and
    findings (default: cwd). ``rules`` restricts to a subset of rule
    names. ``baseline_path`` points at the committed debt ledger.
    """
    root = os.path.abspath(root or os.getcwd())
    selected = all_rules()
    if rules is not None:
        unknown = set(rules) - set(selected)
        if unknown:
            raise LintError(f"unknown rule(s): {sorted(unknown)}")
        selected = {n: r for n, r in selected.items() if n in rules}
    if not selected:
        raise LintError("no rules registered — import "
                        "horovod_tpu.analysis (not .engine) to load "
                        "the passes")
    files = _collect(list(paths))
    file_rules = [r for r in selected.values() if r.scope == "file"]
    proj_rules = [r for r in selected.values() if r.scope == "project"]

    parsed = {}
    raw = []

    def _one(path):
        pf = _parse_one(path, root)
        out = []
        for r in file_rules:
            out.extend(_check_file(r, pf))
        return pf, out

    # per-file parallel walk: parse + file-scoped passes fan out over a
    # thread pool (the AST work is pure-Python but I/O and the many
    # small files still overlap; jobs=1 gives a deterministic
    # single-threaded walk for debugging)
    jobs = jobs or min(8, (os.cpu_count() or 2))
    if jobs <= 1 or len(files) <= 1:
        results = [_one(p) for p in files]
    else:
        with concurrent.futures.ThreadPoolExecutor(jobs) as ex:
            results = list(ex.map(_one, files))
    for pf, founds in results:
        parsed[pf.rel] = pf
        raw.extend(founds)
    for r in proj_rules:
        try:
            raw.extend(r.check(parsed, root))
        except LintError:
            raise
        except Exception as e:  # hvd-lint: disable=HVD-EXCEPT -- same contract as _check_file: name the crashed rule, exit 2
            raise LintError(f"rule {r.name} crashed: {e!r}")

    # suppressions (per file), then the baseline
    kept, suppressed = [], []
    sup_cache = {}
    for f in raw:
        pf = parsed.get(f.file)
        if pf is None:
            kept.append(f)
            continue
        if f.file not in sup_cache:
            covered, malformed = _suppressions(pf)
            sup_cache[f.file] = covered
            kept.extend(malformed)
        covered = sup_cache[f.file]
        if f.rule != SUPPRESS_RULE and f.rule in covered.get(f.line, ()):
            suppressed.append(f)
        else:
            kept.append(f)
    # files with malformed suppressions but zero findings still report
    for rel, pf in parsed.items():
        if rel not in sup_cache:
            covered, malformed = _suppressions(pf)
            sup_cache[rel] = covered
            kept.extend(malformed)

    # scope the baseline to this run: entries for rules that did not
    # run are inert, and entries for files that exist under the root
    # but were not walked (a partial-target run) neither spend budget
    # nor count as stale. Entries for files that no longer exist at
    # all DO count as stale — a deleted file's debt must leave the
    # ledger with it (the ratchet). Project rules extend the scope
    # with the non-walked files they examined (HVD-METRIC: the docs
    # table), else their doc-anchored findings could never baseline.
    in_scope = frozenset(parsed)
    for r in proj_rules:
        if r.scope_files is not None:
            in_scope |= frozenset(r.scope_files(parsed, root))
    entries = [e for e in load_baseline(baseline_path)
               if e["rule"] in selected and e["rule"] != SUPPRESS_RULE
               and (e["file"] in in_scope
                    or not os.path.exists(os.path.join(root, e["file"])))]
    baselinable = [f for f in kept if f.rule != SUPPRESS_RULE]
    unsupp = [f for f in kept if f.rule == SUPPRESS_RULE]
    new, old, stale = _apply_baseline(baselinable, entries)
    new.extend(unsupp)
    new.sort(key=lambda f: (f.file, f.line, f.rule))
    return LintResult(findings=new, suppressed=suppressed, baselined=old,
                      stale_baseline=stale, all_findings=kept,
                      files=len(files), walked=in_scope,
                      rules=frozenset(selected))


def entry_in_scope(entry, result, root):
    """Was this baseline entry within ``result``'s run scope? (Same
    predicate the run itself applies.) Out-of-scope entries — rules
    that did not run, files that exist under the root but were not
    walked — must be PRESERVED by ``--baseline write``, or a partial
    run would silently delete another subtree's debt (and its incurred
    dates) from the ledger."""
    return entry["rule"] in result.rules and (
        entry["file"] in result.walked
        or not os.path.exists(os.path.join(root, entry["file"])))
