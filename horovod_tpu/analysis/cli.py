"""``hvd-lint`` — the static-analysis CLI (docs/ANALYSIS.md).

    hvd-lint                          # lint the tier-1 surface from cwd
    hvd-lint horovod_tpu/elastic      # lint a subtree
    hvd-lint --rules HVD-MESH         # one pass only
    hvd-lint --format json            # structured findings for tooling
    hvd-lint --baseline write         # re-ratchet the debt ledger

Exit codes (matches bin/hvd-doctor / bin/hvd-serve conventions):
0 clean, 1 findings (or stale baseline entries — the ratchet), 2
engine error (unparseable file, bad baseline, rule crash).
"""

import argparse
import json
import os
import sys

from horovod_tpu.analysis import engine
from horovod_tpu.analysis import rules as _rules  # noqa: F401

BASELINE_NAME = ".hvd-lint-baseline.json"


def _parser():
    p = argparse.ArgumentParser(
        prog="hvd-lint",
        description="project-native static analysis: collective-desync,"
                    " host-sync, lock-order, signal-safety, broad-except"
                    ", off-mesh and metric-drift passes")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: "
                        "horovod_tpu/, examples/, bench*.py under "
                        "--root)")
    p.add_argument("--root", default=None,
                   help="project root anchoring relative paths and the "
                        "baseline (default: cwd)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (e.g. HVD-MESH)")
    p.add_argument("--baseline", default=None, choices=("write",),
                   help="'write' regenerates the baseline from current "
                        "findings (the only way the debt ledger may "
                        "change); entries outside this run's scope are "
                        "preserved")
    p.add_argument("--baseline-file", default=None,
                   help=f"debt ledger path (default: <root>/"
                        f"{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the ledger")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--jobs", type=int, default=None,
                   help="parallel file-walk width (1 = deterministic "
                        "sequential)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def main(argv=None):
    args = _parser().parse_args(argv)
    if args.list_rules:
        for name, rule in sorted(engine.all_rules().items()):
            doc = " ".join((rule.doc or "").split())
            print(f"{name:15s} [{rule.scope}] {doc}")
        return 0
    # everything through the baseline write sits inside one guard: any
    # engine failure OR environment failure (unreadable root, unwritable
    # baseline) is exit 2 — never mistakable for "findings present"
    try:
        root = os.path.abspath(args.root or os.getcwd())
        paths = args.paths or engine.default_targets(root)
        if not paths:
            print("hvd-lint: nothing to lint (no default targets under "
                  f"{root})", file=sys.stderr)
            return 2
        baseline_file = args.baseline_file or os.path.join(
            root, BASELINE_NAME)
        rules = None
        if args.rules:
            rules = {r.strip().upper() for r in args.rules.split(",")
                     if r.strip()}
        result = engine.run_lint(
            paths, root=root, rules=rules,
            baseline_path=None if args.no_baseline else baseline_file,
            jobs=args.jobs)
        if args.baseline == "write":
            previous = engine.load_baseline(
                baseline_file if os.path.exists(baseline_file) else None)
            entries = engine.write_baseline(
                baseline_file,
                [f for f in result.all_findings
                 if f.rule != engine.SUPPRESS_RULE],
                previous=previous,
                keep=[e for e in previous
                      if not engine.entry_in_scope(e, result, root)])
            print(f"hvd-lint: wrote {len(entries)} baseline entr"
                  f"{'y' if len(entries) == 1 else 'ies'} to "
                  f"{baseline_file}")
            unsupp = [f for f in result.all_findings
                      if f.rule == engine.SUPPRESS_RULE]
            for f in unsupp:
                print(f.format())
            return 1 if unsupp else 0
    except (engine.LintError, OSError) as e:
        print(f"hvd-lint: error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(result.as_json(), indent=1))
    else:
        for f in result.findings:
            print(f.format())
        for e in result.stale_baseline:
            print(f"{e['file']}: STALE-BASELINE {e['rule']} x"
                  f"{e['count']} (`{e['fingerprint']}`, dated "
                  f"{e['date']}) no longer found — the ratchet: run "
                  "`hvd-lint --baseline write` so the fixed finding "
                  "cannot silently come back")
        tail = (f"{result.files} files, "
                f"{len(result.findings)} finding(s), "
                f"{len(result.suppressed)} suppressed, "
                f"{len(result.baselined)} baselined, "
                f"{len(result.stale_baseline)} stale")
        print(("clean: " if result.clean else "FAILED: ") + tail)
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
