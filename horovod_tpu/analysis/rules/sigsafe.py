"""HVD-SIGSAFE: blocking locking or I/O inside a registered signal
handler. A Python signal handler runs *on the main thread between
bytecodes* — if it blocks on a lock another thread holds (or that the
interrupted frame itself holds, for a non-reentrant Lock), the process
wedges exactly when it was told to die. The flight recorder's
``acquire(blocking=False)`` + bounded ``wait_for_dump`` dance
(``diag/recorder.py``) is the compliant pattern this pass enforces
everywhere else."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common


def _handler_names(tree):
    """Function names registered via ``signal.signal(SIG, fn)`` and
    inline lambdas (returned as AST nodes)."""
    names, lambdas = set(), []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if common.call_name(node) != "signal":
            continue
        recv = common.receiver_ident(node)
        if recv != "signal" or len(node.args) < 2:
            continue
        handler = node.args[1]
        if isinstance(handler, ast.Name):
            names.add(handler.id)
        elif isinstance(handler, ast.Lambda):
            lambdas.append(handler)
        elif isinstance(handler, ast.Attribute):
            names.add(handler.attr)
    return names, lambdas


@engine.register(
    "HVD-SIGSAFE",
    doc="blocking lock / I/O inside a registered signal handler")
def check(pf):
    names, lambdas = _handler_names(pf.tree)
    if not names and not lambdas:
        return []
    findings = []

    def flag(node, what):
        findings.append(engine.Finding(
            rule="HVD-SIGSAFE", file=pf.rel, line=node.lineno,
            col=node.col_offset + 1,
            message=f"{what} inside a signal handler",
            hint="handlers run between bytecodes on the main thread — "
                 "use acquire(blocking=False) / os.write, or set a "
                 "flag and do the work on a watcher thread "
                 "(diag/recorder.py is the compliant pattern)",
            fingerprint=common.fingerprint(pf, node.lineno)))

    def scan(body_nodes):
        for top in body_nodes:
            if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                # a def INSIDE the handler runs when called (on a
                # watcher thread — the recommended fix pattern), not
                # in the handler itself
                continue
            for node in [top] + list(common.walk_skipping_defs(top)):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ident = _with_ident(item.context_expr)
                        if ident and common.ident_is_lockish(ident):
                            flag(item.context_expr,
                                 f"blocking `with {ident}:`")
                if not isinstance(node, ast.Call):
                    continue
                name = common.call_name(node)
                recv = common.receiver_ident(node) or ""
                core = common.blocking_core_reason(node)
                if name == "acquire" and not common.kwarg_is_false(
                        node, "blocking", arg_index=0):
                    flag(node, f"blocking `{recv}.acquire()`")
                elif name == "open" and isinstance(node.func, ast.Name):
                    flag(node, "`open()` (allocates + blocks on the "
                               "filesystem)")
                elif name == "print" and isinstance(node.func, ast.Name):
                    flag(node, "`print()` (takes the stdout lock)")
                elif core:
                    flag(node, core)
                elif recv in ("logging", "logger") or \
                        recv.endswith(".logger"):
                    flag(node, f"logging call `{recv}.{name}()` "
                               "(module lock + allocation)")

    def _with_ident(expr):
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            scan(node.body)
    for lam in lambdas:
        scan([lam.body])
    return findings
