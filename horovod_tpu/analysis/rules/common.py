"""Shared AST helpers for the hvd-lint passes."""

import ast
import re

# every collective dispatch entry point on the explicit plane (eager,
# traced, and fusion-bucket) plus the jax primitives they lower to —
# the schedule these build is what diag/desync.py digests at runtime
COLLECTIVE_NAMES = frozenset({
    "allreduce", "allgather", "all_gather", "broadcast", "reducescatter",
    "reduce_scatter", "alltoall", "all_to_all", "barrier",
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "ppermute",
    "all_gather_bucket", "reduce_scatter_bucket", "fused_allreduce",
    "grouped_allreduce", "allreduce_", "grouped_allreduce_",
})
COLLECTIVE_PREFIXES = ("reduce_scatter_bucket", "all_gather_bucket")


def call_name(node):
    """The rightmost identifier of a Call's func (``hvd.allreduce`` →
    ``allreduce``), or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def is_collective_call(node):
    name = call_name(node)
    if name is None:
        return None
    if name in COLLECTIVE_NAMES or name.startswith(COLLECTIVE_PREFIXES):
        return name
    return None


_RANK_CALLS = frozenset({"rank", "local_rank", "cross_rank", "node_rank",
                         "mesh_rank", "process_index", "axis_index"})


def _ident_tokens(ident):
    return set(re.split(r"[_\d]+", ident.lower())) - {""}


# identifiers that name WHICH rank an op targets (``root_rank``,
# ``src_rank``) are world-common parameters, not this rank's identity
_TARGET_TOKENS = frozenset({"root", "src", "dst", "target", "peer"})


def ident_is_rankish(ident):
    """True for ``rank``/``local_rank``/``rank0`` — NOT for plural
    collections like ``stalled_ranks`` (a list of ranks is world-common
    state) and NOT for target-rank parameters like ``root_rank``
    (every rank passes the same value)."""
    toks = _ident_tokens(ident)
    return "rank" in toks and not (toks & _TARGET_TOKENS)


def expr_is_rank_dependent(expr):
    """Does this expression's value depend on which rank evaluates it?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            name = call_name(n)
            if name in _RANK_CALLS:
                return True
        elif isinstance(n, ast.Name) and ident_is_rankish(n.id):
            return True
        elif isinstance(n, ast.Attribute) and ident_is_rankish(n.attr):
            return True
    return False


def receiver_ident(node):
    """For an Attribute call ``x.y.z(...)`` return the identifier chain
    of the receiver (``x.y``) as a dotted string, else None."""
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return None
    parts = []
    cur = fn.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Constant):
        return "<const>"
    else:
        return None
    return ".".join(reversed(parts))


def ident_is_lockish(ident):
    toks = _ident_tokens(ident.rsplit(".", 1)[-1])
    return bool(toks & {"lock", "mutex", "mu"})


def ident_is_queueish(ident):
    toks = _ident_tokens(ident.rsplit(".", 1)[-1])
    return bool(toks & {"q", "queue"})


def kwarg_is_false(node, name, arg_index=None):
    """True when the call passes ``name=False`` — by keyword, or (when
    ``arg_index`` is given) positionally: ``lock.acquire(False)`` and
    ``q.put(ev, False)`` are the same non-blocking request as their
    keyword spellings."""
    for kw in node.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if arg_index is not None and len(node.args) > arg_index:
        arg = node.args[arg_index]
        if isinstance(arg, ast.Constant) and arg.value is False:
            return True
    return False


def blocking_core_reason(node):
    """The blocking-call classification HVD-LOCKORDER and HVD-SIGSAFE
    share: thread joins (``str.join`` excluded — it always takes a
    positional arg), bounded queue put/get (keyword OR positional
    ``block=False`` recognized as non-blocking), and sleeps. Each rule
    layers its pass-specific extras (collectives, lock acquires, I/O)
    on top — one classifier, so the passes cannot drift apart on the
    same call site."""
    name = call_name(node)
    recv = receiver_ident(node) or ""
    if name == "join" and recv and recv != "<const>" and not node.args:
        return f"`{recv}.join()`"
    if name in ("put", "get") and ident_is_queueish(recv) \
            and not kwarg_is_false(node, "block",
                                   arg_index=1 if name == "put" else 0):
        return f"bounded-queue `{recv}.{name}()`"
    if name == "sleep" and recv in ("time", ""):
        return "`time.sleep()`"
    return None


def fingerprint(pf, lineno):
    try:
        return pf.lines[lineno - 1].strip()
    except IndexError:
        return ""


def walk_skipping_defs(node):
    """Yield descendant nodes WITHOUT descending into nested function /
    lambda bodies — code inside a nested def does not execute in the
    enclosing region (it runs whenever the closure is called)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
