"""The project-native passes. Importing this package registers every
rule with the engine registry (``engine.register``); the public
catalogue with one true-positive and one justified-suppression example
per rule is docs/ANALYSIS.md."""

from horovod_tpu.analysis.rules import (  # noqa: F401
    desync, distinit, excepts, hostsync, lockorder, mesh, metric,
    sigsafe,
)
