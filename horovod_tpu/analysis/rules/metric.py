"""HVD-METRIC: metric-name drift — the former
tests/test_telemetry.py docs↔code pytest guard as an engine pass, plus
a use-site check the pytest version could not do.

Three checks against ``telemetry/instruments.py``'s CATALOGUE (parsed
from the AST, no imports — the pass must run without jax installed):

1. a name documented in docs/OBSERVABILITY.md's metric tables but not
   in CATALOGUE (a documented ghost), flagged at the table row;
2. a CATALOGUE name missing from the docs, flagged at the CATALOGUE
   tuple;
3. a registry registration (``.counter(``/``.gauge(``/``.histogram(``)
   whose name is a string literal not in CATALOGUE, flagged at the use
   site — the drift the old guard only caught if the author also
   remembered to touch the docs.

Plus the serving-trace twin of the same contract: every span kind in
``serve/tracing.py``'s SPAN_KINDS must have an entry in the serve
doctor's PHASE_OF_KIND classifier (``diag/serve_doctor.py``) and vice
versa — a kind the tracer emits but the doctor cannot classify lands
in the slow-request report as dead weight, and a classifier entry for
a kind the tracer never emits is documentation rot. Both directions
are flagged; the sub-check is skipped when either file is absent from
the parsed tree (partial-tree runs).
"""

import ast
import os
import re

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_INSTRUMENTS_SUFFIX = "telemetry/instruments.py"
_TRACING_SUFFIX = "serve/tracing.py"
_SERVE_DOCTOR_SUFFIX = "diag/serve_doctor.py"
_DOC = "docs/OBSERVABILITY.md"  # forward-slash: baseline/finding key
_DOC_ROW = re.compile(r"^\|\s*`(hvd_[a-z0-9_]+)`\s*\|")
_REGISTER_CALLS = frozenset({"counter", "gauge", "histogram"})
_NAME_RE = re.compile(r"hvd_[a-z0-9_]+\Z")


def _catalogue(pf):
    """(names, catalogue_lineno, legacy_values) parsed from the
    instruments module's AST."""
    consts, catalogue, lineno, legacy = {}, [], 1, set()
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                consts[name] = node.value.value
            elif name == "CATALOGUE" and isinstance(node.value,
                                                    ast.Tuple):
                lineno = node.lineno
                for el in node.value.elts:
                    if isinstance(el, ast.Name) and el.id in consts:
                        catalogue.append(consts[el.id])
                    elif isinstance(el, ast.Constant) and isinstance(
                            el.value, str):
                        # a direct string element is as catalogued as
                        # a named constant
                        catalogue.append(el.value)
            elif name == "LEGACY_ALIASES" and isinstance(node.value,
                                                         ast.Dict):
                for v in node.value.values:
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, str):
                        legacy.add(v.value)
    return catalogue, lineno, legacy


def _find_instruments(parsed):
    return next((pf for rel, pf in sorted(parsed.items())
                 if rel.replace("\\", "/").endswith(_INSTRUMENTS_SUFFIX)),
                None)


def _find_suffix(parsed, suffix):
    return next((pf for rel, pf in sorted(parsed.items())
                 if rel.replace("\\", "/").endswith(suffix)), None)


def _tuple_of_strings(pf, target):
    """(values, lineno) of a module-level ``TARGET = ("a", "b", ...)``
    assignment, or (None, 1) when absent/unparseable."""
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == target and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            vals = [el.value for el in node.value.elts
                    if isinstance(el, ast.Constant) and
                    isinstance(el.value, str)]
            return vals, node.lineno
    return None, 1


def _dict_string_keys(pf, target):
    """(keys, lineno) of a module-level ``TARGET = {"a": ..., ...}``
    assignment, or (None, 1) when absent/unparseable."""
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == target and \
                isinstance(node.value, ast.Dict):
            keys = [k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str)]
            return keys, node.lineno
    return None, 1


def _span_table_findings(parsed):
    """SPAN_KINDS (serve/tracing.py) ↔ PHASE_OF_KIND
    (diag/serve_doctor.py) two-way drift."""
    tracing = _find_suffix(parsed, _TRACING_SUFFIX)
    doctor = _find_suffix(parsed, _SERVE_DOCTOR_SUFFIX)
    if tracing is None or doctor is None:
        return []  # partial-tree run: contract not checkable
    kinds, kinds_line = _tuple_of_strings(tracing, "SPAN_KINDS")
    phases, phases_line = _dict_string_keys(doctor, "PHASE_OF_KIND")
    if kinds is None or phases is None:
        pf = tracing if kinds is None else doctor
        line = kinds_line if kinds is None else phases_line
        missing = "SPAN_KINDS" if kinds is None else "PHASE_OF_KIND"
        return [engine.Finding(
            rule="HVD-METRIC", file=pf.rel, line=line, col=1,
            message=f"could not parse {missing} as a module-level "
                    "string table",
            hint="keep the span table a literal tuple/dict so the "
                 "drift check can read it without imports",
            fingerprint=f"span-table:{missing}")]
    findings = []
    for kind in kinds:
        if kind not in phases:
            findings.append(engine.Finding(
                rule="HVD-METRIC", file=tracing.rel, line=kinds_line,
                col=1,
                message=f"span kind `{kind}` has no entry in the serve "
                        "doctor's PHASE_OF_KIND classifier",
                hint="hvd-doctor serve must name a phase for every "
                     "kind the tracer can emit — add the mapping in "
                     "diag/serve_doctor.py",
                fingerprint=f"SPAN_KINDS:{kind}"))
    for kind in phases:
        if kind not in kinds:
            findings.append(engine.Finding(
                rule="HVD-METRIC", file=doctor.rel, line=phases_line,
                col=1,
                message=f"PHASE_OF_KIND classifies span kind `{kind}` "
                        "that serve/tracing.py never emits",
                hint="drop the ghost entry or add the kind to "
                     "SPAN_KINDS — the classifier mirrors the span "
                     "table exactly, both ways",
                fingerprint=f"PHASE_OF_KIND:{kind}"))
    return findings


def _doc_path(root):
    return os.path.join(root, *_DOC.split("/"))


def _scope_files(parsed, root):
    """The non-walked file this pass examines: with instruments.py in
    the run, the docs table is part of the checked surface — its
    baseline entries must stay matchable (engine.Rule.scope_files)."""
    inst = _find_instruments(parsed)
    if inst is None or not os.path.exists(_doc_path(root)):
        return ()
    return (_DOC,)


@engine.register(
    "HVD-METRIC", scope="project",
    doc="metric-name drift: docs vs CATALOGUE vs use sites",
    scope_files=_scope_files)
def check(parsed, root):
    inst = _find_instruments(parsed)
    if inst is None:
        # the span-table contract is independent of instruments.py
        return _span_table_findings(parsed)
    catalogue, cat_line, legacy = _catalogue(inst)
    if not catalogue:
        return [engine.Finding(
            rule="HVD-METRIC", file=inst.rel, line=cat_line, col=1,
            message="could not parse CATALOGUE from instruments.py",
            hint="keep CATALOGUE a module-level tuple of the string "
                 "constants defined above it",
            fingerprint=common.fingerprint(inst, cat_line))]
    known = set(catalogue)
    findings = []

    # 1+2: the docs/OBSERVABILITY.md two-way drift contract
    doc_path = _doc_path(root)
    if os.path.exists(doc_path):
        with open(doc_path, encoding="utf-8") as f:
            doc_lines = f.read().splitlines()
        documented = {}
        for i, text in enumerate(doc_lines, start=1):
            m = _DOC_ROW.match(text)
            if m:
                documented.setdefault(m.group(1), i)
        for name, line in sorted(documented.items()):
            if name not in known:
                findings.append(engine.Finding(
                    rule="HVD-METRIC", file=_DOC, line=line, col=1,
                    message=f"documented metric `{name}` is not in "
                            "instruments.CATALOGUE (documented ghost)",
                    hint="remove the row or register the family — the "
                         "catalogue is the one authority "
                         "(docs/OBSERVABILITY.md header)",
                    fingerprint=doc_lines[line - 1].strip()))
        for name in catalogue:
            if name not in documented:
                findings.append(engine.Finding(
                    rule="HVD-METRIC", file=inst.rel, line=cat_line,
                    col=1,
                    message=f"catalogued metric `{name}` has no row in "
                            "docs/OBSERVABILITY.md's metric tables",
                    hint="every registered family gets a documented "
                         "row (the tier-1 drift contract)",
                    fingerprint=f"CATALOGUE:{name}"))

    # 3: string-literal registrations outside the catalogue —
    # instruments.py itself included (a literal registration there
    # dodges the CATALOGUE↔docs comparison just as easily)
    for rel, pf in sorted(parsed.items()):
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            if common.call_name(node) not in _REGISTER_CALLS:
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if not node.args or not isinstance(node.args[0],
                                               ast.Constant):
                continue
            val = node.args[0].value
            if not isinstance(val, str) or not _NAME_RE.fullmatch(val):
                continue
            if val in known or val in legacy:
                continue
            findings.append(engine.Finding(
                rule="HVD-METRIC", file=pf.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=f"metric `{val}` registered here is not in "
                        "instruments.CATALOGUE",
                hint="add the name to the catalogue (and its "
                     "docs/OBSERVABILITY.md row), or reuse an existing "
                     "family — uncatalogued names dodge the drift "
                     "contract",
                fingerprint=common.fingerprint(pf, node.lineno)))

    # 4: the serving span-table twin of the same two-way contract
    findings.extend(_span_table_findings(parsed))
    return findings
