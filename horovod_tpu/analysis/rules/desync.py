"""HVD-DESYNC: collective dispatch reachable under rank-dependent
control flow — the static twin of the runtime desync doctor
(``diag/desync.py``). Horovod's core contract is that every rank
executes an *identical* collective schedule; a collective under
``if hvd.rank() == 0`` (or after a rank-conditional early return) forks
the schedule and parks every other rank in the op forever — the hang
the flight recorder can only name after the fact."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common


def _contains_exit(stmts, kinds, skip_loops=False):
    """Does any statement (recursively — ``if rank: with x: return``
    still exits) contain an exit of ``kinds``? Nested function bodies
    never count (they exit the closure, not this scope); with
    ``skip_loops`` nested loop bodies are excluded too (a break/
    continue inside an INNER loop does not exit the current one),
    which is what the break/continue check needs."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, kinds):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if skip_loops and isinstance(n, (ast.For, ast.AsyncFor,
                                         ast.While)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


@engine.register(
    "HVD-DESYNC",
    doc="collective dispatch under rank-dependent control flow")
def check(pf):
    findings = []

    def flag(node, name, why):
        findings.append(engine.Finding(
            rule="HVD-DESYNC", file=pf.rel, line=node.lineno,
            col=node.col_offset + 1,
            message=f"collective `{name}` {why}",
            hint="every rank must dispatch an identical collective "
                 "schedule — hoist the call out of the rank branch, or "
                 "make the branch world-common (runtime twin: "
                 "diag/desync.py)",
            fingerprint=common.fingerprint(pf, node.lineno)))

    class Scope:
        """One function (or the module top level): tracks the stack of
        rank-conditional regions and the rank-conditional early exits
        seen so far, in statement order. ``return``/``raise`` exits
        taint the rest of the FUNCTION; ``break``/``continue`` only
        end an iteration, so they taint the rest of the enclosing LOOP
        body and nothing after it."""

        def __init__(self):
            self.cond_stack = []   # linenos of enclosing rank-dep tests
            self.early_exits = []  # function-scope exits (return/raise)
            self.loop_exits = []   # one list per enclosing loop

        def tainted(self):
            if self.early_exits:
                return self.early_exits[-1]
            for exits in reversed(self.loop_exits):
                if exits:
                    return exits[-1]
            return None

    def visit(node, scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            inner = Scope()
            body = node.body if not isinstance(node, ast.Lambda) \
                else [node.body]
            for child in body:
                visit(child, inner)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # a loop body: break/continue exits recorded inside it
            # expire when the loop ends. `for i in range(rank())`
            # iterates a rank-dependent number of times — its body is
            # rank-conditional.
            dep = common.expr_is_rank_dependent(node.iter)
            body = list(node.body)
            for child in ast.iter_child_nodes(node):
                if child in body:
                    continue
                visit(child, scope)
            if dep:
                scope.cond_stack.append(node.iter.lineno)
            scope.loop_exits.append([])
            for child in body:
                visit(child, scope)
            scope.loop_exits.pop()
            if dep:
                scope.cond_stack.pop()
            return
        if isinstance(node, (ast.If, ast.While)):
            dep = common.expr_is_rank_dependent(node.test)
            visit(node.test, scope)
            if dep:
                scope.cond_stack.append(node.test.lineno)
            is_loop = isinstance(node, ast.While)
            if is_loop:
                scope.loop_exits.append([])
            for child in node.body + getattr(node, "orelse", []):
                visit(child, scope)
            if is_loop:
                scope.loop_exits.pop()
            if dep:
                scope.cond_stack.pop()
                # a rank-conditional branch that exits: return/raise
                # taint the rest of the function; break/continue only
                # the rest of the enclosing loop body
                if isinstance(node, ast.If):
                    stmts = node.body + node.orelse
                    if _contains_exit(stmts, (ast.Return, ast.Raise)):
                        scope.early_exits.append(node.test.lineno)
                    elif scope.loop_exits and _contains_exit(
                            stmts, (ast.Break, ast.Continue),
                            skip_loops=True):
                        scope.loop_exits[-1].append(node.test.lineno)
            return
        if isinstance(node, ast.IfExp):
            dep = common.expr_is_rank_dependent(node.test)
            visit(node.test, scope)
            if dep:
                scope.cond_stack.append(node.test.lineno)
            visit(node.body, scope)
            visit(node.orelse, scope)
            if dep:
                scope.cond_stack.pop()
            return
        if isinstance(node, ast.BoolOp):
            # `rank == 0 and allreduce(x)`: operands after a rank-dep
            # operand only evaluate on some ranks
            dep_from = None
            for i, v in enumerate(node.values):
                if dep_from is not None:
                    scope.cond_stack.append(v.lineno)
                visit(v, scope)
                if dep_from is not None:
                    scope.cond_stack.pop()
                if dep_from is None and common.expr_is_rank_dependent(v):
                    dep_from = i
            return
        if isinstance(node, ast.Call):
            name = common.is_collective_call(node)
            if name is not None:
                taint = scope.tainted()
                if scope.cond_stack:
                    flag(node, name,
                         "dispatched under rank-dependent control flow "
                         f"(condition at line {scope.cond_stack[-1]})")
                elif taint is not None:
                    flag(node, name,
                         "reachable after a rank-conditional early "
                         f"exit (line {taint}) — some "
                         "ranks never arrive")
        for child in ast.iter_child_nodes(node):
            visit(child, scope)

    top = Scope()
    for stmt in pf.tree.body:
        visit(stmt, top)
    return findings
