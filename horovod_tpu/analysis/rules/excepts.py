"""HVD-EXCEPT: bare / broad exception handlers. On the collective
plane a swallowed exception is worse than a crash: the rank that ate
the error stops dispatching collectives while its peers park in the
next one forever — the desync doctor then names it at 3am. A broad
handler is acceptable only when it (a) re-raises, or (b) carries an
inline justification saying why this plane must never propagate
(telemetry/forensics paths that ride the liveness channel). Bare
``except:`` and ``except BaseException:`` additionally swallow
``KeyboardInterrupt``/``SystemExit`` — control flow, not errors."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_BROAD = frozenset({"Exception", "BaseException"})


def _names_in(type_node):
    if type_node is None:
        return {"<bare>"}
    out = set()
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    for n in nodes:
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _reraises(handler):
    for node in common.walk_skipping_defs(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


@engine.register(
    "HVD-EXCEPT",
    doc="broad exception handler that swallows control flow")
def check(pf):
    findings = []
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        caught = _names_in(node.type)
        broad = caught & _BROAD
        bare = "<bare>" in caught
        if not broad and not bare:
            continue
        if _reraises(node):
            continue
        if bare or "BaseException" in broad:
            what = "bare `except:`" if bare else "`except BaseException`"
            msg = (f"{what} swallows KeyboardInterrupt/SystemExit — "
                   "a rank told to die keeps running (and desyncs)")
        else:
            msg = ("broad `except Exception` without re-raise — a "
                   "swallowed error here turns into a silent desync "
                   "hang on the collective plane")
        findings.append(engine.Finding(
            rule="HVD-EXCEPT", file=pf.rel, line=node.lineno,
            col=node.col_offset + 1, message=msg,
            hint="catch the specific exceptions, re-raise, or suppress "
                 "with a justification naming why this plane must "
                 "never propagate (docs/ANALYSIS.md)",
            fingerprint=common.fingerprint(pf, node.lineno)))
    return findings
