"""HVD-HOSTSYNC: host synchronization inside functions that flow into
``jit``/``make_train_step`` — ``.item()``, ``float()``, ``np.asarray``,
``jax.device_get``, blocking I/O on traced values. These either fail at
trace time or (worse) silently force a device→host readback every step,
the pipeline stall the goodput ledger (runtime twin) can only *bill*
after the fact, never prevent."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_JIT_NAMES = frozenset({"jit", "pjit"})
_STEP_BUILDERS = frozenset({"make_train_step", "make_lm_train_step"})

# attribute calls that force a transfer regardless of receiver
_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})
# numpy-ish module receivers whose asarray/array pulls a traced value
_NP_RECEIVERS = frozenset({"np", "numpy", "onp"})
_BLOCKING_NAMES = frozenset({"print", "open", "input"})


def _jit_entry_names(tree):
    """Names of functions that flow into a jit boundary in this module:
    decorated with ``@jit``/``@jax.jit``/``@partial(jax.jit, ...)``, or
    passed by name to ``jit(...)`` / ``make_train_step(...)``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if isinstance(target, ast.Attribute):
                    dn = target.attr
                elif isinstance(target, ast.Name):
                    dn = target.id
                else:
                    continue
                if dn in _JIT_NAMES:
                    names.add(node.name)
                elif dn == "partial" and isinstance(dec, ast.Call):
                    for a in dec.args:
                        an = a.attr if isinstance(a, ast.Attribute) else \
                            getattr(a, "id", None)
                        if an in _JIT_NAMES:
                            names.add(node.name)
        elif isinstance(node, ast.Call):
            cn = common.call_name(node)
            if cn in _JIT_NAMES or cn in _STEP_BUILDERS:
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
    return names


@engine.register(
    "HVD-HOSTSYNC",
    doc="host sync / blocking I/O inside a jit-traced function")
def check(pf):
    entries = _jit_entry_names(pf.tree)
    if not entries:
        return []
    findings = []

    def flag(node, what):
        findings.append(engine.Finding(
            rule="HVD-HOSTSYNC", file=pf.rel, line=node.lineno,
            col=node.col_offset + 1,
            message=f"{what} inside a jit-traced function",
            hint="this forces a device→host sync (or a trace-time "
                 "error) on the hot path — return the value and read "
                 "it outside the step, or use a deferred telemetry "
                 "gauge (runtime twin: the goodput ledger can only "
                 "bill this stall)",
            fingerprint=common.fingerprint(pf, node.lineno)))

    def scan(fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = common.call_name(node)
            recv = common.receiver_ident(node)
            if name in _SYNC_ATTRS and recv is not None:
                flag(node, f"`.{name}()`")
            elif name in ("asarray", "array") and recv in _NP_RECEIVERS:
                flag(node, f"`{recv}.{name}()` on a traced value")
            elif name == "device_get":
                flag(node, "`jax.device_get()`")
            elif name in ("float", "bool") and isinstance(
                    node.func, ast.Name) and node.args and not isinstance(
                    node.args[0], ast.Constant):
                flag(node, f"`{name}()` scalar conversion")
            elif name in _BLOCKING_NAMES and isinstance(node.func,
                                                        ast.Name):
                flag(node, f"blocking `{name}()`")
            elif name == "sleep" and (recv == "time" or isinstance(
                    node.func, ast.Name)):
                flag(node, "`time.sleep()`")

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in entries:
            scan(node)
    return findings
