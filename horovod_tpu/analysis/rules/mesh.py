"""HVD-MESH: explicit ``pmap(``/``shard_map(`` call sites — the former
tests/test_gspmd.py regex ratchet, now an engine pass whose baseline
lives in the committed baseline file. A new explicit per-rank call
site moves work OFF the one logical mesh and out of the partitioner's
reach (docs/PERFORMANCE.md "The GSPMD path"); the pinned legacy sites
ride in the baseline, and the engine's stale-entry ratchet enforces
that a removed site cannot silently come back.

``compat.py`` (the version shim) and ``parallel/gspmd.py`` (the
NamedSharding plan layer) are excluded by design, same as the old
guard. The compiled wire-compression island (ISSUE 17) rides that
exclusion deliberately: the ONLY sanctioned ``shard_map`` entry point
is ``gspmd.shard_map_island`` — a per-shard region embedded INSIDE the
jitted GSPMD step for the chunked quantized exchange — and its raw
``jax.shard_map(`` call lives in ``parallel/gspmd.py``. Call sites in
``training.py`` invoke the helper by name, so they neither trip this
rule nor grow the baseline; a new raw ``shard_map(`` anywhere else
still does."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_EXCLUDED_SUFFIXES = ("horovod_tpu/compat.py",
                      "horovod_tpu/parallel/gspmd.py")
_MESH_CALLS = frozenset({"pmap", "shard_map"})


@engine.register(
    "HVD-MESH",
    doc="explicit pmap/shard_map call site off the logical mesh")
def check(pf):
    rel = pf.rel.replace("\\", "/")
    if rel.endswith(_EXCLUDED_SUFFIXES):
        return []
    findings = []
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call):
            name = common.call_name(node)
            if name in _MESH_CALLS:
                findings.append(engine.Finding(
                    rule="HVD-MESH", file=pf.rel, line=node.lineno,
                    col=node.col_offset + 1,
                    message=f"explicit `{name}(` call site off the "
                            "logical mesh",
                    hint="express the sharding as NamedSharding / "
                         "with_sharding_constraint on the one logical "
                         "mesh (parallel/gspmd.py) — justify any new "
                         "per-rank call site in the PR",
                    fingerprint=common.fingerprint(pf, node.lineno)))
    return findings
