"""HVD-LOCKORDER: the cross-module lock-acquisition graph. Collects
every ``threading.Lock``/``RLock`` definition and every ``with lock:``
held region, then reports (a) locks held across blocking calls —
``Thread.join``, bounded ``queue.put``/``get``, ``Event.wait``,
``time.sleep``, and any collective dispatch — and (b) lock-order
cycles (A taken under B here, B taken under A there). The PR 7
recorder-watcher SIGTERM deadlock (handler re-raising while the watcher
held the dump lock mid-write) is exactly shape (a); this pass is its
static twin.

Limitations (documented in docs/ANALYSIS.md): held regions are ``with``
blocks only (bare ``.acquire()`` spans are not tracked), nested
function bodies are excluded (a closure defined under a lock does not
run there), and ``Condition.wait`` — which releases its lock — is
excluded by receiver-name heuristic."""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_LOCK_CTORS = frozenset({"Lock", "RLock", "Semaphore",
                         "BoundedSemaphore"})


def _modname(rel):
    return rel[:-3].replace("\\", "/").replace("/", ".") \
        if rel.endswith(".py") else rel


def _lock_defs(pf):
    """``{local_ident: global_key}`` for locks visible in this file.
    Idents are ``name`` (module scope) or ``self.attr`` (class scope);
    keys are ``module::name`` / ``module::Class.attr``. Lock-named
    imports (``from a import run_lock``) resolve to the DEFINING
    module's key, so an A→B nesting here and a B→A nesting in another
    importer close a detectable cross-module cycle."""
    defs = {}
    mod = _modname(pf.rel)
    for node in pf.tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                if common.ident_is_lockish(alias.name):
                    defs[alias.asname or alias.name] = \
                        f"{node.module}::{alias.name}"

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
                continue
            if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call):
                ctor = common.call_name(child.value)
                if ctor in _LOCK_CTORS:
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name):
                            defs[tgt.id] = f"{mod}::{tgt.id}"
                        elif isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and cls:
                            defs[f"self.{tgt.attr}"] = \
                                f"{mod}::{cls}.{tgt.attr}"
            visit(child, cls)

    visit(pf.tree, None)
    return defs


def _expr_ident(expr):
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        parts = [expr.attr]
        cur = expr.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            return ".".join(reversed(parts))
    return None


def _lock_key(expr, defs, rel):
    """Resolve a with-context expression to a lock key, or None when it
    is not lock-like. Unknown-but-lock-named objects (imported, passed
    in) get a synthetic per-name key so nesting is still tracked."""
    ident = _expr_ident(expr)
    if ident is None:
        return None, None
    if ident in defs:
        return defs[ident], ident
    short = ident.replace("self.", "", 1)
    if f"self.{short}" in defs:
        return defs[f"self.{short}"], ident
    if common.ident_is_lockish(ident):
        return f"{rel}::~{short}", ident
    return None, None


def _blocking_reason(node):
    """Why this Call blocks while a lock is held, or None."""
    name = common.call_name(node)
    recv = common.receiver_ident(node) or ""
    coll = common.is_collective_call(node)
    if coll:
        return (f"collective dispatch `{coll}()` — a peer that never "
                "arrives parks this rank while it holds the lock")
    core = common.blocking_core_reason(node)
    if core:
        return core
    if name == "wait" and recv and not any(
            t in recv.lower() for t in ("cond", "cv")):
        return f"`{recv}.wait()`"
    if name == "acquire" and common.ident_is_lockish(recv) \
            and not common.kwarg_is_false(node, "blocking", arg_index=0):
        return f"`{recv}.acquire()`"
    return None


@engine.register(
    "HVD-LOCKORDER", scope="project",
    doc="lock-order cycles and locks held across blocking calls")
def check(parsed, root):
    findings = []
    edges = {}  # (outer_key, inner_key) -> (rel, lineno, outer_i, inner_i)

    def flag(pf, node, msg, hint):
        findings.append(engine.Finding(
            rule="HVD-LOCKORDER", file=pf.rel, line=node.lineno,
            col=node.col_offset + 1, message=msg, hint=hint,
            fingerprint=common.fingerprint(pf, node.lineno)))

    def scan_with(pf, defs, node, held):
        """``held`` is the stack of (key, ident) currently held. Items
        of one ``with a, b:`` acquire left-to-right, so each later item
        orders after the earlier ones too — ``held`` grows item by
        item, not per statement."""
        for item in node.items:
            key, ident = _lock_key(item.context_expr, defs, pf.rel)
            if key is not None:
                for okey, oident in held:
                    if okey != key:
                        edges.setdefault((okey, key), (
                            pf.rel, item.context_expr.lineno, oident,
                            ident))
                held = held + [(key, ident)]
        for child in node.body:
            scan_stmt(pf, defs, child, held)

    def scan_stmt(pf, defs, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def's body runs when called, not under this lock
            inner_held = []
            body = node.body if not isinstance(node, ast.Lambda) else []
            for child in body:
                scan_stmt(pf, defs, child, inner_held)
            return
        if isinstance(node, ast.With):
            scan_with(pf, defs, node, held)
            return
        if isinstance(node, ast.Call) and held:
            reason = _blocking_reason(node)
            # `.wait()` on the very object being held is a Condition
            # wait — it RELEASES the lock while parked, so it is not a
            # held-across-blocking hazard
            if reason and common.call_name(node) == "wait" and \
                    common.receiver_ident(node) in \
                    {i for _, i in held}:
                reason = None
            if reason:
                key, ident = held[-1]
                flag(pf, node,
                     f"lock `{ident}` ({key}) held across blocking "
                     f"call {reason}",
                     "a blocked holder wedges every other acquirer — "
                     "move the blocking call outside the critical "
                     "section, or bound it with a timeout and document "
                     "why (runtime twin: the PR 7 recorder-watcher "
                     "SIGTERM deadlock, docs/ANALYSIS.md)")
        for child in ast.iter_child_nodes(node):
            scan_stmt(pf, defs, child, held)

    for pf in parsed.values():
        defs = _lock_defs(pf)
        for stmt in pf.tree.body:
            scan_stmt(pf, defs, stmt, [])

    # lock-order cycles over the cross-module edge set (2-cycles and
    # longer, found by DFS from each node; report each cycle once)
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles = set()

    def dfs(start, node, path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = tuple(sorted(path))
                if cyc in seen_cycles:
                    continue
                seen_cycles.add(cyc)
                steps = []
                for i, a in enumerate(path):
                    b = path[(i + 1) % len(path)]
                    rel, line, oident, iident = edges[(a, b)]
                    steps.append(f"`{oident}`→`{iident}` at {rel}:{line}")
                rel, line, _, _ = edges[(path[0], path[1 % len(path)])]
                pf = parsed[rel]
                findings.append(engine.Finding(
                    rule="HVD-LOCKORDER", file=rel, line=line, col=1,
                    message="lock-order cycle: " + "; ".join(steps),
                    hint="two threads taking these locks in opposite "
                         "orders deadlock — pick one global order "
                         "(docs/ANALYSIS.md)",
                    fingerprint=common.fingerprint(pf, line)))
            elif nxt not in path and len(path) < 6:
                dfs(start, nxt, path + [nxt])

    for node in sorted(graph):
        dfs(node, node, [node])
    return findings
