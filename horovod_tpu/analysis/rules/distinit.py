"""HVD-DISTINIT: ``jax.distributed.initialize`` call sites outside the
one sanctioned entry point, ``cluster/procmesh.ensure_distributed``.

Joining the multi-process runtime is a process-global, once-only act
with hard ordering constraints (before any backend touch, after the
CPU collectives implementation and forced device count are set). A
second call site either races the first for the coordinator or runs
after the backend initialized and dies with an opaque XLA error — and
every such bug reproduces only under a real multi-process launch, the
most expensive place to debug it. ``ensure_distributed`` owns the
idempotence record, the foreign-init adoption path, and the CPU
bring-up ordering; everything else in the tree must go through it.

``compat.py`` rides the usual version-shim exclusion.
"""

import ast

from horovod_tpu.analysis import engine
from horovod_tpu.analysis.rules import common

_SANCTIONED_SUFFIXES = ("horovod_tpu/cluster/procmesh.py",
                        "horovod_tpu/compat.py")


def _is_distributed_initialize(node):
    if common.call_name(node) != "initialize":
        return False
    recv = common.receiver_ident(node) or ""
    return recv == "distributed" or recv.endswith(".distributed")


@engine.register(
    "HVD-DISTINIT",
    doc="jax.distributed.initialize outside cluster.ensure_distributed")
def check(pf):
    rel = pf.rel.replace("\\", "/")
    if rel.endswith(_SANCTIONED_SUFFIXES):
        return []
    findings = []
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call) and _is_distributed_initialize(node):
            findings.append(engine.Finding(
                rule="HVD-DISTINIT", file=pf.rel, line=node.lineno,
                col=node.col_offset + 1,
                message="jax.distributed.initialize outside the "
                        "sanctioned cluster entry point",
                hint="join the multi-process runtime through "
                     "cluster.ensure_distributed() — it owns the "
                     "idempotence record, foreign-init adoption and "
                     "the CPU collectives bring-up ordering",
                fingerprint=common.fingerprint(pf, node.lineno)))
    return findings
