"""hvd-lint: project-native static analysis (docs/ANALYSIS.md).

The runtime diagnosis plane (flight recorder, desync doctor, goodput
ledger) names a desync, a host-sync stall, or a deadlock *after* it has
burned a cluster allocation. This package is the static twin: AST
passes over the tree that reject the same bug classes at review time —
collectives reachable under rank-dependent control flow (HVD-DESYNC ↔
``diag/desync.py``), silent host syncs inside jitted step functions
(HVD-HOSTSYNC ↔ the goodput ledger's ``data_wait``/``overhead`` bills),
lock-order cycles and locks held across blocking calls (HVD-LOCKORDER ↔
the PR 7 recorder-watcher SIGTERM deadlock), unsafe signal handlers
(HVD-SIGSAFE), broad exception handlers on the collective plane
(HVD-EXCEPT), off-mesh ``pmap``/``shard_map`` call sites (HVD-MESH, the
former tests/test_gspmd.py regex ratchet) and metric-name drift
(HVD-METRIC, the former OBSERVABILITY.md↔CATALOGUE pytest guard).

Public surface::

    from horovod_tpu.analysis import run_lint, default_targets
    result = run_lint(paths, baseline_path=...)   # LintResult
    result.clean                                   # tier-1 gate bit
"""

from horovod_tpu.analysis.engine import (  # noqa: F401
    Finding, LintError, LintResult, all_rules, default_targets,
    load_baseline, run_lint, write_baseline,
)
from horovod_tpu.analysis import rules  # noqa: F401  (registers passes)
