"""``horovod_tpu.jax`` — framework-adapter namespace for JAX.

Mirrors the reference's per-framework layout (``horovod/tensorflow``,
``horovod/torch``, ``horovod/mxnet``): everything user-facing for JAX in one
place. Implementation lives in ``horovod_tpu.hvd_jax`` (module named to
avoid confusion with the top-level ``jax`` package in tracebacks).
"""

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, num_devices, mesh, data_axes,
    mpi_threads_supported,
)
from horovod_tpu.ops.collective import (  # noqa: F401
    Sum, Average, Adasum, Min, Max,
    allreduce, allgather, broadcast, reducescatter, alltoall,
    mesh_rank, mesh_size,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.ops.fusion import fused_allreduce  # noqa: F401
from horovod_tpu.hvd_jax import (  # noqa: F401
    DistributedOptimizer, DistributedGradientTransform, HorovodOptimizer,
    distributed_grad, distributed_value_and_grad,
    broadcast_variables, broadcast_parameters, broadcast_optimizer_state,
    allreduce_metrics, join,
)
