"""ctypes binding to the native host core (libhvdcore.so).

Rebuilds the reference's ctypes surface (``horovod/common/basics.py:22``
loading the built extension and calling ``horovod_init``/...;
``horovod/torch/mpi_ops.py`` handle-based async ops) against the
TPU-framework core in ``cxx/``: name-negotiated queue, TCP controller,
ring collectives, Adasum, timeline, stall inspector.

The native core is the **host** data plane (numpy/torch CPU tensors, Join,
barrier, parameter sync). TPU-resident arrays use the compiled XLA path in
``horovod_tpu.ops.collective`` and never touch this module.
"""

import ctypes
import os
import subprocess

import numpy as np

# Request::Type (cxx/include/hvd/message.h)
ALLREDUCE, ALLGATHER, BROADCAST, JOIN, ADASUM, ALLTOALL = 0, 1, 2, 3, 4, 5
REDUCESCATTER, BARRIER = 6, 7
# ReduceOp (cxx/include/hvd/cpu_ops.h)
OP_SUM, OP_AVERAGE, OP_MIN, OP_MAX, OP_ADASUM = 0, 1, 2, 3, 4

_DTYPE_MAP = {
    np.dtype(np.uint8): 0, np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2, np.dtype(np.int16): 3,
    np.dtype(np.int32): 4, np.dtype(np.int64): 5,
    np.dtype(np.float16): 6, np.dtype(np.float32): 7,
    np.dtype(np.float64): 8, np.dtype(np.bool_): 9,
}

_OP_MAP = {"sum": OP_SUM, "average": OP_AVERAGE, "min": OP_MIN,
           "max": OP_MAX, "adasum": OP_ADASUM}

_LIB_PATH = os.path.join(os.path.dirname(__file__), "lib", "libhvdcore.so")
_CXX_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "cxx")

_lib = None


def build(force=False):
    """Build libhvdcore.so from cxx/ (the reference's setup.py build step,
    here a plain make). File-locked: concurrently launched ranks must not
    run make into the same build dir at once."""
    if os.path.exists(_LIB_PATH) and not force:
        return _LIB_PATH
    import fcntl
    lock_path = os.path.join(os.path.dirname(__file__), ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(_LIB_PATH) and not force:  # built while waiting
                return _LIB_PATH
            subprocess.run(["make", "-C", os.path.abspath(_CXX_DIR), "-j"],
                           check=True, capture_output=True)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _LIB_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.hvdc_init.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_int, ctypes.c_char_p]
    lib.hvdc_enqueue.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_double]
    lib.hvdc_enqueue_borrow.argtypes = lib.hvdc_enqueue.argtypes
    lib.hvdc_copy_bytes.restype = ctypes.c_int64
    lib.hvdc_error_message.restype = ctypes.c_char_p
    lib.hvdc_last_error.restype = ctypes.c_char_p
    lib.hvdc_output_size.restype = ctypes.c_int64
    lib.hvdc_copy_output.argtypes = [ctypes.c_int, ctypes.c_void_p]
    lib.hvdc_autotune_state.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.hvdc_control_bytes.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.hvdc_data_bytes.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    _lib = lib
    return lib


def core_available():
    try:
        _load()
        return True
    # hvd-lint: disable=HVD-EXCEPT -- availability probe: any failure means the core is absent
    except Exception:
        return False


def init(rank=0, size=1, coord_host="127.0.0.1", coord_port=0,
         advertise_host="127.0.0.1"):
    """Start the native core (background negotiation loop + TCP planes).
    Reference: InitializeHorovodOnce (operations.cc:584)."""
    lib = _load()
    rv = lib.hvdc_init(rank, size, coord_host.encode(), coord_port,
                       advertise_host.encode())
    if rv != 0:
        raise RuntimeError("native core init failed: " +
                           lib.hvdc_last_error().decode())


def shutdown():
    if _lib is not None and _lib.hvdc_is_initialized():
        _sweep_orphans()  # drain completed fire-and-forget handles
        _lib.hvdc_shutdown()


def is_initialized():
    return _lib is not None and bool(_lib.hvdc_is_initialized())


def rank():
    return _lib.hvdc_rank() if _lib is not None else -1


def size():
    return _lib.hvdc_size() if _lib is not None else -1


# Buffers the core is borrowing, keyed by handle: the registry (not just
# the Handle object) pins each array until the op completes, so a caller
# that fires-and-forgets an inplace op can never leave the background
# loop holding a pointer into freed numpy memory.
_borrowed_refs = {}
# C handles whose Python Handle was garbage-collected before completion:
# their borrow must stay pinned until the background loop is done with
# the pointer, so they are swept (released + unpinned) from _enqueue once
# hvdc_poll reports completion. Keeps fire-and-forget callers leak-free.
_orphaned = set()


def _finalize_completed(h):
    """If handle ``h`` is done, unpin its borrow and release the C
    handle. Returns True when finalized (single home for the completion
    protocol: Handle.__del__ and the orphan sweep both go through it)."""
    if _lib is None or _lib.hvdc_poll(h) == 0:
        return False
    _borrowed_refs.pop(h, None)
    _lib.hvdc_release(h)
    return True


def _sweep_orphans():
    for h in list(_orphaned):
        if _finalize_completed(h):
            _orphaned.discard(h)


class Handle:
    """Async op handle (reference: horovod/torch/handle_manager.h).

    When ``borrowed`` is set the core operated zero-copy on that array's
    buffer: the handle keeps it alive until completion and ``wait``
    returns it directly (the result is already in place)."""

    def __init__(self, h, out_dtype, out_shape_hint=None, borrowed=None):
        self._h = h
        self._dtype = out_dtype
        self._shape_hint = out_shape_hint
        self._borrowed = borrowed  # ref holds caller buffer alive
        if borrowed is not None:
            _borrowed_refs[h] = borrowed
        self._released = False

    def poll(self):
        """True when the op has completed (reference hvd.poll)."""
        done = _lib.hvdc_poll(self._h) != 0
        if done:
            # core dropped the raw pointer: the registry pin can go even
            # if the caller never calls wait() (self._borrowed still
            # keeps the array alive for wait()'s in-place return)
            _borrowed_refs.pop(self._h, None)
        return done

    def __del__(self):
        if getattr(self, "_released", True):
            return
        try:
            if _lib is not None and not _finalize_completed(self._h):
                # still in flight: the background loop may hold our
                # buffer pointer — keep the pin, sweep after completion
                _orphaned.add(self._h)
        # hvd-lint: disable=HVD-EXCEPT -- interpreter shutdown: globals may already be gone
        except Exception:
            pass  # interpreter shutdown: globals may be gone

    def wait(self):
        """Block until done, return the result array (reference
        hvd.synchronize)."""
        if self._released:
            raise RuntimeError("handle already synchronized")
        rv = _lib.hvdc_wait(self._h)
        _borrowed_refs.pop(self._h, None)  # op done: core dropped the ptr
        if rv != 1:
            msg = _lib.hvdc_error_message(self._h).decode()
            _lib.hvdc_release(self._h)
            self._released = True
            raise RuntimeError(msg)
        nbytes = _lib.hvdc_output_size(self._h)
        if self._borrowed is not None and nbytes == 0:
            # in-place op on the borrowed buffer: nothing to copy out
            _lib.hvdc_release(self._h)
            self._released = True
            return self._borrowed
        out = np.empty(nbytes, dtype=np.uint8)
        _lib.hvdc_copy_output(self._h,
                              out.ctypes.data_as(ctypes.c_void_p))
        _lib.hvdc_release(self._h)
        self._released = True
        arr = out.view(self._dtype)
        if self._shape_hint is not None:
            arr = arr.reshape(self._shape_hint)
        return arr


def _enqueue(req_type, name, array, op=OP_SUM, root_rank=-1, prescale=1.0,
             postscale=1.0, out_shape=None, inplace=False):
    lib = _load()
    _sweep_orphans()
    arr = np.ascontiguousarray(array)
    if arr.dtype not in _DTYPE_MAP:
        raise ValueError(f"unsupported dtype {arr.dtype}")
    # zero-copy borrow: the core reads (and for allreduce/broadcast
    # writes) the caller's buffer directly. The in-place promise only
    # holds for a C-contiguous writable array — anything else would
    # silently reduce into a hidden ascontiguousarray copy while the
    # caller keeps reading their stale original, so refuse loudly.
    if inplace and (arr is not array or not arr.flags.writeable):
        raise ValueError(
            "inplace=True requires a C-contiguous writable ndarray "
            "(got a copy or read-only view); drop inplace or pass "
            "np.ascontiguousarray(x) yourself and read the result there")
    # Failure contract for inplace: if the collective fails, the buffer
    # contents are undefined — the single-tensor fast path may leave it
    # partially reduced, the fused path untouched (it scales and reduces
    # in the fusion buffer) — see hvdc_enqueue_borrow in
    # cxx/include/hvd/operations.h.
    borrow = inplace
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    fn = lib.hvdc_enqueue_borrow if borrow else lib.hvdc_enqueue
    h = fn(req_type, name.encode(),
           arr.ctypes.data_as(ctypes.c_void_p), shape,
           arr.ndim, _DTYPE_MAP[arr.dtype], op, root_rank,
           prescale, postscale)
    if h < 0:
        raise RuntimeError(lib.hvdc_last_error().decode())
    return Handle(h, arr.dtype, out_shape, borrowed=arr if borrow else None)


def allreduce_async(array, name, op="average", prescale=1.0, postscale=1.0,
                    inplace=False):
    req = ADASUM if op == "adasum" else ALLREDUCE
    # the caller's array goes straight to _enqueue: its single
    # ascontiguousarray is what the inplace contract checks against
    return _enqueue(req, name, array, _OP_MAP[op],
                    out_shape=np.shape(array), prescale=prescale,
                    postscale=postscale, inplace=inplace)


def allreduce(array, name, op="average", **kw):
    return allreduce_async(array, name, op, **kw).wait()


def allgather_async(array, name):
    arr = np.ascontiguousarray(array)
    out_shape = (-1,) + arr.shape[1:] if arr.ndim > 0 else (-1,)
    return _enqueue(ALLGATHER, name, arr, out_shape=out_shape)


def allgather(array, name):
    return allgather_async(array, name).wait()


def broadcast_async(array, name, root_rank=0, inplace=False):
    return _enqueue(BROADCAST, name, array, root_rank=root_rank,
                    out_shape=np.shape(array), inplace=inplace)


def broadcast(array, name, root_rank=0, **kw):
    return broadcast_async(array, name, root_rank, **kw).wait()


def copy_bytes():
    """Cumulative host-side memcpy bytes the core has performed (enqueue
    copy-in, fusion staging, output copy-out). The zero-copy ``inplace``
    paths keep this flat for large tensors."""
    return int(_load().hvdc_copy_bytes())


def reducescatter_async(array, name, op="sum", prescale=1.0, postscale=1.0):
    """Reduce across ranks, scatter along dim 0: this rank receives rows
    [rank*base + min(rank, rem) ...) of the reduction (remainder rows go
    to the first ranks), matching the compiled path's dim-0 split."""
    arr = np.ascontiguousarray(array)
    d0 = arr.shape[0] if arr.ndim > 0 else 1
    n = _lib.hvdc_size() if _lib is not None and _lib.hvdc_size() > 0 else 1
    base, rem = divmod(d0, n)
    r = _lib.hvdc_rank() if _lib is not None else 0
    rows = base + (1 if r < rem else 0)
    out_shape = (rows,) + arr.shape[1:]
    return _enqueue(REDUCESCATTER, name, arr, _OP_MAP[op],
                    out_shape=out_shape, prescale=prescale,
                    postscale=postscale)


def reducescatter(array, name, op="sum", **kw):
    return reducescatter_async(array, name, op, **kw).wait()


def alltoall_async(array, name):
    arr = np.ascontiguousarray(array)
    return _enqueue(ALLTOALL, name, arr, out_shape=arr.shape)


def alltoall(array, name):
    return alltoall_async(array, name).wait()


def join():
    """Announce data exhaustion; returns the rank that joined LAST once
    every rank has joined (reference EnqueueJoin + hvd.join()'s
    last-joined-rank return, operations.cc:909)."""
    lib = _load()
    h = lib.hvdc_enqueue_join()
    if h < 0:
        raise RuntimeError("join: core not initialized")
    rv = lib.hvdc_wait(h)
    msg = lib.hvdc_error_message(h).decode()
    last = -1
    if rv == 1 and lib.hvdc_output_size(h) == 4:
        out = np.zeros(1, dtype=np.int32)
        lib.hvdc_copy_output(h, out.ctypes.data_as(ctypes.c_void_p))
        last = int(out[0])
    lib.hvdc_release(h)
    if rv != 1:
        raise RuntimeError(f"join failed: {msg}")
    return last


def barrier():
    lib = _load()
    if lib.hvdc_barrier() != 0:
        raise RuntimeError("barrier failed")
    # a barrier proves every previously enqueued op completed: sweep so
    # fire-and-forget callers that never enqueue again don't pin
    # orphaned buffers until process exit
    _sweep_orphans()


def control_bytes():
    """Cumulative control-plane bytes (sent, received) in negotiation
    rounds — the response-cache bitvector protocol shrinks these in
    steady state."""
    lib = _load()
    sent = ctypes.c_int64(0)
    recvd = ctypes.c_int64(0)
    if lib.hvdc_control_bytes(ctypes.byref(sent), ctypes.byref(recvd)) != 0:
        raise RuntimeError("native core is not initialized")
    return sent.value, recvd.value


def data_bytes():
    """Cumulative data-plane payload bytes (intra-host, cross-host) this
    rank has sent, split by the HOROVOD_LOCAL_*/CROSS_* topology —
    hierarchical collectives exist to shrink the cross-host share."""
    lib = _load()
    local = ctypes.c_int64(0)
    cross = ctypes.c_int64(0)
    if lib.hvdc_data_bytes(ctypes.byref(local), ctypes.byref(cross)) != 0:
        raise RuntimeError("native core is not initialized")
    return local.value, cross.value


def autotune_state():
    """Autotuner snapshot: dict with ``enabled``, current
    ``fusion_threshold`` / ``cycle_time_ms`` and the categorical
    ``hierarchical`` / ``cache`` gates, coordinator-side ``samples``
    (-1 on workers) and ``done`` (reference: parameter_manager state)."""
    lib = _load()
    fusion = ctypes.c_int64(0)
    cycle = ctypes.c_double(0.0)
    samples = ctypes.c_int(0)
    done = ctypes.c_int(0)
    hier = ctypes.c_int(0)
    cache = ctypes.c_int(0)
    rv = lib.hvdc_autotune_state(ctypes.byref(fusion), ctypes.byref(cycle),
                                 ctypes.byref(samples), ctypes.byref(done),
                                 ctypes.byref(hier), ctypes.byref(cache))
    if rv < 0:
        raise RuntimeError("native core is not initialized")
    return {"enabled": bool(rv), "fusion_threshold": fusion.value,
            "cycle_time_ms": cycle.value, "samples": samples.value,
            "done": bool(done.value), "hierarchical": bool(hier.value),
            "cache": bool(cache.value)}
