"""Decoder-only Transformer with first-class sequence parallelism.

Not present in the 2019 reference (SURVEY.md §5.7: long-context machinery is
absent there) — built here because long-context is a first-class requirement
of the TPU framework. Design:

* Pre-RMSNorm, rotary position embeddings, GELU MLP — the standard modern
  decoder block, all shapes static and MXU-friendly (bf16 compute).
* ``sequence_axis``: when set (inside shard_map over that mesh axis), the
  sequence dimension is sharded across the axis and attention runs as
  **ring attention** (``horovod_tpu.parallel.ring``): K/V blocks rotate
  around the ring via ``lax.ppermute`` while each shard's Q stays put,
  with online-softmax accumulation — memory per chip stays O(S/n), enabling
  contexts n× longer than a single chip could hold.
* Causal masking composes with the ring: block pairs that are entirely
  in the future are still computed (static shapes) but masked.
* **Incremental decode** (``kv_cache=`` — the serving plane,
  docs/SERVING.md): feed only the new tokens with their absolute
  positions plus per-layer cached K/V; attention runs dense over
  cache ++ new (absolute-position masking makes pad slots exact no-ops)
  and the new tokens' K/V come back for the caller's paged pool
  (``horovod_tpu/serve/kvcache.py``). One parameter tree serves both
  modes — a training checkpoint decodes unchanged.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    dtype: Any = jnp.bfloat16
    causal: bool = True
    # mesh axis the sequence dim is sharded over (ring attention), or None
    sequence_axis: Optional[str] = None
    # fused Pallas flash-attention kernel for the local (non-ring) path
    # (ops/flash_attention.py). Requires the default contiguous positions;
    # falls back to plain XLA attention when shapes don't tile.
    # None (default) = auto: ON when running on TPU with local seq >=
    # 1024 — the measured crossover on v5e with bf16 operands and
    # 512x512 blocks (BENCH_NOTES.md round 5: flash fwd+bwd is ~2.4x
    # dense at seq 2048, ~4x at 1024; a wash at 512). OFF elsewhere
    # (interpret mode would crawl). Set True/False to force.
    flash_attention: Optional[bool] = None
    # Sparse-FFN blocks: every `moe_every`-th block (1-based; 0 = dense
    # everywhere) replaces its MLP with a top-k MoE of `num_experts`
    # experts (models/moe.py). `expert_mesh` activates the
    # expert-parallel sharding constraints over its `expert_axis` axis.
    moe_every: int = 0
    num_experts: int = 8
    # routing fanout: 1 = Switch, 2 = GShard top-2 (models/moe.py);
    # raise moe_capacity_factor with it (top-k needs ~k slots/token)
    moe_top_k: int = 1
    moe_capacity_factor: float = 2.0
    expert_mesh: Any = None
    expert_axis: str = "expert"
    # GShard grouped dispatch: tokens split into `moe_num_groups` groups
    # of (B*S)/G, dispatch memory O(T^2/G); `moe_group_axis` shards the
    # group dim (usually the data axis) so EP composes with DP
    moe_num_groups: int = 1
    moe_group_axis: Optional[str] = None


def _rotary(x, positions):
    """Apply rotary position embedding. x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1)


def dense_attention(q, k, v, *, causal, q_positions, kv_positions):
    """Single-device attention: softmax(QK^T/sqrt(d)) V with causal mask by
    absolute position (so it composes with sequence-sharded inputs)."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    scores = scores.astype(jnp.float32) / (float(d) ** 0.5)
    if causal:
        mask = q_positions[:, None, :, None] >= kv_positions[:, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, contiguous_positions=False,
                 cache=None):
        cfg = self.cfg
        h, d = cfg.num_heads, cfg.d_model // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (h, d), axis=-1, dtype=cfg.dtype, use_bias=False, name=name)
        q = _rotary(dense("query")(x), positions)
        k = _rotary(dense("key")(x), positions)
        v = dense("value")(x)
        if cache is not None:
            # incremental decode: attend over cached context ++ the new
            # tokens, and hand the new tokens' (post-rotary) K/V back to
            # the caller to write into its pool (serve/kvcache.py). Pad
            # context slots carry a sentinel position larger than any
            # real one, so the absolute-position causal mask hides them;
            # masked scores are exactly -inf -> exactly-zero probs, so
            # padding never perturbs the visible tokens' output. Always
            # the dense path: decode q_len (1, or one prefill chunk)
            # sits below the flash kernel's MXU block floor
            # (ops/flash_attention.kernel_supported routes it out too).
            ck, cv, ctx_positions = cache
            k_all = jnp.concatenate([ck.astype(k.dtype), k], axis=1)
            v_all = jnp.concatenate([cv.astype(v.dtype), v], axis=1)
            kv_pos = jnp.concatenate([ctx_positions, positions], axis=1)
            out = dense_attention(q, k_all, v_all, causal=cfg.causal,
                                  q_positions=positions,
                                  kv_positions=kv_pos)
            out = nn.DenseGeneral(cfg.d_model, axis=(-2, -1),
                                  dtype=cfg.dtype, use_bias=False,
                                  name="out")(out)
            return out, (k, v)
        use_flash = cfg.flash_attention
        if use_flash is None:
            # auto: TPU only, and only past the measured seq crossover
            # (see TransformerConfig.flash_attention)
            use_flash = (jax.devices()[0].platform == "tpu"
                         and x.shape[1] >= 1024)
        if cfg.sequence_axis is not None:
            from horovod_tpu.parallel import ring
            if use_flash and contiguous_positions:
                # Pallas kernel per rotated K/V block, lse-merged
                out = ring.ring_attention(
                    q, k, v, axis_name=cfg.sequence_axis,
                    causal=cfg.causal, use_flash=True)
            else:
                out = ring.ring_attention(
                    q, k, v, axis_name=cfg.sequence_axis,
                    causal=cfg.causal, q_positions=positions,
                    kv_positions=positions)
        elif use_flash and contiguous_positions:
            # the kernel masks by offset-contiguous positions; arbitrary
            # user-supplied position arrays must use the dense path
            from horovod_tpu.ops import flash_attention as fa
            out = fa.attention(q, k, v, causal=cfg.causal)
        else:
            out = dense_attention(q, k, v, causal=cfg.causal,
                                  q_positions=positions,
                                  kv_positions=positions)
        return nn.DenseGeneral(cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
                               use_bias=False, name="out")(out)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions, contiguous_positions=False,
                 cache=None):
        cfg = self.cfg
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        new_kv = None
        if cache is not None:
            attn_out, new_kv = Attention(cfg, name="attn")(
                y, positions, contiguous_positions, cache)
            x = x + attn_out
        else:
            x = x + Attention(cfg, name="attn")(y, positions,
                                                contiguous_positions)
        y = nn.RMSNorm(dtype=cfg.dtype)(x)
        if self.use_moe:
            from horovod_tpu.models.moe import MoE
            b, s, d = y.shape
            y = MoE(num_experts=cfg.num_experts, d_model=d,
                    d_ff=cfg.d_ff, dtype=cfg.dtype, mesh=cfg.expert_mesh,
                    expert_axis=cfg.expert_axis,
                    num_groups=cfg.moe_num_groups,
                    group_axis=cfg.moe_group_axis, top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    name="moe")(y.reshape(b * s, d)).reshape(b, s, d)
        else:
            y = nn.Dense(cfg.d_ff, dtype=cfg.dtype, use_bias=False)(y)
            y = nn.gelu(y)
            y = nn.Dense(cfg.d_model, dtype=cfg.dtype, use_bias=False)(y)
        if cache is not None:
            return x + y, new_kv
        return x + y


class Transformer(nn.Module):
    """tokens [B, S_local] -> logits [B, S_local, vocab].

    With ``cfg.sequence_axis`` set, S_local = S_global / axis_size and
    ``positions`` must carry each shard's absolute positions (the training
    utilities compute them from the shard index).
    """
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = True,
                 kv_cache=None):
        del train  # no dropout in this family: decode needs no RNG
        cfg = self.cfg
        if kv_cache is not None:
            # incremental decode (docs/SERVING.md): ``kv_cache`` is
            # ``(ctx_k, ctx_v, ctx_positions)`` with per-layer context
            # K/V stacked ``[L, B, S_ctx, H, D]`` and ``ctx_positions``
            # ``[B, S_ctx]`` int32 absolute positions (pad slots carry a
            # sentinel past every real position). ``positions`` must be
            # the fed tokens' absolute positions. Returns
            # ``(logits, (new_k, new_v))`` — the fed tokens' K/V,
            # ``[L, B, S_q, H, D]``, for the caller's cache writes. The
            # same parameter tree drives both modes, so a training
            # checkpoint serves unchanged.
            if cfg.sequence_axis is not None:
                raise ValueError(
                    "incremental decode composes with a paged cache, not "
                    "ring attention — build the serving model with "
                    "sequence_axis=None")
            if not cfg.causal:
                raise ValueError("incremental decode requires causal "
                                 "attention (cfg.causal=True)")
            if positions is None:
                raise ValueError(
                    "incremental decode needs explicit absolute "
                    "positions for the fed tokens")
            ctx_k, ctx_v, ctx_positions = kv_cache
            x = nn.Embed(cfg.vocab_size, cfg.d_model,
                         dtype=cfg.dtype, name="embed")(tokens)
            new_ks, new_vs = [], []
            for i in range(cfg.num_layers):
                use_moe = (cfg.moe_every > 0
                           and (i + 1) % cfg.moe_every == 0)
                x, (nk, nv) = Block(cfg, use_moe=use_moe,
                                    name=f"block_{i}")(
                    x, positions, False,
                    (ctx_k[i], ctx_v[i], ctx_positions))
                new_ks.append(nk)
                new_vs.append(nv)
            x = nn.RMSNorm(dtype=cfg.dtype)(x)
            logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                              use_bias=False, name="lm_head")(x)
            return (logits.astype(jnp.float32),
                    (jnp.stack(new_ks), jnp.stack(new_vs)))
        contiguous = positions is None  # auto positions are 0..S-1
        if positions is None:
            from horovod_tpu.parallel.ring import default_positions
            positions = default_positions(cfg.sequence_axis,
                                          tokens.shape[0], tokens.shape[1])
        x = nn.Embed(cfg.vocab_size, cfg.d_model,
                     dtype=cfg.dtype, name="embed")(tokens)
        for i in range(cfg.num_layers):
            use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            x = Block(cfg, use_moe=use_moe,
                      name=f"block_{i}")(x, positions, contiguous)
        x = nn.RMSNorm(dtype=cfg.dtype)(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, use_bias=False,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)
