"""Mixture-of-Experts layer with expert parallelism (GShard-style).

Beyond-parity (SURVEY §2.7 marks EP absent from the 2019 reference) —
built the TPU-native way, consistent with ``parallel/tensor.py``: the
layer is ONE dense program over global token/expert dims, expert weights
carry ``P('expert', ...)`` shardings, and sharding constraints on the
dispatched activations make XLA/GSPMD place the token all-to-alls —
no hand-written collectives.

Routing is switch-style top-1 with a static per-expert capacity C
(compiler-friendly: every shape static, drops overflow tokens instead of
dynamic shapes). The dispatch math is the standard one-hot/cumsum
construction:

* ``probs [T, E]``      gate softmax
* ``pos [T, E]``        each token's 1-based position in its expert queue
* ``disp [T, E, C]``    one-hot dispatch (token t -> slot (e, c))
* ``expert_in [E,C,d]`` tokens gathered per expert (XLA: all_to_all)
* expert FFN, then the transposed einsum routes results back, weighted
  by the gate prob (second all_to_all).

Because capacity/cumsum are computed over the GLOBAL token dim, the math
is identical on any mesh — a 1-device run is the oracle for the
expert-parallel run, which the tests assert.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P


class MoE(nn.Module):
    """Top-1 MoE FFN: ``[T, d_model] -> [T, d_model]``.

    ``capacity_factor`` scales per-expert capacity
    ``C = ceil(T / num_experts * capacity_factor)``; tokens routed past
    an expert's capacity pass through with a zero FFN contribution (the
    residual connection around the layer keeps them alive).
    """
    num_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    # mesh with an expert axis (named by ``expert_axis``): activates the
    # sharding constraints that make GSPMD place the all-to-alls;
    # None = single-device math
    mesh: Any = None
    expert_axis: str = "expert"

    def _constrain(self, v, spec):
        if self.mesh is None:
            return v
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(self.mesh, spec))

    @nn.compact
    def __call__(self, x):
        E, d, f = self.num_experts, self.d_model, self.d_ff
        T = x.shape[0]
        C = max(1, int(-(-T * self.capacity_factor // E)))  # ceil

        gate = self.param("gate", nn.initializers.lecun_normal(), (d, E),
                          self.dtype)
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (E, d, f), self.dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (E, f, d), self.dtype)

        probs = jax.nn.softmax((x @ gate).astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(probs, axis=-1)                       # [T]
        onehot = jax.nn.one_hot(top1, E, dtype=jnp.float32)     # [T, E]
        top_prob = jnp.sum(probs * onehot, axis=-1)             # [T]

        # 1-based queue position of each token within its expert; tokens
        # past capacity drop out of the dispatch (static shapes)
        pos = jnp.cumsum(onehot, axis=0) * onehot               # [T, E]
        keep = (pos > 0) & (pos <= C)
        disp = jax.nn.one_hot(
            (pos - 1.0).astype(jnp.int32), C,
            dtype=x.dtype) * keep.astype(x.dtype)[..., None]    # [T, E, C]

        # gather tokens per expert — GSPMD turns this einsum's output
        # resharding into the forward all-to-all
        expert_in = jnp.einsum("tec,td->ecd", disp, x)
        expert_in = self._constrain(expert_in,
                                    P(self.expert_axis, None, None))
        h = nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
        out_e = jnp.einsum("ecf,efd->ecd", h, w_out)
        out_e = self._constrain(out_e, P(self.expert_axis, None, None))

        # route back, weighted by the gate prob (second all-to-all)
        combine = disp * top_prob.astype(x.dtype)[:, None, None]
        return jnp.einsum("tec,ecd->td", combine, out_e)


def expert_major_spec(param_path, expert_axis):
    """The ONE copy of the expert-weight sharding rule (used here and by
    ``parallel.tensor.transformer_param_specs`` for embedded MoE blocks):
    returns the spec for an expert-major weight, or None for anything
    else (gate, norms, ...)."""
    if param_path.endswith("w_in") or param_path.endswith("w_out"):
        return P(expert_axis, None, None)
    return None


def moe_param_specs(params, expert_axis="expert"):
    """PartitionSpecs for ``MoE`` params: expert-major weights sharded
    over ``expert_axis``, gate replicated."""
    def spec_for(path, leaf):
        names = "/".join(getattr(k, "key", str(k)) for k in path)
        spec = expert_major_spec(names, expert_axis)
        return spec if spec is not None else P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_moe_params(params, mesh, expert_axis="expert"):
    """Place MoE params on the mesh by the rule shardings."""
    specs = moe_param_specs(params, expert_axis)
    return jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P)))
