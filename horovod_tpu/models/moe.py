"""Mixture-of-Experts layer with expert parallelism (GShard-style).

Beyond-parity (SURVEY §2.7 marks EP absent from the 2019 reference) —
built the TPU-native way, consistent with ``parallel/tensor.py``: the
layer is ONE dense program over global token/expert dims, expert weights
carry ``P('expert', ...)`` shardings, and sharding constraints on the
dispatched activations make XLA/GSPMD place the token all-to-alls —
no hand-written collectives.

Routing is top-k with a static per-expert capacity C
(compiler-friendly: every shape static, drops overflow tokens instead
of dynamic shapes): ``top_k=1`` is Switch (combine weight = raw gate
prob), ``top_k>=2`` is GShard (weights renormalized over the chosen
experts; k-th choices queue behind all earlier choices for capacity —
the GShard yield rule). Tokens are dispatched in ``num_groups``
independent groups (GShard's grouping): the dispatch tensor is
``[G, T/G, E, C]`` with ``C = ceil(T/G / E * capacity_factor)``, so
dispatch memory is O(T²·cf/G) instead of O(T²·cf) — at LM scale
(T = batch×seq ≈ 32k) the un-grouped construction is a memory wall.
Per group and per choice k:

* ``probs [g, t, E]``      gate softmax
* ``pos [g, t, E]``        token's 1-based position in its expert queue
* ``disp [g, t, E, C]``    one-hot dispatch (token t -> slot (e, c)),
  summed over choices
* ``expert_in [g,E,C,d]``  tokens gathered per expert (XLA: all_to_all)
* expert FFN, then the transposed einsum routes results back through
  the gate-weighted combine tensor (second all-to-all).

Capacity (and the cumsum) is per-group, so the math depends only on
``(num_groups, capacity_factor)`` — never on the mesh. A 1-device run
with the same ``num_groups`` is the oracle for the expert-parallel run,
which the tests assert.

Training recipe (Switch Transformer): top-1 routing collapses onto few
experts without the load-balancing auxiliary loss, so ``__call__`` sows
two fp32 scalars into the ``"losses"`` collection:

* ``load_balance``: ``E · Σ_e f_e·P_e`` (fraction of tokens argmax-routed
  to expert e × mean router prob for e; minimized at uniform routing),
* ``router_z``: ``mean(logsumexp(logits)²)`` (keeps gate logits small).

Run ``apply(..., mutable=["losses"])`` and add ``aux_loss(mutated)`` to
the task loss (``parallel.tensor.make_tp_lm_train_step`` does this for
``TransformerConfig.moe_every`` models). Callers that ignore the
collection get the plain output — sow is a no-op then.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

_GROUP_FALLBACKS = set()  # (T, num_groups) pairs already logged


class MoE(nn.Module):
    """Top-k MoE FFN: ``[T, d_model] -> [T, d_model]``.

    ``capacity_factor`` scales per-expert capacity
    ``C = ceil(T/G / num_experts * capacity_factor)``; tokens routed past
    an expert's capacity pass through with a zero FFN contribution (the
    residual connection around the layer keeps them alive).

    ``num_groups`` splits the tokens into (at most) G independent
    dispatch groups — the effective count is the largest divisor of T
    ``<= num_groups``; ``group_axis`` optionally shards the group dim
    over a mesh axis (typically the data axis) so grouped dispatch
    composes with DP.
    """
    num_experts: int
    d_model: int
    d_ff: int
    capacity_factor: float = 2.0
    num_groups: int = 1
    # routing fanout: 1 = Switch (combine weight is the raw gate prob),
    # >=2 = GShard (weights renormalized over the chosen experts;
    # later choices queue behind all earlier-choice tokens for
    # capacity, the GShard yield rule)
    top_k: int = 1
    dtype: Any = jnp.float32
    # mesh with an expert axis (named by ``expert_axis``): activates the
    # sharding constraints that make GSPMD place the all-to-alls;
    # None = single-device math
    mesh: Any = None
    expert_axis: str = "expert"
    group_axis: Optional[str] = None

    def _constrain(self, v, spec):
        if self.mesh is None:
            return v
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(self.mesh, spec))

    @nn.compact
    def __call__(self, x):
        E, d, f = self.num_experts, self.d_model, self.d_ff
        T = x.shape[0]
        # effective group count: the largest divisor of T <= num_groups.
        # num_groups is a memory knob (an upper bound), not a contract —
        # a strict divisibility error would crash init samples whose
        # B*S differs from the training batch (e.g. shard_lm_state's
        # batch-1 sample). Deterministic in (T, num_groups), so the
        # 1-device oracle still matches any mesh run at the same T.
        G = max(1, min(self.num_groups, T))
        while T % G != 0:
            G -= 1
        if G != self.num_groups:
            # effective G changes per-group capacity and therefore which
            # tokens get dropped — the same config routes differently at
            # a different batch*seq. One info line per (T, num_groups)
            # so the numerics shift is never silent.
            key = (T, self.num_groups)
            if key not in _GROUP_FALLBACKS:
                _GROUP_FALLBACKS.add(key)
                import logging
                logging.getLogger("horovod_tpu").info(
                    "MoE grouped dispatch: T=%d not divisible by "
                    "num_groups=%d; using G=%d (affects per-group "
                    "capacity and routing/drop numerics)",
                    T, self.num_groups, G)
        if T > 1024 and 2 * G <= self.num_groups:
            # the divisor fallback quietly reinstated (most of) the
            # O(T^2) dispatch wall — surface it: at real token counts an
            # awkward T (prime, 2*prime, ...) deserves a diagnostic, not
            # a silent compile-time OOM far from this config
            import warnings
            warnings.warn(
                f"MoE grouped dispatch: T={T} has no divisor near "
                f"num_groups={self.num_groups}; using G={G}. Dispatch "
                f"memory scales O(T^2/G) — pad/choose batch*seq so it "
                f"divides by num_groups.", stacklevel=2)
        t = T // G
        C = max(1, int(-(-t * self.capacity_factor // E)))  # ceil

        gate = self.param("gate", nn.initializers.lecun_normal(), (d, E),
                          self.dtype)
        w_in = self.param("w_in", nn.initializers.lecun_normal(),
                          (E, d, f), self.dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(),
                           (E, f, d), self.dtype)

        K = self.top_k
        if not 1 <= K <= E:
            raise ValueError(f"top_k={K} must be in [1, {E}]")

        xg = x.reshape(G, t, d)
        logits = (xg @ gate).astype(jnp.float32)                # [G, t, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # k-th choice one-hots by iterated masked argmax (K is static)
        remaining = probs
        ohs, raw_w = [], []
        for _ in range(K):
            choice = jnp.argmax(remaining, axis=-1)             # [G, t]
            oh = jax.nn.one_hot(choice, E, dtype=jnp.float32)   # [G, t, E]
            ohs.append(oh)
            raw_w.append(jnp.sum(probs * oh, axis=-1))          # [G, t]
            remaining = remaining * (1.0 - oh)

        # aux terms, fp32 over ALL tokens pre-capacity: f_e = fraction
        # with e as FIRST choice (Switch/GShard), P_e = mean router prob
        frac = ohs[0].mean(axis=(0, 1))                         # [E]
        mean_prob = probs.mean(axis=(0, 1))                     # [E]
        self.sow("losses", "load_balance", E * jnp.sum(frac * mean_prob))
        self.sow("losses", "router_z",
                 jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2))

        # combine weights: Switch (K=1) keeps the raw gate prob; GShard
        # (K>=2) renormalizes over the chosen experts
        if K == 1:
            weights = raw_w
        else:
            denom = jnp.maximum(sum(raw_w), 1e-9)
            weights = [w / denom for w in raw_w]

        # per-expert queue positions: k-th choices count AFTER every
        # earlier choice's tokens (GShard yield rule); past-capacity
        # tokens drop out of the dispatch (static shapes)
        base = jnp.zeros((G, 1, E), jnp.float32)
        disp = jnp.zeros((G, t, E, C), x.dtype)
        combine = jnp.zeros((G, t, E, C), x.dtype)
        for oh, w in zip(ohs, weights):
            pos = (jnp.cumsum(oh, axis=1) + base) * oh          # [G, t, E]
            keep = (pos > 0) & (pos <= C)
            d_k = jax.nn.one_hot(
                (pos - 1.0).astype(jnp.int32), C,
                dtype=x.dtype) * keep.astype(x.dtype)[..., None]
            disp = disp + d_k
            combine = combine + d_k * w.astype(x.dtype)[..., None, None]
            base = base + jnp.sum(oh, axis=1, keepdims=True)

        # gather tokens per expert — GSPMD turns this einsum's output
        # resharding into the forward all-to-all
        expert_in = jnp.einsum("gtec,gtd->gecd", disp, xg)      # [G,E,C,d]
        espec = P(self.group_axis, self.expert_axis, None, None)
        expert_in = self._constrain(expert_in, espec)
        h = nn.gelu(jnp.einsum("gecd,edf->gecf", expert_in, w_in))
        out_e = jnp.einsum("gecf,efd->gecd", h, w_out)
        out_e = self._constrain(out_e, espec)

        # route back, gate-weighted (second all-to-all)
        out = jnp.einsum("gtec,gecd->gtd", combine, out_e)
        return out.reshape(T, d)


def aux_loss(mutated, load_balance_weight=0.01, router_z_weight=1e-3):
    """Scalar auxiliary loss from the collections mutated by ``apply``.

    Accepts either the full mutated-variables dict or its ``"losses"``
    entry; sums every sown ``load_balance`` / ``router_z`` scalar (one
    pair per MoE block) with the Switch-paper default weights. Returns
    fp32 zero when nothing was sown (dense model), so callers can add it
    unconditionally.
    """
    losses = mutated.get("losses", mutated) if hasattr(mutated, "get") \
        else mutated
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_leaves_with_path(losses):
        keys = "/".join(str(getattr(k, "key", k)) for k in path)
        if "load_balance" in keys:
            total = total + load_balance_weight * leaf
        elif "router_z" in keys:
            total = total + router_z_weight * leaf
    return total


def expert_major_spec(param_path, expert_axis):
    """The ONE copy of the expert-weight sharding rule (used here and by
    ``parallel.tensor.transformer_param_specs`` for embedded MoE blocks):
    returns the spec for an expert-major weight, or None for anything
    else (gate, norms, ...)."""
    if param_path.endswith("w_in") or param_path.endswith("w_out"):
        return P(expert_axis, None, None)
    return None


def moe_param_specs(params, expert_axis="expert"):
    """PartitionSpecs for ``MoE`` params: expert-major weights sharded
    over ``expert_axis``, gate replicated."""
    def spec_for(path, leaf):
        names = "/".join(getattr(k, "key", str(k)) for k in path)
        spec = expert_major_spec(names, expert_axis)
        return spec if spec is not None else P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_moe_params(params, mesh, expert_axis="expert"):
    """Place MoE params on the mesh by the rule shardings."""
    specs = moe_param_specs(params, expert_axis)
    return jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P)))
