"""Model zoo for benchmarks and examples.

The reference ships no model code of its own — its benchmark models come
from ``tf_cnn_benchmarks`` / torchvision (ResNet-50/101, VGG-16,
Inception V3 — ``docs/benchmarks.rst:16-83``, ``/root/reference/examples/
pytorch_synthetic_benchmark.py:24`` pulls ``models.resnet50``) and its
example nets are small MNIST CNNs (``examples/pytorch_mnist.py:44-60``).
This package provides TPU-first flax equivalents of that model surface so
the framework is benchmarkable and usable standalone:

* ``resnet``      — ResNet v1.5 family (18/34/50/101/152), the headline
  benchmark model (``BASELINE.md``).
* ``vgg``         — VGG-16, the bandwidth-bound scaling stress test.
* ``simple``      — MNIST-scale ConvNet/MLP for the example suite.
* ``transformer`` — decoder-only Transformer with sequence-parallel (ring
  attention) support; not in the 2019 reference, first-class here.

All models are NHWC, bf16-compute/fp32-param by default — the layout the
MXU wants.
"""

from horovod_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from horovod_tpu.models.simple import MNISTConvNet, MLP
from horovod_tpu.models.vgg import VGG16
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.models.moe import MoE

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
    "MNISTConvNet", "MLP", "VGG16", "Transformer", "TransformerConfig",
    "MoE",
]
