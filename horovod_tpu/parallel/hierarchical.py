"""Two-level hierarchical allreduce: ICI within a slice, DCN across slices.

Reference: ``NCCLHierarchicalAllreduce`` (``nccl_operations.cc:150-346``) —
intra-node ncclReduceScatter → cross-node MPI_Allreduce → intra-node
ncclAllGather, with a remainder handled separately and fusion-buffer
divisibility constraints (``controller.cc:348-366``).

TPU-native version: the same reduce-scatter / allreduce / all-gather
algebra expressed over mesh axes, but padding replaces the remainder path
(static shapes; XLA requires equal shards) and there are no D2H/H2D hops —
the DCN transfer is a compiled collective on device-resident data.
"""

import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS


def hierarchical_reducescatter(x, ici_axes=(DATA_AXIS,), dcn_axis=DCN_AXIS,
                               op="sum"):
    """Reduce-scatter composed ICI-first: scatter over the torus links,
    then scatter the already-1/ici_size shard over DCN — cross-slice
    traffic shrinks by ici_size, the same economics as
    :func:`hierarchical_allreduce` but keeping the shard (the ZeRO-1 /
    bucket-pipeline building block). Dim 0 must divide by the total
    participant count (callers pad — ``ops.fusion.bucket_schedule``).

    Chunk ownership is linearized ``(*ici_axes, dcn_axis)``-major, i.e.
    ``collective.mesh_rank((*ici_axes, dcn_axis))`` — and
    :func:`hierarchical_allgather` inverts it exactly."""
    if op not in ("sum", "average"):
        raise ValueError(
            f"hierarchical_reducescatter supports sum/average, got {op!r}")
    if isinstance(ici_axes, str):
        ici_axes = (ici_axes,)
    out = x
    total = lax.axis_size(dcn_axis)
    for a in ici_axes:
        total *= lax.axis_size(a)
        out = lax.psum_scatter(out, a, scatter_dimension=0, tiled=True)
    out = lax.psum_scatter(out, dcn_axis, scatter_dimension=0, tiled=True)
    if op == "average":
        out = out / total
    return out


def hierarchical_allgather(x, ici_axes=(DATA_AXIS,), dcn_axis=DCN_AXIS):
    """Inverse of :func:`hierarchical_reducescatter`: gather over DCN
    first (undoing the last scatter), then over the ICI axes in reverse."""
    if isinstance(ici_axes, str):
        ici_axes = (ici_axes,)
    out = lax.all_gather(x, dcn_axis, axis=0, tiled=True)
    for a in reversed(ici_axes):
        out = lax.all_gather(out, a, axis=0, tiled=True)
    return out


def hierarchical_allreduce(x, ici_axes=(DATA_AXIS,), dcn_axis=DCN_AXIS,
                           op="average"):
    """Allreduce ``x`` over ``ici_axes + (dcn_axis,)`` in three stages:

    1. reduce-scatter over the ICI axes (bandwidth-optimal on the torus),
    2. allreduce of the 1/ici_size shard over DCN (cross-slice traffic is
       reduced by a factor of ici_size — the whole point of the hierarchy,
       same as the reference's per-local-rank parallel MPI_Allreduce),
    3. all-gather over the ICI axes.
    """
    if op not in ("sum", "average"):
        # Adasum has its own composite (ops.adasum.
        # hierarchical_adasum_allreduce); min/max don't reduce-scatter
        raise ValueError(
            f"hierarchical_allreduce supports sum/average, got {op!r}")
    if isinstance(ici_axes, str):
        ici_axes = (ici_axes,)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    ici_size = 1
    for a in ici_axes:
        ici_size *= lax.axis_size(a)
    pad = (-n) % ici_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = flat
    for a in ici_axes:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, dcn_axis)
    out = shard
    for a in reversed(ici_axes):
        out = lax.all_gather(out, a, axis=0, tiled=True)
    out = out[:n].reshape(shape)
    if op == "average":
        total = ici_size * lax.axis_size(dcn_axis)
        out = out / total
    return out
