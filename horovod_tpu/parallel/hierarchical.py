"""Two-level hierarchical allreduce: ICI within a slice, DCN across slices.

Reference: ``NCCLHierarchicalAllreduce`` (``nccl_operations.cc:150-346``) —
intra-node ncclReduceScatter → cross-node MPI_Allreduce → intra-node
ncclAllGather, with a remainder handled separately and fusion-buffer
divisibility constraints (``controller.cc:348-366``).

TPU-native version: the same reduce-scatter / allreduce / all-gather
algebra expressed over mesh axes, but padding replaces the remainder path
(static shapes; XLA requires equal shards) and there are no D2H/H2D hops —
the DCN transfer is a compiled collective on device-resident data.
"""

import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS


def hierarchical_allreduce(x, ici_axes=(DATA_AXIS,), dcn_axis=DCN_AXIS,
                           op="average"):
    """Allreduce ``x`` over ``ici_axes + (dcn_axis,)`` in three stages:

    1. reduce-scatter over the ICI axes (bandwidth-optimal on the torus),
    2. allreduce of the 1/ici_size shard over DCN (cross-slice traffic is
       reduced by a factor of ici_size — the whole point of the hierarchy,
       same as the reference's per-local-rank parallel MPI_Allreduce),
    3. all-gather over the ICI axes.
    """
    if op not in ("sum", "average"):
        # Adasum has its own composite (ops.adasum.
        # hierarchical_adasum_allreduce); min/max don't reduce-scatter
        raise ValueError(
            f"hierarchical_allreduce supports sum/average, got {op!r}")
    if isinstance(ici_axes, str):
        ici_axes = (ici_axes,)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    ici_size = 1
    for a in ici_axes:
        ici_size *= lax.axis_size(a)
    pad = (-n) % ici_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = flat
    for a in ici_axes:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, dcn_axis)
    out = shard
    for a in reversed(ici_axes):
        out = lax.all_gather(out, a, axis=0, tiled=True)
    out = out[:n].reshape(shape)
    if op == "average":
        total = ici_size * lax.axis_size(dcn_axis)
        out = out / total
    return out
