"""GSPMD hot path: one logical mesh, NamedSharding-compiled collectives.

The explicit pipeline (``ops/fusion.py`` + ``training.make_train_step``
with ``overlap_grads=True``) hand-dispatches one reduce-scatter per
bucket and one all-gather per bucket, in an order the builder chose.
That mirrors reference Horovod's fusion buffer — which exists only
because the frameworks it wraps cannot schedule collectives themselves
(PAPER.md, layer map). XLA can: annotate the state with
:class:`~jax.sharding.NamedSharding` on ONE logical mesh, ``jax.jit``
the whole step, and the SPMD partitioner inserts, fuses and — with the
latency-hiding scheduler flags ``config.xla_overlap_flags`` already
installs — overlaps every collective the shardings imply. The pattern
scales "from 8-chip v4 to 6000-chip v5p without changing application
code" (SNIPPETS.md [2]/[3]).

This module is the plan layer for that path:

* :class:`GspmdPlan` — derives the logical mesh + axes from
  ``parallel/mesh.py``; batches shard over its data axes, params stay
  replicated, and ZeRO-1 optimizer rows shard over their SCHEDULE's
  scatter axes (``state_partition_specs`` → ``zero.state_specs``) on
  dim 0 of the same ``[world, shard]`` bucket layout the explicit path
  uses — so checkpoints are interchangeable between the two paths, bit
  for bit.
* :func:`apply_shards_spmd` — the ZeRO-1 exchange with **no explicit
  collective calls**: gradients are packed into the schedule's bucket
  rows and constrained to the row sharding (XLA inserts the
  reduce-scatter), the inner optimizer updates only the local rows, and
  the unpacked updates are constrained back to replicated (XLA inserts
  the all-gather).
* :func:`collective_bytes_from_hlo` / :func:`record_compiled_collectives`
  — byte accounting for the compiled path. There are no per-dispatch
  counters to advance (nothing in Python dispatches a collective), so
  the wire volume is read off the compiled HLO module itself and
  recorded under the standard ``hvd_collective_*`` families with
  ``spmd_*`` op labels.

``training.make_train_step(spmd=True)`` is the consumer;
``hvd.DistributedOptimizer`` stays the user-facing veneer
(``hvd_jax.HorovodOptimizer.update_spmd`` routes here). Version gating
lives in ``compat.gspmd_supported`` — jax builds without
``NamedSharding``-aware ``jit`` keep the explicit pipeline.
"""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class GspmdPlan:
    """Static description of the GSPMD hot path's logical mesh: which
    axes batches (and ZeRO rows) shard over, and which axis — if any —
    tensor-parallel layers may shard model weights over. Hashable, so a
    plan can key jit caches and ride as static data."""

    mesh: jax.sharding.Mesh
    data_axes: tuple
    model_axis: str = None

    @property
    def batch_spec(self):
        """Leading (batch) dim sharded over every data axis. ZeRO-1 row
        specs are NOT a plan property: a row's scatter axes belong to
        its ``ZeroState``'s schedule (``zero.state_specs`` /
        ``state_partition_specs`` below — an optimizer built with
        explicit ``axes=`` may scatter over a subset of the mesh), so
        :func:`apply_shards_spmd` derives them from the schedule it is
        handed rather than publishing a plan-level spec that could
        disagree with it."""
        return P(self.data_axes)

    def sharding(self, spec):
        return jax.sharding.NamedSharding(self.mesh, spec)

    def world(self):
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        return int(np.prod([shape[a] for a in self.data_axes]))


def derive_plan(mesh=None, model_axis=None):
    """Build the :class:`GspmdPlan` for ``mesh`` (default: the mesh
    ``horovod_tpu.init()`` installed). Data axes come from
    ``mesh_lib.data_axis_names`` — ``data`` plus ``dcn`` when present —
    exactly the axes the explicit path reduces gradients over, so the
    two paths shard the same state the same way. ``model_axis`` names an
    extra mesh axis for tensor-parallel composition (validated to exist;
    the DP-only step leaves params replicated over it)."""
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    data_axes = mesh_lib.data_axis_names(mesh)
    if not data_axes:
        raise ValueError(
            f"mesh {mesh.axis_names!r} has no data/dcn axis to shard "
            "batches over; build it with parallel.mesh.build_mesh")
    if model_axis is not None and model_axis not in mesh.axis_names:
        raise ValueError(
            f"model_axis {model_axis!r} is not an axis of the mesh "
            f"{mesh.axis_names!r}")
    return GspmdPlan(mesh=mesh, data_axes=tuple(data_axes),
                     model_axis=model_axis)


def state_partition_specs(state):
    """PartitionSpecs for a training-state pytree: everything replicated
    except ``ZeroState`` bucket rows, which shard over their schedule's
    scatter axes (``zero.state_specs``). The ONE spec authority for both
    hot paths — ``training.state_specs`` delegates here, so the explicit
    shard_map step, the GSPMD jit step, placement and checkpointing all
    agree on which leaf lives where."""
    from horovod_tpu.parallel import zero as zero_lib

    def one(node):
        if isinstance(node, zero_lib.ZeroState):
            return zero_lib.state_specs(node)
        return jax.tree_util.tree_map(lambda _: P(), node)

    return jax.tree_util.tree_map(
        one, state, is_leaf=lambda x: isinstance(x, zero_lib.ZeroState))


def _is_spec(x):
    return isinstance(x, P)


def state_shardings(plan, state):
    """``NamedSharding`` tree matching ``state``'s structure — feed
    straight to ``jax.jit(in_shardings=...)`` / ``out_shardings``."""
    return jax.tree_util.tree_map(plan.sharding,
                                  state_partition_specs(state),
                                  is_leaf=_is_spec)


def place_state(plan, state):
    """``device_put`` ``state`` onto its plan shardings (no-op when
    already placed) — the GSPMD analogue of the explicit path's
    ``place_state``, and what a checkpoint restore feeds its
    host-assembled tree through before stepping. Host or process-local
    leaves headed for a multi-process mesh are sliced locally
    (``cluster.procmesh.place``) rather than broadcast through the
    fabric by device_put's cross-process equality assert."""
    def _put(x, s):
        if s.is_fully_addressable:
            return jax.device_put(x, s)
        from horovod_tpu.cluster import procmesh

        return procmesh.place(x, s)

    return jax.tree_util.tree_map(_put, state,
                                  state_shardings(plan, state))


def constrain(x, plan, spec):
    """``with_sharding_constraint`` against the plan's mesh — the only
    way this path ever asks for communication: the constraint states
    where the value must live, XLA decides how it gets there."""
    return jax.lax.with_sharding_constraint(x, plan.sharding(spec))


def shard_map_island(fn, plan, in_specs, out_specs):
    """The SANCTIONED ``shard_map`` entry point of the GSPMD hot path:
    a per-shard region embedded INSIDE the jitted step, over the plan's
    mesh. The chunked quantized exchange (fp8/int8 wires) needs
    per-device partial gradients and per-chunk scales — values no
    sharding annotation can express — so the compressed
    reduce-scatter/all-gather cycle runs as this island while XLA's
    latency-hiding scheduler still owns the schedule of the surrounding
    program (``training._make_spmd_train_step`` is the consumer; the
    compiled module's collectives are accounted by the same HLO parser
    as the annotation-only path). Mesh-ratchet status: this helper lives
    in ``parallel/gspmd.py`` — one of hvd-lint HVD-MESH's excluded shim
    layers — precisely so the island call sites in ``training.py`` go
    through a named, reviewed entry point instead of growing new raw
    ``shard_map(`` sites (``analysis/rules/mesh.py``)."""
    return jax.shard_map(fn, mesh=plan.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def apply_shards_spmd(tx, grads, zstate, params, plan, wire=None,
                      ag_residuals=None):
    """ZeRO-1 under GSPMD: the sharding-annotation replacement for
    ``zero.sharded_update`` — identical ``[world, shard]`` bucket-row
    layout and identical inner-optimizer math, but **zero explicit
    collective calls**:

    1. pack the (logically global-mean) gradient into each bucket's
       padded rows and constrain them to ``schedule.axes`` on dim 0 —
       the partitioner turns the pending gradient reduction plus this
       sharded consumer into a reduce-scatter (or an all-reduce it then
       slices; either way the annotation, not this code, owns the
       choice and the latency-hiding scheduler owns the overlap);
    2. run ``tx.update`` on the row pytree — each device touches only
       its own rows, the ~1/N optimizer compute and state of ZeRO-1;
    3. constrain the updated rows replicated and unpack — the implied
       all-gather of the parameter deltas.

    Returns ``(updates, new_zstate)`` with ``updates`` shaped like
    ``params``. The inner state structure matches the explicit path's
    exactly, so checkpoints restore across paths unchanged.

    ``wire`` (a CAST compressor — bf16/float16) narrows both halves of
    the exchange by dtype-narrowed constraints: gradient rows are cast
    to the wire dtype BEFORE the row constraint (the pending reduction
    plus a sharded consumer at the narrow dtype lets the partitioner
    move the reduce-scatter's bytes at wire width), and the updated
    parameter-delta rows are cast before the replicated constraint (the
    implied all-gather genuinely moves wire-width bytes). Chunked
    quantizers (fp8/int8) are REJECTED here: per-chunk scales have no
    annotation-only form — that exchange is the :func:`shard_map_island`
    that ``training._make_spmd_train_step`` compiles instead.

    ``ag_residuals`` (per-bucket ``[world, shard]`` fp32 arrays, sharded
    over the schedule axes) turns on delta error feedback for the
    all-gather half only: the cast error of each delta row is carried
    into the next step's row before narrowing. The reduce-scatter half
    stays stateless by construction — a carried residual would have to
    be added to the still-unreduced logical gradient, forcing the
    reduction to complete BEFORE the narrowing cast and defeating the
    annotation. With ``ag_residuals`` the return grows to
    ``(updates, new_zstate, new_ag_residuals)``."""
    from horovod_tpu.ops import fusion
    from horovod_tpu.parallel import zero as zero_lib

    if wire is not None and getattr(wire, "chunked", False):
        raise ValueError(
            f"chunked wire format {wire.name!r} has no annotation-only "
            "form (per-chunk scales cannot ride a sharding constraint) "
            "— the quantized exchange is the shard_map island that "
            "training.make_train_step(spmd=True) compiles into the jit "
            "step; this constraint path narrows cast wires only")

    schedule = zstate.plan.schedule
    row_spec = P(tuple(schedule.axes))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    grad_leaves = jax.tree_util.tree_leaves(grads)
    if len(grad_leaves) != len(leaves):
        raise ValueError(
            f"gradient tree has {len(grad_leaves)} leaves, params have "
            f"{len(leaves)}; was the optimizer initialized with a "
            "different parameter tree?")
    grad_rows, param_rows = {}, {}
    for i in range(len(schedule.buckets)):
        rows = zero_lib.bucket_rows(schedule, i, grad_leaves)
        if wire is not None and jnp.issubdtype(rows.dtype, jnp.floating):
            # dtype-narrowed constraint: cast the (still logically
            # unreduced) rows to the wire dtype, then ask for the row
            # sharding — the partitioner owns where the reduce-scatter
            # lands, and the narrow producer lets it move wire-width
            # bytes; decode is the cast back for the fp32 update math
            grad_dtype = rows.dtype
            rows = constrain(rows.astype(wire.wire_dtype), plan,
                             row_spec).astype(grad_dtype)
            grad_rows[f"b{i}"] = rows
        else:
            grad_rows[f"b{i}"] = constrain(rows, plan, row_spec)
        param_rows[f"b{i}"] = constrain(
            zero_lib.bucket_rows(schedule, i, leaves), plan, row_spec)
    update_rows, new_inner = tx.update(grad_rows, zstate.inner, param_rows)

    new_residuals = list(ag_residuals) if ag_residuals is not None else None
    new_leaves = [None] * len(leaves)
    for i in range(len(schedule.buckets)):
        rows = constrain(update_rows[f"b{i}"], plan, row_spec)
        if wire is not None and jnp.issubdtype(rows.dtype, jnp.floating):
            # narrow the delta all-gather: each rank's [world, shard]
            # rows are cast to the wire dtype while still sharded, the
            # replicated constraint gathers the narrow bytes, and every
            # rank decodes the same values — params stay replicated-
            # consistent. Delta-EF compensates the cast error per row.
            out_dtype = rows.dtype
            x = rows
            if new_residuals is not None and new_residuals[i].size:
                x = x.astype(jnp.float32) + new_residuals[i].reshape(
                    x.shape)
                wire_rows = x.astype(wire.wire_dtype)
                new_residuals[i] = constrain(
                    x - wire_rows.astype(jnp.float32), plan, row_spec)
            else:
                wire_rows = x.astype(wire.wire_dtype)
            wire_rows = constrain(wire_rows, plan, row_spec)
            flat = constrain(wire_rows.reshape(-1), plan,
                             P()).astype(out_dtype)
        else:
            flat = constrain(rows.reshape(-1), plan, P())
        for j, arr in fusion.unpack_bucket(schedule, i, flat,
                                           leaves).items():
            new_leaves[j] = arr
    missing = [j for j, leaf in enumerate(new_leaves) if leaf is None]
    if missing:
        raise ValueError(
            f"ZeRO plan does not cover gradient leaves {missing}; was "
            "the optimizer initialized with a different parameter tree?")
    updates = jax.tree_util.tree_unflatten(treedef, new_leaves)
    new_zstate = zero_lib.ZeroState(new_inner, zstate.plan)
    if new_residuals is not None:
        return updates, new_zstate, new_residuals
    return updates, new_zstate


# -- compiled-HLO byte accounting -------------------------------------------

# The collective kinds this framework prices and attributes, in one
# place: the HLO byte parser below, the device-trace X-ray
# (telemetry/xprof.py) and the doctor's bandwidth join all derive their
# matching from this tuple + classifier — one authority, so a kind
# added here is priced AND time-attributed, and the two views can never
# drift on what counts as a collective.
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "all-to-all", "collective-permute")

# kinds as they appear in metric labels / summary JSON (dashes don't
# survive Prometheus label conventions)
def collective_label(op):
    return op.replace("-", "_")


_COLLECTIVE_KIND_RE = re.compile(
    r"^(" + "|".join(re.escape(op) for op in COLLECTIVE_OPS) + r")"
    r"(-start|-done)?(?:[.\-_]|\d|$)")


def collective_kind(name):
    """Classify one HLO instruction/op/trace-event name against
    :data:`COLLECTIVE_OPS`: returns ``(kind, async_edge)`` where
    ``kind`` is the base op (``"all-reduce"``) and ``async_edge`` is
    ``"start"``/``"done"`` for the latency-hiding scheduler's async
    pair halves (``all-reduce-start.1``), else ``None`` — or
    ``(None, None)`` when the name is not a collective. Longest-match
    first, so ``all-reduce-scatter-fusion``-style names cannot
    misclassify (``reduce-scatter`` is matched before a bare prefix
    could lie)."""
    m = _COLLECTIVE_KIND_RE.match(name)
    if not m:
        return None, None
    edge = m.group(2)
    return m.group(1), edge[1:] if edge else None


# `%name = f32[128,256]{1,0} all-reduce(...)` — result dtype/shape, then
# the collective op. Two wrinkles:
#
# * With the latency-hiding scheduler (the exact configuration this
#   path targets on TPU — config.xla_overlap_flags), collectives lower
#   to async `all-reduce-start`/`all-reduce-done` PAIRS instead of the
#   sync form. The `-start` carries the op (counted, attributed to the
#   base op name); the `-done` is the completion handle (skipped — the
#   regexes require `(` right after the optional `-start`, so `-done(`
#   never matches). CPU emits only sync forms, which is why a
#   CPU-only check cannot stand in for this.
# * Variadic/async collectives produce a TUPLE result. For variadic
#   sync ops every tuple element is an output (sum them); an async
#   `-start` tuple is (inputs..., outputs...) — symmetric halves, k
#   aliased inputs then k outputs (the combiner passes fuse many
#   gradient tensors into one variadic collective) — so sum only the
#   OUTPUT half; counting the input aliases too would double the
#   bytes.
_HLO_OP_ALTERNATION = "|".join(re.escape(op) for op in COLLECTIVE_OPS)
_HLO_RESULT_RE = re.compile(
    r"=\s*([a-z][a-z0-9]*)\[([0-9,]*)\][^=]*?"
    r"\b(" + _HLO_OP_ALTERNATION + r")(-start)?\(")
_HLO_TUPLE_RE = re.compile(
    r"=\s*\(.*?\)\s*"
    r"(" + _HLO_OP_ALTERNATION + r")(-start)?\(")
_HLO_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")

_HLO_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _shape_bytes(dtype, dims):
    itemsize = _HLO_ITEMSIZE.get(dtype)
    if itemsize is None:
        return 0
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n * itemsize


def _line_collective_bytes(line):
    """``(op, nbytes)`` when the HLO line is a counted collective
    instruction, else ``None`` — the one parser behind both the per-op
    and the per-axis accounting."""
    m = _HLO_RESULT_RE.search(line)
    if m:
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        return op, _shape_bytes(dtype, dims)
    t = _HLO_TUPLE_RE.search(line)
    if not t:
        return None
    op = t.group(1)
    head = line[:t.end(1)]
    shapes = _HLO_SHAPE_RE.findall(head)
    if t.group(2):
        # async -start: (inputs..., outputs...) — keep the
        # output half. collective-permute-start additionally
        # carries trailing rank-0 unsigned context handles
        # (u32[] tokens): strip those first, or the "half"
        # would land on them and count ~0 payload. An
        # unexpectedly odd tuple degrades to the final element
        # rather than over-counting.
        while (len(shapes) > 2 and shapes[-1][1] == ""
               and shapes[-1][0] in ("u32", "s32", "u64",
                                     "s64")):
            shapes = shapes[:-1]
        half = len(shapes) // 2
        shapes = (shapes[half:] if half and not len(shapes) % 2
                  else shapes[-1:])
    return op, sum(_shape_bytes(d, dims) for d, dims in shapes)


def collective_bytes_from_hlo(hlo_text):
    """Per-op collective byte/call totals of one compiled module, parsed
    from its optimized HLO text: ``{op: {"calls": n, "bytes": b}}``
    where ``bytes`` is the per-device result payload of every
    instruction of that op. This is the compiled path's replacement for
    the explicit pipeline's per-dispatch counters — the module IS the
    schedule, so the module is what gets accounted."""
    out = {}
    for line in hlo_text.splitlines():
        hit = _line_collective_bytes(line)
        if hit is None:
            continue
        op, nbytes = hit
        slot = out.setdefault(op, {"calls": 0, "bytes": 0})
        slot["calls"] += 1
        slot["bytes"] += nbytes
    return out


# Which mesh TIER does each collective ride? The partitioner stamps
# every collective with the participating device groups — explicit
# (`replica_groups={{0,1},{2,3}}`), iota/v2
# (`replica_groups=[2,4]<=[8]` with an optional `T(perm)` transpose),
# or, for collective-permute, `source_target_pairs={{0,4},{4,0}}`.
# Group members are LOGICAL partition ids, i.e. positions in the mesh's
# row-major device grid — so the axes a group varies over are exactly
# the mesh axes (ICI vs DCN tiers) its traffic rides.
_HLO_EXPLICIT_GROUPS_RE = re.compile(
    r"(?:replica_groups|source_target_pairs)=\{(\{[0-9, {}]*\})\}")
_HLO_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")


def _parse_device_groups(line):
    """The collective's participating device-id groups, or ``None``
    when the line carries no group annotation (single-device module)."""
    m = _HLO_EXPLICIT_GROUPS_RE.search(line)
    if m:
        return [[int(x) for x in grp.split(",") if x.strip()]
                for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
    m = _HLO_IOTA_GROUPS_RE.search(line)
    if m:
        import numpy as _np
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = _np.arange(int(_np.prod(dims)))
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.reshape(dims).transpose(perm).reshape(-1)
        return ids.reshape(n_groups, group_size).tolist()
    return None


def group_axes(groups, mesh):
    """The mesh axes a collective's device groups span, in mesh axis
    order — ``("data",)`` for an intra-host/ICI reduction, ``("dcn",)``
    for the cross-process tier, both for a global collective. For
    collective-permute pass the source→target pairs: the axes where
    source and target coordinates differ are the wire the hop rides."""
    shape = mesh.devices.shape
    varies = [False] * len(shape)
    import numpy as _np
    for grp in groups:
        coords = [_np.unravel_index(d, shape) for d in grp]
        for ax in range(len(shape)):
            if len({c[ax] for c in coords}) > 1:
                varies[ax] = True
    return tuple(a for a, v in zip(mesh.axis_names, varies) if v)


def collective_axis_bytes_from_hlo(hlo_text, mesh):
    """Per-mesh-tier collective byte totals of one compiled module:
    ``{axis_label: {"calls", "bytes", "ops": {op: bytes}}}`` where the
    label is ``"+"``-joined mesh axes (``"data"``, ``"dcn"``,
    ``"dcn+data"`` for a global collective) and ``"replica"`` collects
    instructions whose groups never leave one device (or carry no group
    annotation). This is what prices a DCN tier separately from ICI in
    the scaling sweep (bench_scaling.py / SCALING_*.json)."""
    out = {}
    for line in hlo_text.splitlines():
        hit = _line_collective_bytes(line)
        if hit is None:
            continue
        op, nbytes = hit
        groups = _parse_device_groups(line)
        axes = group_axes(groups, mesh) if groups else ()
        label = "+".join(axes) if axes else "replica"
        slot = out.setdefault(label, {"calls": 0, "bytes": 0, "ops": {}})
        slot["calls"] += 1
        slot["bytes"] += nbytes
        slot["ops"][op] = slot["ops"].get(op, 0) + nbytes
    return out


class CompiledProgramCache:
    """Shape-signature-keyed AOT executable cache: ONE
    ``lower().compile()`` per (jitted program, argument shape/dtype
    signature), each compiled module's collectives accounted exactly
    once via :func:`record_compiled_collectives` under ``<prefix>_*``
    labels, and the executable returned for DIRECT calls (on this jax
    an AOT compile does not populate the jit dispatch cache, so
    dispatching through the wrapper after compiling would build the
    identical module twice). The ONE copy of this machinery — the GSPMD
    training scaffold (``training._SpmdProgram``) and the serving
    engine (``serve/engine.py``) both wrap it, so a fix to the key or
    the accounting semantics cannot miss a site."""

    def __init__(self, prefix="spmd", mesh=None):
        self.prefix = prefix
        self.mesh = mesh  # set → per-axis (ICI/DCN tier) attribution too
        self._programs = {}  # sig -> (executable, collectives, by_axis)
        self.last_collectives = None
        self.last_axis_collectives = None

    @staticmethod
    def signature(args):
        import jax.numpy as jnp

        return tuple((tuple(jnp.shape(x)), str(jnp.result_type(x)))
                     for x in jax.tree_util.tree_leaves(args))

    def executable(self, jitted, args):
        key = self.signature(args)
        entry = self._programs.get(key)
        if entry is None:
            compiled = jitted.lower(*args).compile()
            by_axis = None
            try:
                collectives = record_compiled_collectives(
                    compiled, prefix=self.prefix)
                if self.mesh is not None:
                    by_axis = collective_axis_bytes_from_hlo(
                        compiled.as_text(), self.mesh)
            # hvd-lint: disable=HVD-EXCEPT -- HLO accounting must not kill a step
            except Exception:  # pragma: no cover — must not kill a step
                collectives = {}
            entry = (compiled, collectives, by_axis)
            self._programs[key] = entry
        self.last_collectives = entry[1]
        self.last_axis_collectives = entry[2]
        return entry[0]


def record_compiled_collectives(compiled, prefix="spmd"):
    """Account one compiled step's collectives into the standard
    telemetry families (``hvd_collective_{calls,bytes,logical_bytes}
    _total`` under ``<prefix>_<op>`` labels). Analogous to the explicit
    path's trace-time counters: recorded ONCE per compile, describing
    the collectives baked into the program — multiply by step count for
    cumulative volume (docs/OBSERVABILITY.md). Returns the parsed
    ``{op: {calls, bytes}}`` dict ({} when the HLO is unavailable)."""
    from horovod_tpu.telemetry import instruments as _tele

    try:
        text = compiled if isinstance(compiled, str) else compiled.as_text()
    # hvd-lint: disable=HVD-EXCEPT -- HLO text unavailable on this jax; accounting skipped
    except Exception:
        return {}
    ops = collective_bytes_from_hlo(text)
    for op, tot in ops.items():
        _tele.record_compiled_collective(
            f"{prefix}_{op}", calls=tot["calls"], nbytes=tot["bytes"])
    return ops
