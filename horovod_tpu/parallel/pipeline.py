"""Pipeline parallelism over a ``stage`` mesh axis: GPipe and 1F1B.

Beyond-parity (SURVEY §2.7 marks PP absent from the 2019 reference) —
the TPU-native formulation: the layer stack's parameters are STACKED on
a leading dim and sharded over the ``stage`` axis (each stage holds its
contiguous slice of layers), activations flow stage-to-stage with
``ppermute`` inside a compiled ``scan`` over schedule ticks, and every
stage executes the same per-tick program (SPMD lockstep) with
``lax.cond`` skipping the ticks a stage idles — bubbles cost a branch,
not a full layer-stack application.

Two schedules:

* ``pipelined_forward`` — GPipe. One differentiable XLA program:
  reverse-mode AD routes cotangents through the transposed
  ``ppermute``s, so backward pipelining falls out of autodiff. Simple
  and composable (it is just a function of the params), but the scan
  saves residuals for every tick: activation memory grows O(n_micro).
* ``pipeline_train_1f1b`` — 1F1B. Forward AND backward are explicitly
  scheduled in ONE forward-only scan; each stage keeps ring buffers of
  at most ``n_stages`` in-flight microbatch activations and computes
  its backward with a per-microbatch ``jax.vjp`` (recompute-from-saved-
  input, i.e. remat at stage granularity). Activation memory is
  O(n_stages) regardless of ``n_micro`` — the schedule to use when you
  scale microbatches to shrink the bubble fraction.

Both compose with data parallelism (``batch_axis``: each data slice
runs its own pipeline; parameter cotangents are psum'd over the data
axis) and with tensor parallelism (``param_specs``: per-leaf
PartitionSpecs for the non-stacked dims, with ``block_fn`` free to use
collectives over the model axis — the Megatron column/row pattern).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def stack_params(param_trees):
    """Stack per-layer param trees along a new leading dim — the layout
    the pipeline schedules shard over the stage axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def _param_in_specs(stacked_params, axis_name, param_specs):
    """Per-leaf in_specs: stage-sharded leading dim + the caller's TP
    spec for the remaining dims (replicated when param_specs is None)."""
    if param_specs is None:
        return P(axis_name)
    def join(spec):
        return P(axis_name, *tuple(spec))
    return jax.tree_util.tree_map(
        join, param_specs, is_leaf=lambda v: isinstance(v, P))


def _check_shapes(stacked_params, h, mesh, axis_name, n_micro, batch_axis):
    n_stages = mesh.shape[axis_name]
    B = h.shape[0]
    dp = mesh.shape[batch_axis] if batch_axis else 1
    if B % (n_micro * dp):
        raise ValueError(
            f"batch {B} not divisible by n_micro={n_micro} x dp={dp}")
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    return n_stages


def _apply_local(block_fn, params, x):
    # this stage's slice of the layer stack, in order
    return jax.lax.scan(lambda c, p: (block_fn(p, c), None), x, params)[0]


def _vma_of(x):
    """Varying-manifest axes of a traced value (vma type system)."""
    return tuple(getattr(jax.typeof(x), "vma", ()))


def _pcast_to(x, axes):
    """Promote ``x`` to varying over ``axes`` (no-op where already)."""
    missing = tuple(a for a in axes if a not in _vma_of(x))
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def pipelined_forward(block_fn: Callable[[Any, Any], Any], stacked_params,
                      h, *, mesh, axis_name="stage", n_micro=None,
                      batch_axis=None, param_specs=None, remat=False):
    """Run ``h`` through the stacked layers as a GPipe pipeline.

    ``block_fn(layer_params, x) -> x`` applies ONE layer. ``stacked_params``
    has every leaf stacked ``[L, ...]``; L must divide by the stage-axis
    size (each stage scans its local layers in order). ``h`` is the input
    activation ``[B, ...]`` with the per-shard batch divisible by
    ``n_micro`` (default: one microbatch per stage).

    ``batch_axis`` composes PP with DP: ``h``'s leading dim shards over
    that mesh axis and each data slice runs its own pipeline; the stacked
    params are replicated across ``batch_axis``, so their reverse-mode
    cotangents are psum'd over it by the ``shard_map`` transpose — the
    gradient allreduce falls out for free.

    ``param_specs`` composes PP with TP: a tree of ``PartitionSpec``s for
    the per-layer (unstacked) dims — e.g. ``P(None, 'model')`` for a
    column-parallel kernel — and ``block_fn`` may use collectives over
    the model axis (its AD transpose handles the backward collectives).

    CONTRACT (round 4, breaking): the pipeline runs under
    ``check_vma=True``, so a ``block_fn`` using collectives must be
    vma-correct — promote replicated operands with
    ``jax.lax.pcast(x, axis, to='varying')`` before mixing them into a
    ``psum``. Plain (collective-free) blocks need no change. See
    docs/PARALLELISM.md for the canonical TP block.

    Bubble ticks take a ``lax.cond`` fast path (identity) instead of a
    full layer-stack application, so the (n_stages-1) bubble slots cost
    a branch each rather than compute.

    ``remat=True`` wraps each layer application in ``jax.checkpoint``:
    the scan saves only per-layer boundaries and recomputes block
    internals in backward — the knob between GPipe's O(n_micro)
    full-residual memory and 1F1B's O(n_stages) schedule.
    """
    n_stages = mesh.shape[axis_name]
    if n_micro is None:
        n_micro = n_stages
    _check_shapes(stacked_params, h, mesh, axis_name, n_micro, batch_axis)
    if remat:
        block_fn = jax.checkpoint(block_fn)

    def inner(params, h):
        n = jax.lax.axis_size(axis_name)
        s = jax.lax.axis_index(axis_name)
        micro = h.reshape(n_micro, h.shape[0] // n_micro, *h.shape[1:])
        micro = _pcast_to(micro, (axis_name,) +
                          ((batch_axis,) if batch_axis else ()))

        def tick(carry, t):
            state, outs = carry
            x_in = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            cur = jnp.where(s == 0, x_in, state)
            # bubble skip: stage s computes micro t-s; out-of-range ticks
            # pass the activation through untouched (no compute, and no
            # NaN-able math on garbage — norm-blocks stay safe)
            valid = (t - s >= 0) & (t - s < n_micro)
            y = jax.lax.cond(
                valid, lambda p, c: _apply_local(block_fn, p, c),
                lambda p, c: c, params, cur)
            idx = t - (n - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, y, jnp.clip(idx, 0, n_micro - 1), 0)
            take = (s == n - 1) & (idx >= 0) & (idx < n_micro)
            outs = jnp.where(take, upd, outs)
            # hand my output to the next stage (stage 0 receives zeros)
            state = jax.lax.ppermute(
                y, axis_name, [(i, i + 1) for i in range(n - 1)])
            return (state, outs), None

        state0 = micro[0]
        outs0 = jnp.zeros_like(micro)
        (_, outs), _ = jax.lax.scan(
            tick, (state0, outs0), jnp.arange(n_micro + n_stages - 1))
        # replicate the finished microbatches from the last stage
        outs = jax.lax.psum(
            jnp.where(s == n - 1, outs, jnp.zeros_like(outs)), axis_name)
        return outs.reshape(h.shape)

    io_spec = P(batch_axis) if batch_axis else P()
    # check_vma=True: same varying-manifest contract as the 1F1B path,
    # so one (vma-correct) block_fn serves both schedules and the AD
    # transpose of a TP block's pcast/psum lands the right collectives
    return jax.shard_map(inner, mesh=mesh,
                         in_specs=(_param_in_specs(stacked_params,
                                                   axis_name, param_specs),
                                   io_spec),
                         out_specs=io_spec,
                         check_vma=True)(stacked_params, h)


def _schedule_1f1b(n_stages, n_micro):
    """Static 1F1B schedule table, computed in Python at trace time.

    Greedy lockstep simulation (one F or B slot per stage per tick):
    a stage prefers backward once its in-flight count reaches
    ``min(n_micro, n_stages - s)`` — the classic warmup / steady-1F1B /
    cooldown shape. Returns ``(fwd, bwd)`` int arrays ``[T, n_stages]``
    holding the microbatch index each stage processes (-1 = idle), with
    peak in-flight microbatches per stage <= n_stages by construction.
    """
    fdone = [0] * n_stages
    bdone = [0] * n_stages
    f_tick = [[-1] * n_micro for _ in range(n_stages)]
    b_tick = [[-1] * n_micro for _ in range(n_stages)]
    fwd, bwd = [], []
    t = 0
    while bdone[0] < n_micro:
        frow = [-1] * n_stages
        brow = [-1] * n_stages
        for s in range(n_stages):
            m_f, m_b = fdone[s], bdone[s]
            f_ready = m_f < n_micro and (
                s == 0 or (0 <= f_tick[s - 1][m_f] < t))
            if s == n_stages - 1:
                b_ready = m_b < n_micro and 0 <= f_tick[s][m_b] < t
            else:
                b_ready = m_b < n_micro and 0 <= b_tick[s + 1][m_b] < t
            inflight = m_f - m_b
            max_inflight = min(n_micro, n_stages - s)
            # in-flight may NEVER exceed max_inflight: the ring buffers
            # (and the saved-input slots the backward recomputes from)
            # are sized by it — a stage at capacity idles until its next
            # backward is ready rather than clobbering a live slot
            if b_ready and (inflight >= max_inflight or m_f == n_micro):
                brow[s] = m_b
            elif f_ready and inflight < max_inflight:
                frow[s] = m_f
            elif b_ready:
                brow[s] = m_b
        for s in range(n_stages):
            if frow[s] >= 0:
                f_tick[s][frow[s]] = t
                fdone[s] += 1
            if brow[s] >= 0:
                b_tick[s][brow[s]] = t
                bdone[s] += 1
        fwd.append(frow)
        bwd.append(brow)
        t += 1
        if t > 4 * (n_micro + n_stages) + 8:
            raise RuntimeError("1F1B schedule did not converge")
    return np.asarray(fwd, np.int32), np.asarray(bwd, np.int32)


def pipeline_train_1f1b(block_fn: Callable[[Any, Any], Any], stacked_params,
                        h, per_micro_loss: Callable[[Any, Any], Any], *,
                        mesh, axis_name="stage", n_micro=None,
                        batch_axis=None, param_specs=None,
                        with_input_grad=False):
    """One 1F1B training step: ``(loss, stacked_grads)``.

    Unlike ``pipelined_forward`` (differentiate it yourself), this IS
    the forward+backward: the schedule interleaves one forward and one
    backward slot per stage per tick, backward recomputes the stage's
    forward from its saved INPUT via ``jax.vjp`` (stage-granular remat),
    and every buffer is a ring of ``n_stages`` microbatch activations —
    activation memory is O(n_stages), not O(n_micro).

    ``per_micro_loss(y, m) -> scalar`` scores the last stage's output
    for microbatch ``m``; the returned ``loss`` (and the grads) are the
    SUM over microbatches (and over ``batch_axis`` slices) — normalize
    inside ``per_micro_loss`` for a mean. ``stacked_grads`` matches
    ``stacked_params``'s layout and sharding. ``with_input_grad=True``
    appends d(loss)/d(h).

    ``batch_axis`` / ``param_specs`` compose with DP / TP exactly as in
    ``pipelined_forward`` (here the cross-data psum of the grads is
    explicit rather than an AD transpose).
    """
    from horovod_tpu import compat
    composed = ((batch_axis is not None and mesh.shape.get(batch_axis, 1) > 1)
                or param_specs is not None)
    if composed and not compat.NATIVE_VMA:
        # The PP x DP / PP x TP composition's backward relies on the vma
        # pcast<->psum AD transpose pair; on pre-vma jax the compat shims
        # keep only forward semantics, and the gradients would be
        # silently wrong (not an approximation — wrong). Refuse loudly.
        raise NotImplementedError(
            "pipeline_train_1f1b composed with a data/model axis needs "
            "jax's varying-manual-axes (vma) AD semantics; this jax "
            f"({jax.__version__}) predates them. Run the pure-PP form "
            "(no batch_axis/param_specs) or upgrade jax.")
    n_stages = mesh.shape[axis_name]
    if n_micro is None:
        n_micro = n_stages
    _check_shapes(stacked_params, h, mesh, axis_name, n_micro, batch_axis)
    fwd_sched, bwd_sched = _schedule_1f1b(n_stages, n_micro)
    fwd_sched, bwd_sched = jnp.asarray(fwd_sched), jnp.asarray(bwd_sched)

    def inner(params, h):
        S = jax.lax.axis_size(axis_name)
        s = jax.lax.axis_index(axis_name)
        micro = h.reshape(n_micro, h.shape[0] // n_micro, *h.shape[1:])
        # canonical vma for the tick-loop state: varying over the stage
        # (every stage computes different values) and the data slice
        base = (axis_name,) + ((batch_axis,) if batch_axis else ())
        micro = _pcast_to(micro, base)
        ring = lambda: _pcast_to(  # noqa: E731
            jnp.zeros((n_stages,) + micro.shape[1:], micro.dtype), base)
        # grad accumulator: cotangents carry their PRIMAL's manifest
        # (the vma-typed pullback psums over axes the param does not
        # vary on — incl. the data axis — by itself), so the
        # accumulator keeps exactly the params' vma
        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        zero_loss = _pcast_to(jnp.zeros((), jnp.float32), base)

        def tick(carry, t):
            inbox_f, saved_x, inbox_b, grads, dh, loss_acc = carry
            frow = fwd_sched[t]
            brow = bwd_sched[t]
            f_m = frow[s]
            b_m = brow[s]

            # ---- forward slot
            f_mc = jnp.maximum(f_m, 0)
            x_f = jnp.where(s == 0, micro[jnp.clip(f_m, 0, n_micro - 1)],
                            inbox_f[f_mc % n_stages])
            # last stage's forward output is never consumed (it is not
            # a ppermute source, and its backward recomputes from
            # saved_x inside vjp) — skip that dead layer-slice apply
            y_send = jax.lax.cond(
                (f_m >= 0) & (s < S - 1),
                lambda p, x: _apply_local(block_fn, p, x),
                lambda p, x: x, params, x_f)
            saved_x = jnp.where(f_m >= 0,
                                saved_x.at[f_mc % n_stages].set(x_f),
                                saved_x)

            # ---- backward slot (remat: re-run this stage's forward
            # from the saved input inside vjp)
            b_mc = jnp.maximum(b_m, 0)
            x_b = saved_x[b_mc % n_stages]
            dy_b = inbox_b[b_mc % n_stages]

            def canon(dp, dx, loss_m):
                # cond branches must agree on vma: promote every output
                # to the accumulator manifests (no-op when already there)
                dp = jax.tree_util.tree_map(
                    lambda v, t: _pcast_to(v, _vma_of(t)), dp, zero_grads)
                return dp, _pcast_to(dx, base), _pcast_to(loss_m, base)

            def b_run(p, x, dy, m):
                def last_branch(_):
                    loss_m, pull = jax.vjp(
                        lambda p_, x_: per_micro_loss(
                            _apply_local(block_fn, p_, x_), m).astype(
                                jnp.float32), p, x)
                    # seed inherits the primal's varying manifest
                    dp, dx = pull(loss_m * 0 + 1)
                    return canon(dp, dx, loss_m)
                def mid_branch(_):
                    y, pull = jax.vjp(
                        lambda p_, x_: _apply_local(block_fn, p_, x_),
                        p, x)
                    dp, dx = pull(_pcast_to(dy, _vma_of(y)))
                    return canon(dp, dx, zero_loss)
                return jax.lax.cond(s == S - 1, last_branch, mid_branch,
                                    None)

            dp, dx_send, loss_m = jax.lax.cond(
                b_m >= 0, b_run,
                lambda p, x, dy, m: canon(zero_grads, jnp.zeros_like(x),
                                          zero_loss),
                params, x_b, dy_b, b_mc)
            grads = jax.tree_util.tree_map(jnp.add, grads, dp)
            loss_acc = loss_acc + loss_m
            if with_input_grad:  # static: dh carry only when requested
                dh = jnp.where((s == 0) & (b_m >= 0),
                               dh.at[b_mc].set(dx_send), dh)

            # ---- exchange: activations right, cotangents left; the
            # receiver knows the arriving micro from the sender's
            # schedule row
            y_right = jax.lax.ppermute(
                y_send, axis_name, [(i, i + 1) for i in range(S - 1)])
            dx_left = jax.lax.ppermute(
                dx_send, axis_name, [(i, i - 1) for i in range(1, S)])
            arr_f = frow[(s - 1) % S]
            inbox_f = jnp.where(
                (s > 0) & (arr_f >= 0),
                inbox_f.at[jnp.maximum(arr_f, 0) % n_stages].set(y_right),
                inbox_f)
            arr_b = brow[(s + 1) % S]
            inbox_b = jnp.where(
                (s < S - 1) & (arr_b >= 0),
                inbox_b.at[jnp.maximum(arr_b, 0) % n_stages].set(dx_left),
                inbox_b)
            return (inbox_f, saved_x, inbox_b, grads, dh, loss_acc), None

        dh0 = jnp.zeros_like(micro) if with_input_grad else \
            _pcast_to(jnp.zeros((), micro.dtype), base)
        carry0 = (ring(), ring(), ring(), zero_grads, dh0, zero_loss)
        (_, _, _, grads, dh, loss_acc), _ = jax.lax.scan(
            tick, carry0, jnp.arange(fwd_sched.shape[0]))

        # loss lives on the last stage, dh on stage 0: replicate both.
        # grads need NO cross-data psum: the vma-typed pullback already
        # reduced them onto the params' manifest.
        loss = jax.lax.psum(loss_acc, axis_name)
        if batch_axis:
            loss = jax.lax.psum(loss, batch_axis)
        if not with_input_grad:
            return loss, grads
        dh = jax.lax.psum(
            jnp.where(s == 0, dh, jnp.zeros_like(dh)), axis_name)
        return loss, grads, dh.reshape(h.shape)

    p_specs = _param_in_specs(stacked_params, axis_name, param_specs)
    io_spec = P(batch_axis) if batch_axis else P()
    out_specs = (P(), p_specs) + ((io_spec,) if with_input_grad else ())
    # check_vma=True: the varying-manifest type system is what makes the
    # per-microbatch jax.vjp transpose collectives correctly when
    # block_fn is tensor-parallel (pcast-to-varying transposes to psum,
    # psum to pcast) — with it, TP input-cotangents come back complete
    # instead of per-model-shard partials.
    return jax.shard_map(
        inner, mesh=mesh, in_specs=(p_specs, io_spec),
        out_specs=out_specs,
        check_vma=True)(stacked_params, h)
