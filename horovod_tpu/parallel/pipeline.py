"""Pipeline parallelism: a GPipe microbatch schedule over a ``stage``
mesh axis, built from ``shard_map`` + ``lax.scan`` + ``ppermute``.

Beyond-parity (SURVEY §2.7 marks PP absent from the 2019 reference) —
the TPU-native formulation: the layer stack's parameters are STACKED on
a leading dim and sharded over the ``stage`` axis (each stage holds its
contiguous slice of layers), activations flow stage-to-stage with
``ppermute`` inside a compiled ``scan`` over schedule ticks, and the
whole pipeline stays one differentiable XLA program — reverse-mode AD
routes gradients backward through the transposed ``ppermute``s, so
backward pipelining comes from the autodiff transpose instead of
hand-written schedule code.

Schedule: ``T = n_micro + n_stages - 1`` ticks. At tick ``t`` stage
``s`` processes microbatch ``t - s``. Bubble ticks compute on a REAL
microbatch (the state is seeded with ``micro[0]``, never zeros) whose
outputs are ``where``-masked away: the mask makes the bubble chains'
parameter cotangents exactly zero, but only because the bubble
intermediates are finite — a zero seed would send blocks with
norm/division structure (x/||x||, RMSNorm) through a point where the
vjp is NaN, and ``NaN * 0`` would poison the shared parameter
gradients. The last stage's collected outputs are ``psum``-replicated
back to every stage so the caller's loss sees a replicated activation.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def stack_params(param_trees):
    """Stack per-layer param trees along a new leading dim — the layout
    ``pipelined_forward`` shards over the stage axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_trees)


def pipelined_forward(block_fn: Callable[[Any, Any], Any], stacked_params,
                      h, *, mesh, axis_name="stage", n_micro=None,
                      batch_axis=None):
    """Run ``h`` through the stacked layers as a GPipe pipeline.

    ``block_fn(layer_params, x) -> x`` applies ONE layer. ``stacked_params``
    has every leaf stacked ``[L, ...]``; L must divide by the stage-axis
    size (each stage scans its local layers in order). ``h`` is the input
    activation ``[B, ...]`` with the per-shard batch divisible by
    ``n_micro`` (default: one microbatch per stage).

    ``batch_axis`` composes PP with DP: ``h``'s leading dim shards over
    that mesh axis and each data slice runs its own pipeline; the stacked
    params are replicated across ``batch_axis``, so their reverse-mode
    cotangents are psum'd over it by the ``shard_map`` transpose — the
    gradient allreduce falls out for free.
    """
    n_stages = mesh.shape[axis_name]
    if n_micro is None:
        n_micro = n_stages
    B = h.shape[0]
    dp = mesh.shape[batch_axis] if batch_axis else 1
    if B % (n_micro * dp):
        raise ValueError(
            f"batch {B} not divisible by n_micro={n_micro} x dp={dp}")
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")

    def inner(params, h):
        n = jax.lax.axis_size(axis_name)
        s = jax.lax.axis_index(axis_name)
        micro = h.reshape(n_micro, h.shape[0] // n_micro, *h.shape[1:])

        def apply_local(x):
            # this stage's slice of the layer stack, in order
            return jax.lax.scan(
                lambda c, p: (block_fn(p, c), None), x, params)[0]

        def tick(carry, t):
            state, outs = carry
            x_in = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            cur = jnp.where(s == 0, x_in, state)
            y = apply_local(cur)
            idx = t - (n - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, y, jnp.clip(idx, 0, n_micro - 1), 0)
            take = (s == n - 1) & (idx >= 0) & (idx < n_micro)
            outs = jnp.where(take, upd, outs)
            # hand my output to the next stage (stage 0 receives zeros)
            state = jax.lax.ppermute(
                y, axis_name, [(i, i + 1) for i in range(n - 1)])
            return (state, outs), None

        # seed bubbles with real data (see module docstring: a zeros seed
        # NaN-poisons gradients of norm-structured blocks); its masked
        # output contributes exactly zero cotangent
        state0 = micro[0]
        outs0 = jnp.zeros_like(micro)
        (_, outs), _ = jax.lax.scan(
            tick, (state0, outs0), jnp.arange(n_micro + n_stages - 1))
        # replicate the finished microbatches from the last stage
        outs = jax.lax.psum(
            jnp.where(s == n - 1, outs, jnp.zeros_like(outs)), axis_name)
        return outs.reshape(h.shape)

    io_spec = P(batch_axis) if batch_axis else P()
    return jax.shard_map(inner, mesh=mesh,
                         in_specs=(P(axis_name), io_spec),
                         out_specs=io_spec,
                         check_vma=False)(stacked_params, h)
