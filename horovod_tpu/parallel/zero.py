"""ZeRO stage-1: reduce-scattered gradients, sharded optimizer state.

Every rank in plain data parallelism holds the full optimizer state and
redundantly applies the full update. ZeRO-1 (Rajbhandari et al., 2020,
"ZeRO: Memory Optimizations Toward Training Trillion Parameter Models")
keeps the wire bytes of a bandwidth-optimal allreduce — which is a
reduce-scatter plus an all-gather — but inserts the optimizer between the
halves: reduce-scatter the gradients, update only this rank's 1/N shard of
the optimizer state, all-gather the updated shard. Optimizer compute and
optimizer-state memory shrink by ~1/N per device; parameters stay
replicated (stage 1 only).

The partition is defined by ``ops.fusion.BucketSchedule``: gradients are
packed into reverse-traversal buckets, each padded to a multiple of the
world size, and rank ``r`` owns flat chunk ``r`` of every bucket (the same
chunk the schedule's reduce-scatter deposits on it). Optimizer state is
stored per bucket as a ``[world, shard]`` array sharded over the scatter
axes, so the N-way partition is visible to jax as a real sharding — each
device materializes 1/N of the bytes.

Works with any elementwise ``optax`` transformation (sgd, momentum, adam,
adamw — anything whose update for element ``i`` depends only on
gradient/param/state element ``i``). Transformations that take global
norms across the whole pytree (clip_by_global_norm) would compute
shard-local norms here; compose those INSIDE the model's loss or before
``DistributedOptimizer`` instead.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import collective, fusion
from horovod_tpu.ops.reduction import Average, Sum


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static description of the optimizer-state partition: the bucket
    schedule (which flat ranges exist and who owns which chunk) plus the
    reduction op. Hashable — it rides as pytree aux data on
    :class:`ZeroState` so the partition travels with the state through
    jit/shard_map without retracing surprises."""

    schedule: fusion.BucketSchedule
    op: str = Average


class ZeroState:
    """Sharded optimizer state: ``inner`` is the wrapped optax state over
    the bucket-row pytree ``{"b0": [world, shard0], ...}``; ``plan`` is the
    static partition. Registered as a pytree node with ``plan`` as aux so
    tree_map/jit see only the arrays."""

    def __init__(self, inner: Any, plan: ZeroPlan):
        self.inner = inner
        self.plan = plan

    def tree_flatten(self):
        return ((self.inner,), self.plan)

    @classmethod
    def tree_unflatten(cls, plan, children):
        return cls(children[0], plan)

    def __repr__(self):
        return f"ZeroState(buckets={len(self.plan.schedule.buckets)})"


jax.tree_util.register_pytree_node(
    ZeroState, ZeroState.tree_flatten, ZeroState.tree_unflatten)


def _register_flax_serialization():
    """Make ZeroState round-trip through ``checkpoint.py`` (flax msgpack
    only serializes types it knows): the state dict carries the inner
    leaves; the static plan is NOT serialized — it is rebuilt from the
    live target's plan on restore, which is exactly the checkpoint
    module's structure-from-target contract."""
    try:
        from flax import serialization
    except ImportError:  # pragma: no cover - flax is a hard dep in practice
        return

    def to_state(z):
        return {"inner": serialization.to_state_dict(z.inner)}

    def from_state(target, state):
        return ZeroState(
            serialization.from_state_dict(target.inner, state["inner"]),
            target.plan)

    serialization.register_serialization_state(ZeroState, to_state,
                                               from_state)


_register_flax_serialization()


def _bucket_key(i):
    return f"b{i}"


def make_plan(params, op=Average, axes=None, threshold_bytes=None,
              hierarchical=False, mesh=None):
    """Build the ZeRO partition for ``params`` over the current mesh."""
    from horovod_tpu.parallel import mesh as mesh_lib

    if op not in (Sum, Average):
        raise ValueError(f"ZeRO-1 supports Sum or Average, got {op!r}")
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    axes = collective._resolve_axes(axes)
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    world = 1
    for a in axes:
        world *= shape[a]
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        raise ValueError("ZeRO-1 needs a non-empty parameter pytree")
    schedule = fusion.bucket_schedule(leaves, world,
                                      threshold_bytes=threshold_bytes,
                                      axes=axes, hierarchical=hierarchical)
    return ZeroPlan(schedule=schedule, op=op)


def _bucket_rows(schedule, idx, leaves):
    """Pack bucket ``idx`` of ``leaves`` into its padded flat form and
    reshape to ``[world, shard]`` rows (row ``r`` = rank ``r``'s chunk)."""
    flat = fusion._pack(schedule.buckets[idx], leaves)
    pad = schedule.padded_sizes[idx] - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(schedule.world, schedule.shard_sizes[idx])


# the GSPMD plan layer (parallel/gspmd.py) packs gradients/params into
# the same rows; public alias so it does not reach into a private name
bucket_rows = _bucket_rows


def init(tx, params, plan):
    """Initialize the wrapped optimizer over the bucket-row view of
    ``params``. Runs at top level (outside shard_map): the rows come out
    replicated and become genuinely sharded when placed with
    :func:`state_specs` shardings (``training.make_train_step`` does
    this)."""
    schedule = plan.schedule
    leaves = jax.tree_util.tree_leaves(params)
    rows = {_bucket_key(i): _bucket_rows(schedule, i, leaves)
            for i in range(len(schedule.buckets))}
    return ZeroState(tx.init(rows), plan)


def state_specs(zstate):
    """PartitionSpecs for a :class:`ZeroState`: bucket-row leaves
    (``[world, shard]``) are sharded over the scatter axes on dim 0;
    everything else (step counts, schedules) replicated. Returns a
    ZeroState-shaped spec tree, usable directly in shard_map in/out_specs
    and for ``jax.device_put`` placement."""
    schedule = zstate.plan.schedule
    row_spec = P(tuple(schedule.axes))

    def one(leaf):
        shape = jnp.shape(leaf)
        if len(shape) >= 1 and shape[0] == schedule.world:
            return row_spec
        return P()

    return ZeroState(jax.tree_util.tree_map(one, zstate.inner), zstate.plan)


def _local_param_rows(schedule, leaves):
    """This rank's ``[1, shard]`` slice of every bucket's packed params
    (replicated params sliced at ``mesh_rank`` — no communication)."""
    rank = collective.mesh_rank(schedule.axes)
    rows = {}
    for i in range(len(schedule.buckets)):
        flat = fusion._pack(schedule.buckets[i], leaves)
        pad = schedule.padded_sizes[i] - flat.shape[0]
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        shard = schedule.shard_sizes[i]
        rows[_bucket_key(i)] = lax.dynamic_slice(
            flat, (rank * shard,), (shard,))[None]
    return rows


def apply_shards(tx, grad_rows, zstate, params, wire=None,
                 ag_residuals=None):
    """The sharded-update tail: run ``tx.update`` on this rank's gradient
    shards (``{"bi": [1, shard]}``), then all-gather the updated-parameter
    DELTAS back into a full update pytree. Must run inside a named-axis
    context (shard_map). Returns ``(updates, new_zstate)`` with ``updates``
    shaped like ``params`` — feed ``optax.apply_updates``.

    ``wire`` (an ``ops.compression`` compressor) narrows the delta
    all-gather to the wire format; ``ag_residuals`` (a list of per-bucket
    shard-sized arrays) additionally turns on delta error feedback — the
    quantization error of THIS rank's delta shard is carried into the
    next step's shard before encoding, so the cumulative applied delta
    tracks the exact one (DoubleSqueeze-style; ``training.
    make_train_step`` threads the residuals through the train state).
    With ``ag_residuals`` the return grows to ``(updates, new_zstate,
    new_ag_residuals)``."""
    schedule = zstate.plan.schedule
    leaves, treedef = jax.tree_util.tree_flatten(params)
    param_rows = _local_param_rows(schedule, leaves)
    update_rows, new_inner = tx.update(grad_rows, zstate.inner, param_rows)

    new_residuals = list(ag_residuals) if ag_residuals is not None else None
    new_leaves = [None] * len(leaves)
    for i in range(len(schedule.buckets)):
        row = update_rows[_bucket_key(i)][0]
        if wire is None:
            flat = fusion.all_gather_bucket(schedule, i, row)
        else:
            res = ag_residuals[i] if ag_residuals is not None else None
            flat, new_res = fusion.all_gather_bucket_compressed(
                schedule, i, row, wire, residual=res)
            if new_residuals is not None:
                new_residuals[i] = new_res
        for j, arr in fusion.unpack_bucket(schedule, i, flat,
                                           leaves).items():
            new_leaves[j] = arr
    # a leaf can only be missing if the schedule was built for a different
    # pytree — fail loudly rather than emit zero updates
    missing = [j for j, leaf in enumerate(new_leaves) if leaf is None]
    if missing:
        raise ValueError(
            f"ZeRO plan does not cover gradient leaves {missing}; was the "
            "optimizer initialized with a different parameter tree?")
    updates = jax.tree_util.tree_unflatten(treedef, new_leaves)
    new_zstate = ZeroState(new_inner, zstate.plan)
    if new_residuals is not None:
        return updates, new_zstate, new_residuals
    return updates, new_zstate


def sharded_update(tx, grads, zstate, params, wire=None):
    """Full ZeRO-1 exchange for one already-accumulated gradient pytree:
    per-bucket reduce-scatter → sharded ``tx.update`` → all-gather of the
    updates. The ``DistributedOptimizer(sharded_update=True).update``
    implementation; the overlapped microbatch pipeline in
    ``training.make_train_step`` instead accumulates reduce-scattered
    shards itself and calls :func:`apply_shards` directly.

    ``wire`` compresses both halves of the exchange (gradient
    reduce-scatter + delta all-gather) STATELESSLY — this entry point has
    no step-to-step carry, so no error feedback; the pipeline path in
    ``make_train_step`` is the one that threads residuals."""
    schedule = zstate.plan.schedule
    leaves = jax.tree_util.tree_leaves(grads)
    grad_rows = {}
    for i in range(len(schedule.buckets)):
        if wire is None:
            shard = fusion.reduce_scatter_bucket(schedule, i, leaves,
                                                 op=zstate.plan.op)
        else:
            shard, _ = fusion.reduce_scatter_bucket_compressed(
                schedule, i, leaves, wire, op=zstate.plan.op)
        grad_rows[_bucket_key(i)] = shard[None]
    return apply_shards(tx, grad_rows, zstate, params, wire=wire)


def local_state_bytes(zstate):
    """Per-device optimizer-state bytes under this partition (the ZeRO-1
    memory claim, computable without devices): sharded ``[world, shard]``
    leaves count ``1/world`` of their bytes, replicated leaves count in
    full."""
    schedule = zstate.plan.schedule

    def one(total, leaf):
        arr = jnp.asarray(leaf)
        nbytes = arr.size * arr.dtype.itemsize
        if arr.ndim >= 1 and arr.shape[0] == schedule.world:
            return total + nbytes // schedule.world
        return total + nbytes

    return jax.tree_util.tree_reduce(one, zstate.inner, 0)
