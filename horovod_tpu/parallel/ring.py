"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Absent from the 2019 reference (SURVEY.md §5.7) but first-class here: the
mesh machinery that gives data parallelism also gives sequence sharding.
Two interchangeable strategies, both compiled by XLA over ICI:

* **Ring attention** (``ring_attention``): Q stays resident per shard; K/V
  blocks rotate around the mesh-axis ring via ``lax.ppermute`` while
  attention accumulates with the online-softmax (flash) recurrence in fp32.
  Per-chip memory stays O(S/n); the ppermute overlaps with the block
  matmuls in XLA's schedule. This is the TPU-idiomatic form of
  Ring Attention (Liu et al. 2023) — see PAPERS.md.
* **Ulysses** (``ulysses_attention``): one ``all_to_all`` re-shards from
  sequence-sharded/full-heads to head-sharded/full-sequence, runs dense
  attention locally, and reverses. Cheaper at moderate S, needs
  num_heads % axis_size == 0.

Causality is enforced by **absolute positions**, so both compose with any
ring order and with unequal offsets.
"""

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = jnp.float32(-1e30)


def default_positions(axis_name, batch, seq_local):
    """Absolute token positions for a sequence-sharded [B, S_local] block:
    this shard's offset on the ring plus the local arange. The single source
    of truth for the position formula used by causal masking."""
    offset = lax.axis_index(axis_name) * seq_local if axis_name else 0
    return (offset + jnp.arange(seq_local))[None, :] * jnp.ones(
        (batch, 1), jnp.int32)


def _block_update(q, k, v, q_pos, kv_pos, m, l, o, causal, scale):
    """One online-softmax accumulation step against a K/V block (fp32).

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D]; m,l: [B,H,Sq]; o: [B,H,Sq,D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = q_pos[:, None, :, None] >= kv_pos[:, None, None, :]
        s = jnp.where(mask, s, _NEG_BIG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(-1e30 - m_new) could overflow to 1 when the whole row is masked
    # (m_new == -1e30); zero those probabilities explicitly instead.
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG_BIG, 0.0, p)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name, causal=True, q_positions=None,
                   kv_positions=None, use_flash=False):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Shapes per shard: q/k/v ``[B, S_local, H, D]``; positions ``[B, S_local]``
    absolute token positions (used for causal masking across shards).
    Returns ``[B, S_local, H, D]`` in q.dtype.

    ``use_flash`` runs each shard's block attention through the Pallas
    flash kernel (ops/flash_attention.py) and merges blocks by
    log-sum-exp weighting; requires the DEFAULT contiguous positions
    (pass ``q_positions=None``) and tiling shapes — callers with custom
    positions keep the jnp path.
    """
    if use_flash and q_positions is None and kv_positions is None:
        from horovod_tpu.ops import flash_attention as fa
        _, sq_, _, d_ = q.shape
        if fa.kernel_supported(sq_, sq_, d_):
            return _ring_attention_flash(q, k, v, axis_name, causal)
        # shapes don't tile onto the kernel: silently use the jnp ring,
        # same fallback contract as the local attention() helper
    n = lax.axis_size(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / (float(d) ** 0.5)
    if q_positions is None:
        q_positions = default_positions(axis_name, b, sq)
    if kv_positions is None:
        kv_positions = q_positions

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, _):
        k_blk, v_blk, kv_pos, m, l, o = carry
        m, l, o = _block_update(q, k_blk, v_blk, q_positions, kv_pos,
                                m, l, o, causal, scale)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        kv_pos = lax.ppermute(kv_pos, axis_name, perm)
        return (k_blk, v_blk, kv_pos, m, l, o), None

    m0 = jnp.full((b, h, sq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (_, _, _, m, l, o), _ = lax.scan(
        step, (k, v, kv_positions, m0, l0, o0), None, length=n)
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.einsum("bhqd->bqhd", out)


def _ring_attention_flash(q, k, v, axis_name, causal):
    """Ring attention whose per-block compute is the Pallas flash kernel
    in BOTH directions. Forward: blocks merge by the standard
    log-sum-exp composition ``out = sum_j exp(lse_j - LSE) * out_j``.
    Backward: a second ring pass runs the fused dQ/dKV kernels per
    rotated K/V block against the globally-merged lse (saved from the
    forward) and the once-computed ``delta = sum_d dO*O``; the dK/dV
    partial accumulators rotate WITH their K/V blocks, so after n steps
    each block's gradient arrives back at its home rank having collected
    every rank's contribution. p = exp(s - LSE) factorizes per block
    once LSE is global, so the summed partials equal the exact
    global-softmax gradient while peak memory stays O(S_local * block)
    — the dense jnp ring VJP it replaces materialized
    S_local x S_local score blocks per step."""
    from horovod_tpu.ops import flash_attention as fa

    n = lax.axis_size(axis_name)
    b, sq, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def fwd_impl(q, k, v):
        # axis_index must be taken INSIDE the custom_vjp'd function: a
        # closed-over tracer has no constant handler under grad tracing
        me = lax.axis_index(axis_name)
        q_off = (me * sq).astype(jnp.int32)

        def step(carry, _):
            k_blk, v_blk, kv_off, o_run, lse_run = carry
            o_j, lse_j = fa.flash_attention_with_lse(
                q, k_blk, v_blk, causal=causal, q_offset=q_off,
                kv_offset=kv_off[0])
            # streaming log-sum-exp merge (elementwise, XLA-fused)
            m = jnp.maximum(lse_run, lse_j)
            m_safe = jnp.where(m <= _NEG_BIG / 2, 0.0, m)
            w_run = jnp.where(lse_run <= _NEG_BIG / 2, 0.0,
                              jnp.exp(lse_run - m_safe))
            w_j = jnp.where(lse_j <= _NEG_BIG / 2, 0.0,
                            jnp.exp(lse_j - m_safe))
            tot = w_run + w_j
            tot_safe = jnp.where(tot == 0.0, 1.0, tot)
            # fp32 carry across all n steps; cast once after the scan
            # (repeated bf16 re-rounding would compound over the ring)
            o_run = ((o_run * w_run[..., None]
                      + o_j.astype(jnp.float32) * w_j[..., None])
                     / tot_safe[..., None])
            lse_run = jnp.where(tot == 0.0, _NEG_BIG,
                                m_safe + jnp.log(tot_safe))
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            kv_off = lax.ppermute(kv_off, axis_name, perm)
            return (k_blk, v_blk, kv_off, o_run, lse_run), None

        kv_off0 = (me * sq).astype(jnp.int32)[None]
        o0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((b, sq, h), _NEG_BIG, jnp.float32)
        (_, _, _, out, lse), _ = lax.scan(
            step, (k, v, kv_off0, o0, lse0), None, length=n)
        return out.astype(q.dtype), lse

    @jax.custom_vjp
    def run(q, k, v):
        out, _ = fwd_impl(q, k, v)
        return out

    def run_fwd(q, k, v):
        out, lse = fwd_impl(q, k, v)
        return out, (q, k, v, out, lse)

    def run_bwd(res, g):
        q, k, v, out, lse = res
        me = lax.axis_index(axis_name)
        q_off = (me * sq).astype(jnp.int32)
        # softmax-jacobian row correction against the FINAL output,
        # shared by every block's partial backward: [B,Sq,H,D] -> [B,Sq,H]
        delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)

        def step(carry, _):
            k_blk, v_blk, kv_off, dq_acc, dk_acc, dv_acc = carry
            dq_p, dk_p, dv_p = fa.flash_attention_bwd_block(
                q, k_blk, v_blk, g, lse, delta, causal=causal,
                q_offset=q_off, kv_offset=kv_off[0])
            dq_acc = dq_acc + dq_p
            dk_acc = dk_acc + dk_p
            dv_acc = dv_acc + dv_p
            # dk/dv accumulators travel WITH their K/V block: after the
            # full cycle they land home holding all ranks' contributions
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            kv_off = lax.ppermute(kv_off, axis_name, perm)
            dk_acc = lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = lax.ppermute(dv_acc, axis_name, perm)
            return (k_blk, v_blk, kv_off, dq_acc, dk_acc, dv_acc), None

        kv_off0 = (me * sq).astype(jnp.int32)[None]
        zeros = jnp.zeros((b, sq, h, d), jnp.float32)
        (_, _, _, dq, dk, dv), _ = lax.scan(
            step, (k, v, kv_off0, zeros, zeros, zeros), None, length=n)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    run.defvjp(run_fwd, run_bwd)
    return run(q, k, v)


def ulysses_attention(q, k, v, axis_name, causal=True, q_positions=None,
                      kv_positions=None):
    """Ulysses-style sequence parallelism: all-to-all from sequence-sharded
    to head-sharded, dense attention on the full sequence, and back.
    Requires ``num_heads % axis_size == 0``."""
    from horovod_tpu.models.transformer import dense_attention

    n = lax.axis_size(axis_name)
    b, sq, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"num_heads {h} not divisible by axis size {n}")
    if q_positions is None:
        q_positions = default_positions(axis_name, b, sq)
    if kv_positions is None:
        kv_positions = q_positions

    def to_heads(x):  # [B,S/n,H,D] -> [B,S,H/n,D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)
    pos_full = lax.all_gather(q_positions, axis_name, axis=1, tiled=True)
    kv_pos_full = lax.all_gather(kv_positions, axis_name, axis=1, tiled=True)
    out = dense_attention(qg, kg, vg, causal=causal, q_positions=pos_full,
                          kv_positions=kv_pos_full)
    # back: [B,S,H/n,D] -> [B,S/n,H,D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
