"""Tensor parallelism: shard attention heads and the MLP hidden dim over a
``model`` mesh axis, letting XLA/GSPMD insert the collectives.

Not in the 2019 reference (SURVEY.md §2.7 marks TP "not required for
parity") — built because a complete TPU framework must scale models past
one chip's HBM, and because on TPU the idiomatic implementation is
compiler-first rather than hand-written collectives: parameters carry
``NamedSharding``s derived from name-based rules, the jitted train step
is ONE logical program over the global mesh, and GSPMD partitions the
einsums and places the all-reduces on the residual stream — the Megatron
column/row-parallel schedule, recovered by the compiler from the weight
layouts alone:

* q/k/v projections ``(d_model, heads, head_dim)`` → heads sharded
  (column-parallel); the attention itself is then embarrassingly
  head-parallel.
* attention out ``(heads, head_dim, d_model)`` → heads sharded
  (row-parallel; GSPMD emits the one all-reduce into the residual).
* MLP ``Dense_0 (d_model, d_ff)`` column-parallel, ``Dense_1
  (d_ff, d_model)`` row-parallel — one more all-reduce.
* ``lm_head (d_model, vocab)`` column-parallel: logits arrive
  vocab-sharded and the loss's log-softmax gathers them.
* norms/embedding replicated.

Because the step is a single jitted program (no ``shard_map``), the data
axis needs no explicit gradient allreduce either: the global-batch mean
loss makes XLA emit the cross-data-axis reduction itself. Use a plain
optax optimizer here, not ``DistributedOptimizer`` (there is no named
axis inside to psum over — the compiler owns the collectives).
"""

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from horovod_tpu.training import TrainState


def transformer_param_specs(params, model_axis="model", expert_axis=None):
    """Name-rule ``PartitionSpec`` tree for ``models.transformer`` params.

    ``model_axis=None`` disables the tensor-parallel rules (e.g. an
    expert-parallel-only mesh); ``expert_axis`` shards embedded MoE
    expert weights (``cfg.moe_every``) over that axis. Anything the
    rules don't recognize (norm scales, embeddings, biases, MoE gates)
    is replicated — the safe default for small tensors.
    """
    def spec_for(path, leaf):
        names = [getattr(k, "key", str(k)) for k in path]
        joined = "/".join(names)
        nd = getattr(leaf, "ndim", 0)
        if expert_axis and "moe/" in joined:
            from horovod_tpu.models.moe import expert_major_spec
            spec = expert_major_spec(joined, expert_axis)
            if spec is not None:
                return spec                        # one expert per shard
        if model_axis is None:
            return P()
        if any(f"{p}/kernel" in joined for p in ("query", "key", "value")):
            return P(None, model_axis, None)       # column: shard heads
        if "out/kernel" in joined and nd == 3:
            return P(model_axis, None, None)       # row: reduce to residual
        if "Dense_0/kernel" in joined:
            return P(None, model_axis)             # column: shard d_ff
        if "Dense_1/kernel" in joined:
            return P(model_axis, None)             # row: reduce to residual
        if "lm_head/kernel" in joined:
            return P(None, model_axis)             # vocab-sharded logits
        return P()
    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_lm_state(model, tx, rng, sample_tokens, mesh,
                   model_axis="model", expert_axis=None):
    """Initialize a TP/EP-sharded ``TrainState``: params placed by the
    rule shardings, optimizer state initialized UNDER jit so GSPMD
    propagates the matching layouts onto the moments."""
    variables = model.init(rng, sample_tokens)
    params = variables["params"]
    specs = transformer_param_specs(params, model_axis, expert_axis)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(params, shardings)
    opt_state = jax.jit(tx.init)(params)
    return TrainState(params=params, opt_state=opt_state, batch_stats={},
                      step=jnp.zeros((), jnp.int32))


def make_tp_lm_train_step(model, tx, mesh, model_axis="model",
                          batch_axis="data", expert_axis=None,
                          donate=True, moe_aux_weight=0.01,
                          moe_z_weight=1e-3):
    """Jitted GSPMD language-model train step over a (data x model) mesh.

    ``step(state, tokens) -> (state, loss)``: ``tokens [B, S]`` sharded on
    ``batch_axis``, ``state`` from ``shard_lm_state``. Exact next-token
    loss; gradients/updates stay in the rule shardings (re-constrained
    after the update so a compiler heuristic can never drift the layout).

    MoE models (``cfg.moe_every``) sow Switch auxiliary terms into the
    ``"losses"`` collection; they are added here with the given weights
    (``moe_aux_weight`` load-balance, ``moe_z_weight`` router z-loss) —
    zero-cost no-op for dense models.
    """
    def step_fn(state, tokens):
        def compute_loss(params):
            logits, mutated = model.apply({"params": params}, tokens,
                                          mutable=["losses"])
            targets = tokens[:, 1:]
            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32),
                                      axis=-1)
            ll = jnp.take_along_axis(logp, targets[..., None],
                                     axis=-1)[..., 0]
            from horovod_tpu.models.moe import aux_loss
            return -jnp.mean(ll) + aux_loss(
                mutated, load_balance_weight=moe_aux_weight,
                router_z_weight=moe_z_weight)

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        specs = transformer_param_specs(params, model_axis, expert_axis)
        params = jax.lax.with_sharding_constraint(
            params, jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P)))
        new_state = TrainState(params=params, opt_state=opt_state,
                               batch_stats=state.batch_stats,
                               step=state.step + 1)
        return new_state, loss

    jitted = jax.jit(step_fn, donate_argnums=(0,) if donate else ())
    token_sharding = NamedSharding(mesh, P(batch_axis, None))

    def step(state, tokens):
        return jitted(state, jax.device_put(tokens, token_sharding))

    step.jitted = jitted
    return step
