"""Build and track the global device mesh.

TPU-native replacement for the reference's communicator setup
(``horovod/common/mpi/mpi_context.cc:25-86`` — GLOBAL/LOCAL/CROSS
communicator split; ``horovod/common/gloo/gloo_context.cc:30-56`` — the
gloo equivalent). Instead of three process communicators we build one
``jax.sharding.Mesh`` whose axes express the same hierarchy:

* ``data``  — the intra-slice (ICI) data-parallel axis. Collectives over it
  compile to ICI all-reduces (the role NCCL plays in the reference).
* ``dcn``   — the inter-slice axis, present only when spanning multiple TPU
  slices. Collectives over it ride the data-center network (the role the
  CROSS MPI communicator plays in
  ``horovod/common/ops/nccl_operations.cc:150-346``).
"""

import threading

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
DCN_AXIS = "dcn"

_lock = threading.Lock()
_current_mesh = None


def build_mesh(devices=None, num_slices=1, axis_names=(DCN_AXIS, DATA_AXIS)):
    """Build the global mesh over ``devices``.

    ``num_slices > 1`` produces a 2-D ``(dcn, data)`` mesh so callers can
    express hierarchical reductions (reduce-scatter over ICI, all-reduce over
    DCN, all-gather over ICI) — the TPU analogue of
    ``NCCLHierarchicalAllreduce`` (``nccl_operations.cc:150``). Otherwise the
    mesh is 1-D ``(data,)``.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = devices.size
    if num_slices > 1:
        if n % num_slices != 0:
            raise ValueError(
                f"device count {n} not divisible by num_slices {num_slices}")
        dev_grid = devices.reshape(num_slices, n // num_slices)
        return Mesh(dev_grid, axis_names)
    return Mesh(devices.reshape(n), (axis_names[-1],))


def set_mesh(mesh):
    global _current_mesh
    with _lock:
        _current_mesh = mesh


def get_mesh():
    """The mesh installed by ``horovod_tpu.init()`` (or ``set_mesh``)."""
    with _lock:
        if _current_mesh is None:
            raise RuntimeError(
                "horovod_tpu mesh is not set; call horovod_tpu.init() first")
        return _current_mesh


def data_axis_names(mesh=None):
    """All mesh axes that gradients are reduced over (data + dcn)."""
    mesh = mesh if mesh is not None else get_mesh()
    return tuple(a for a in mesh.axis_names if a in (DCN_AXIS, DATA_AXIS))


def ici_axis_names(mesh=None):
    """The intra-host (ICI) tier: every axis except ``dcn``. On a
    process mesh (cluster/procmesh.py) these are the minor axes whose
    collectives never leave a host."""
    mesh = mesh if mesh is not None else get_mesh()
    return tuple(a for a in mesh.axis_names if a != DCN_AXIS)


def process_span(mesh=None):
    """Number of distinct jax processes the mesh's devices live on
    (1 for every single-process mesh, N under hvdrun --spmd-procs N)."""
    mesh = mesh if mesh is not None else get_mesh()
    return len({d.process_index for d in mesh.devices.flat})
