"""Device-mesh management and parallelism strategies (TPU-native).

The reference scales via process-level NCCL/MPI communicators
(``horovod/common/mpi/mpi_context.cc``, LOCAL/CROSS communicator split at
``mpi_controller.cc:25-86``). The TPU-native equivalent is a
``jax.sharding.Mesh`` whose axes map onto the interconnect hierarchy:
``ici`` (intra-slice, fast torus links) and ``dcn`` (inter-slice data-center
network), with XLA emitting the collectives.
"""

from horovod_tpu.parallel.mesh import (
    build_mesh,
    get_mesh,
    set_mesh,
    data_axis_names,
    DATA_AXIS,
    DCN_AXIS,
)
from horovod_tpu.parallel.hierarchical import (hierarchical_allgather,
                                               hierarchical_allreduce,
                                               hierarchical_reducescatter)
from horovod_tpu.parallel import zero
from horovod_tpu.parallel.tensor import (
    make_tp_lm_train_step,
    shard_lm_state,
    transformer_param_specs,
)
from horovod_tpu.parallel.pipeline import (pipeline_train_1f1b,
                                           pipelined_forward, stack_params)

__all__ = [
    "build_mesh", "get_mesh", "set_mesh", "data_axis_names",
    "DATA_AXIS", "DCN_AXIS", "hierarchical_allreduce",
    "hierarchical_reducescatter", "hierarchical_allgather", "zero",
    "make_tp_lm_train_step", "shard_lm_state", "transformer_param_specs",
    "pipeline_train_1f1b", "pipelined_forward", "stack_params",
]
