"""Driver/task services: NIC discovery and routable-interface election.

Rebuilds the role of the reference driver/task service layer
(``horovod/run/common/service/driver_service.py:1-163``,
``task_service.py:1-165``, ``run/task_fn.py:1-67``): on a multi-host
cluster, every host may have several network interfaces and not all of
them are mutually routable (NAT, docker bridges, IB-only fabrics).  The
reference solves it by having each task register its candidate
``{interface: [(ip, port)]}`` map with a driver service, then ping the
*next* task in a ring with interface matching to weed out NAT'ed paths,
and finally intersecting the surviving interface sets across all hosts.

This framework realizes the same protocol over its authenticated HTTP KV
plane (run/rendezvous.py) instead of bespoke pickled-TCP services:

- each task runs a tiny HMAC-framed TCP ``PingServer`` (JSON payloads,
  never pickle) that reports the source address it observed,
- registration and result collection ride the signed KV under
  ``disc/``, so one server handles rendezvous, function shipping and
  discovery,
- the driver intersects per-link reachable interfaces and publishes the
  common set, which the launcher feeds into the worker env
  (``HOROVOD_COMMON_INTERFACES``) for the control-plane bind.

All messages are HMAC-authenticated with the per-run key; a task or ping
with a bad digest is dropped (reference Wire, ``network.py:61-86``).
"""

import fcntl
import hashlib
import hmac
import json
import socket
import socketserver
import struct
import threading
import time

from horovod_tpu.run import secret as _secret
from horovod_tpu.run.rendezvous import kv_get, kv_put, kv_wait

SIOCGIFADDR = 0x8915


def local_interfaces(port=0, nic=None):
    """``{interface: [(ip, port)]}`` for every AF_INET interface on this
    host (reference ``network.py:127-141`` ``_get_local_addresses``, built
    on ioctls instead of psutil, which this image lacks)."""
    result = {}
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _idx, name in socket.if_nameindex():
            if nic and name != nic:
                continue
            try:
                packed = fcntl.ioctl(
                    s.fileno(), SIOCGIFADDR,
                    struct.pack("256s", name.encode()[:255]))
            except OSError:
                continue  # interface has no IPv4 address
            ip = socket.inet_ntoa(packed[20:24])
            result.setdefault(name, []).append((ip, port))
    finally:
        s.close()
    if not result and nic:
        raise RuntimeError(
            f"no usable IPv4 address on requested interface {nic!r}")
    return result


def host_hash(salt=None):
    """Stable identifier for 'same physical host' used to group ranks for
    shared-memory locality (reference ``util/host_hash.py``). Salt lets
    tests simulate distinct hosts on one machine."""
    base = socket.gethostname()
    if salt:
        base = f"{base}-{salt}"
    return hashlib.md5(base.encode()).hexdigest()


# ---------------------------------------------------------------------------
# HMAC-framed JSON ping protocol (digest || u32 len || json)
# ---------------------------------------------------------------------------

_DIGEST_LEN = 32


def _send_frame(sock, key, obj):
    body = json.dumps(obj).encode()
    digest = hmac.new(key, body, hashlib.sha256).digest()
    sock.sendall(digest + struct.pack("<I", len(body)) + body)


def _recv_frame(sock, key):
    header = _recv_exact(sock, _DIGEST_LEN + 4)
    if header is None:
        return None
    digest, (length,) = header[:_DIGEST_LEN], struct.unpack(
        "<I", header[_DIGEST_LEN:])
    if length > 1 << 20:
        return None
    body = _recv_exact(sock, length)
    if body is None:
        return None
    if not hmac.compare_digest(
            hmac.new(key, body, hashlib.sha256).digest(), digest):
        return None
    return json.loads(body)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# Public names for the frame protocol: the elastic worker-notification
# plane (elastic/notification.py) speaks the same signed framing.
send_frame = _send_frame
recv_frame = _recv_frame


class PingServer:
    """Per-task reachability prober target (the role of the reference
    task service's PingRequest handler, ``network.py:115-117``): answers a
    signed ping with the service name and the source address it saw, so
    the prober can detect NAT (observed source != any local address of
    the interface it used)."""

    def __init__(self, service_name, key, host="0.0.0.0", port=0):
        self._name = service_name
        self._key = key
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                req = _recv_frame(self.request, outer._key)
                if req is None or req.get("op") != "ping":
                    return  # bad digest or garbage: drop silently
                _send_frame(self.request, outer._key,
                            {"service": outer._name,
                             "source": self.client_address[0]})

        self._server = socketserver.ThreadingTCPServer((host, port),
                                                       _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._server.socket.getsockname()[1]

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join()


def probe(addresses, key, service_name, match_intf=False,
          local_addrs=None, timeout=3.0, retries=2):
    """Try every candidate ``(ip, port)`` of every interface; return the
    map of interfaces that answered a correctly-signed ping (reference
    ``BasicClient._probe`` / ``_probe_one``, ``network.py:180-245``).

    With ``match_intf`` the observed source address must belong to the
    same-named local interface — the reference's NAT filter. Candidates
    are probed concurrently, as in the reference."""
    if match_intf and local_addrs is None:
        local_addrs = local_interfaces()
    reachable = {}
    lock = threading.Lock()

    def _one(intf, addr):
        for _ in range(retries):
            try:
                with socket.create_connection(tuple(addr),
                                              timeout=timeout) as sock:
                    _send_frame(sock, key, {"op": "ping"})
                    resp = _recv_frame(sock, key)
                if resp is None or resp.get("service") != service_name:
                    return
                if match_intf:
                    mine = [ip for ip, _p in local_addrs.get(intf, [])]
                    if resp.get("source") not in mine:
                        return  # reached it through a different interface
                with lock:
                    reachable.setdefault(intf, []).append(tuple(addr))
                return
            except OSError:
                continue
    threads = [threading.Thread(target=_one, args=(intf, addr), daemon=True)
               for intf, addrs in addresses.items() for addr in addrs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return reachable


# ---------------------------------------------------------------------------
# Driver / task registration over the signed KV
# ---------------------------------------------------------------------------

class DriverService:
    """Launcher-side aggregation (reference ``BasicDriverService``):
    collects task registrations from the KV, groups ranks by host hash,
    and elects the common routable interface set."""

    def __init__(self, num_tasks, kv_addr, kv_port, key, liveness=None):
        self.num_tasks = num_tasks
        self._kv = (kv_addr, kv_port)
        self._key = key
        self._liveness = liveness
        """Optional callable returning False when a discovery task died —
        turns a would-be full-timeout stall into an immediate error."""

    def _get(self, key_path, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline:
            v = kv_get(*self._kv, key_path, auth_key=self._key)
            if v is not None:
                return v
            if self._liveness is not None and not self._liveness():
                raise RuntimeError(
                    "a discovery task exited before completing the "
                    "protocol (ssh failure or crash on a remote host)")
            time.sleep(0.2)
        raise TimeoutError(f"{key_path} not published within {timeout}s")

    def wait_for_registrations(self, timeout=60.0):
        """Block until every task has registered; returns
        ``{index: {"addresses": ..., "host_hash": ...}}``
        (reference ``wait_for_initial_registration``)."""
        regs = {}
        for i in range(self.num_tasks):
            regs[i] = json.loads(self._get(f"disc/task/{i}", timeout))
        # publish the full registry so tasks can find their ring successor
        kv_put(*self._kv, "disc/all",
               json.dumps(regs).encode(), auth_key=self._key)
        return regs

    def wait_for_probes(self, timeout=60.0):
        """Collect each task's ring-probe result and intersect interface
        names (reference ``driver_service.py`` task-to-task updates +
        ``gloo_run.py`` common-intf intersection)."""
        common = None
        for i in range(self.num_tasks):
            reach = json.loads(self._get(f"disc/reach/{i}", timeout))
            names = set(reach.keys())
            common = names if common is None else (common & names)
        common = sorted(common or ())
        kv_put(*self._kv, "disc/common",
               json.dumps(common).encode(), auth_key=self._key)
        return common

    def host_hash_indices(self, regs):
        """``{host_hash: [sorted indices]}`` — which ranks share a host
        (reference ``task_host_hash_indices``)."""
        groups = {}
        for idx, reg in regs.items():
            groups.setdefault(reg["host_hash"], []).append(int(idx))
        return {h: sorted(v) for h, v in groups.items()}


class TaskAgent:
    """Task-side protocol (reference ``task_fn._task_fn``): start a ping
    server, register with the driver, probe the ring successor with
    interface matching, and report the surviving interfaces."""

    def __init__(self, index, num_tasks, kv_addr, kv_port, key,
                 nic=None, addresses=None, host_salt=None):
        self.index = index
        self.num_tasks = num_tasks
        self._kv = (kv_addr, kv_port)
        self._key = key
        self._server = PingServer(f"task-{index}", key)
        if addresses:  # test fakes carry ip but not the live port
            self._addresses = {
                intf: [(ip, self._server.port) for ip, _p in addrs]
                for intf, addrs in addresses.items()}
        else:
            self._addresses = local_interfaces(port=self._server.port,
                                               nic=nic)
        self._host_salt = host_salt

    @property
    def addresses(self):
        return self._addresses

    def register(self):
        payload = {"addresses": self._addresses,
                   "host_hash": host_hash(self._host_salt)}
        kv_put(*self._kv, f"disc/task/{self.index}",
               json.dumps(payload).encode(), auth_key=self._key)

    def run_ring_probe(self, timeout=60.0):
        """Probe task ``(index+1) % n`` across all its candidate
        addresses and publish the interfaces that worked."""
        all_regs = json.loads(kv_wait(*self._kv, "disc/all",
                                      timeout=timeout, auth_key=self._key))
        succ = (self.index + 1) % self.num_tasks
        succ_addrs = all_regs[str(succ)]["addresses"]
        reach = probe(succ_addrs, self._key, f"task-{succ}",
                      match_intf=True, local_addrs=self._addresses)
        kv_put(*self._kv, f"disc/reach/{self.index}",
               json.dumps({k: [list(a) for a in v]
                           for k, v in reach.items()}).encode(),
               auth_key=self._key)
        return reach

    def common_interfaces(self, timeout=60.0):
        return json.loads(kv_wait(*self._kv, "disc/common",
                                  timeout=timeout, auth_key=self._key))

    def shutdown(self):
        self._server.shutdown()


def discover(num_tasks, kv_addr, kv_port, key, indices=None,
             host_salts=None, timeout=60.0):
    """Run the whole task-side protocol for the given indices in this
    process (used by in-process launch modes and tests); returns the
    common interface list."""
    agents = [TaskAgent(i, num_tasks, kv_addr, kv_port, key,
                        host_salt=(host_salts or {}).get(i))
              for i in (indices or range(num_tasks))]
    try:
        for a in agents:
            a.register()
        driver = DriverService(num_tasks, kv_addr, kv_port, key)
        regs = driver.wait_for_registrations(timeout)
        threads = [threading.Thread(target=a.run_ring_probe, daemon=True)
                   for a in agents]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        common = driver.wait_for_probes(timeout)
        return common, driver.host_hash_indices(regs)
    finally:
        for a in agents:
            a.shutdown()
