"""Process spawning, monitoring, and failure fan-out.

Rebuilds ``horovod/run/gloo_run.py:142-288`` (``_launch_jobs``): one
process per slot with the env contract, local slots via subprocess,
remote slots via ssh; a monitor thread per process; any non-zero exit
kills the whole job; SIGINT/SIGTERM fan out to every child.
"""

import os
import shlex
import signal
import subprocess
import sys
import threading

LOCAL_HOSTS = ("localhost", "127.0.0.1")


def slot_env(slot, controller_addr, controller_port, rendezvous_addr=None,
             rendezvous_port=None, extra_env=None):
    """The worker env contract (reference gloo_run.py:210-236,
    gloo_context.cc:41-50)."""
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
        "HOROVOD_HOSTNAME": slot.hostname,
    }
    if rendezvous_addr is not None:
        env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = rendezvous_addr
        env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(rendezvous_port)
    if extra_env:
        env.update(extra_env)
    return env


def build_command(slot, command, env, ssh_port=None, cwd=None):
    """Local slots exec the command directly; remote slots wrap it in ssh
    with inline env exports (reference gloo_run.py:262-288)."""
    if slot.hostname in LOCAL_HOSTS:
        return command, env  # merged with os.environ by the spawner
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote_cwd = cwd or os.getcwd()
    remote = (f"cd {shlex.quote(remote_cwd)} && env {exports} " +
              " ".join(shlex.quote(c) for c in command))
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    ssh += [slot.hostname, remote]
    return ssh, {}


class Job:
    """A running multi-process job."""

    def __init__(self):
        self.procs = []
        self._failed = threading.Event()
        self.first_failure = None
        self._lock = threading.Lock()

    def kill_all(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass

    def _monitor(self, rank, proc):
        rc = proc.wait()
        if rc != 0 and not self._failed.is_set():
            with self._lock:
                if self.first_failure is None:
                    self.first_failure = (rank, rc)
            self._failed.set()
            self.kill_all()

    def wait(self):
        """Block until all processes exit; raise on any failure
        (reference gloo_run.py:253-259)."""
        threads = [threading.Thread(target=self._monitor, args=(r, p))
                   for r, p in enumerate(self.procs)]
        for t in threads:
            t.start()
        try:
            for t in threads:
                t.join()
        except KeyboardInterrupt:
            self.kill_all(signal.SIGINT)
            for t in threads:
                t.join()
            raise
        if self.first_failure is not None:
            rank, rc = self.first_failure
            raise RuntimeError(
                f"hvdrun: process with rank {rank} exited with code {rc}; "
                f"remaining processes were terminated")


def launcher_addr(slots):
    """Address where workers can reach services running on the LAUNCHER
    machine (the KV/rendezvous server): loopback for all-local jobs, this
    host's address otherwise."""
    import socket
    if all(s.hostname in LOCAL_HOSTS for s in slots):
        return "127.0.0.1"
    return socket.gethostbyname(socket.gethostname())


def launch(slots, command, controller_addr, controller_port,
           rendezvous_addr=None, rendezvous_port=None, extra_env=None,
           ssh_port=None, stdout=None, output_dir=None):
    """Spawn one process per slot and return a Job."""
    job = Job()
    if rendezvous_port is not None and rendezvous_addr is None:
        rendezvous_addr = launcher_addr(slots)
    for slot in slots:
        env = slot_env(slot, controller_addr, controller_port,
                       rendezvous_addr=rendezvous_addr,
                       rendezvous_port=rendezvous_port, extra_env=extra_env)
        cmd, proc_env = build_command(slot, command, env, ssh_port=ssh_port)
        full_env = dict(os.environ)
        full_env.update(proc_env if cmd[0] == "ssh" else env)
        out = stdout
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            out = open(os.path.join(output_dir, f"rank.{slot.rank}.log"),
                       "wb")
        job.procs.append(subprocess.Popen(
            cmd, env=full_env, stdout=out,
            stderr=subprocess.STDOUT if out else None))
    # fan out SIGINT/SIGTERM (only from the main thread of the CLI)
    if threading.current_thread() is threading.main_thread():
        def _forward(signum, frame):
            job.kill_all(signum)
            sys.exit(128 + signum)
        try:
            signal.signal(signal.SIGTERM, _forward)
        except ValueError:
            pass
    return job
