"""Process spawning, monitoring, and failure fan-out.

Rebuilds ``horovod/run/gloo_run.py:142-288`` (``_launch_jobs``): one
process per slot with the env contract, local slots via subprocess,
remote slots via ssh; a monitor thread per process; any non-zero exit
kills the whole job; SIGINT/SIGTERM fan out to every child.
"""

import os
import shlex
import signal
import subprocess
import sys
import threading
import time

from horovod_tpu.run.secret import SECRET_ENV

LOCAL_HOSTS = ("localhost", "127.0.0.1")

# SIGTERM fan-out escalation: forwarded SIGTERM -> wait this many
# seconds for workers to finish their graceful eviction (bounded grace
# commit, elastic/preempt.py) -> SIGKILL survivors. Without the
# escalation one worker ignoring SIGTERM parks the launcher forever.
GRACE_ENV = "HOROVOD_GRACE_SECONDS"
DEFAULT_GRACE_SECONDS = 30.0


def grace_seconds(env=None):
    raw = (env if env is not None else os.environ).get(GRACE_ENV)
    if not raw:
        return DEFAULT_GRACE_SECONDS
    try:
        return max(0.0, float(raw))
    except ValueError:
        return DEFAULT_GRACE_SECONDS


def slot_env(slot, controller_addr, controller_port, rendezvous_addr=None,
             rendezvous_port=None, extra_env=None):
    """The worker env contract (reference gloo_run.py:210-236,
    gloo_context.cc:41-50)."""
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_CONTROLLER_ADDR": controller_addr,
        "HOROVOD_CONTROLLER_PORT": str(controller_port),
        "HOROVOD_HOSTNAME": slot.hostname,
    }
    if rendezvous_addr is not None:
        env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = rendezvous_addr
        env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(rendezvous_port)
    if extra_env:
        env.update(extra_env)
    # metrics endpoint: the launcher-level HOROVOD_METRICS_PORT is the
    # BASE port; each rank serves on base + local_rank so co-located
    # ranks never collide (0 = every rank binds its own ephemeral port)
    base = env.get("HOROVOD_METRICS_PORT")
    if base:
        try:
            base_port = int(base)
        except ValueError:
            base_port = 0
        if base_port > 0:
            env["HOROVOD_METRICS_PORT"] = str(base_port + slot.local_rank)
    return env


def build_command(hostname, command, env, ssh_port=None, cwd=None,
                  remote_middleman=False):
    """Local hosts exec the command directly; remote hosts wrap it in ssh
    with inline env exports (reference gloo_run.py:262-288).

    Returns ``(cmd, proc_env, stdin_payload)``. The per-run HMAC secret
    must never ride the ssh argv (world-readable in /proc/*/cmdline on
    every host), so for remote hosts it is stripped from the inline
    exports and shipped over the ssh channel's stdin instead; the remote
    end reads one line into the env before exec. The remote string runs
    under an explicit ``/bin/sh -c`` so a csh/fish login shell can't
    break the POSIX prefix."""
    if hostname in LOCAL_HOSTS:
        # local: plain process env — readable only by the same user
        return command, env, None
    env = dict(env)
    payload = None
    prefix = ""
    if SECRET_ENV in env:
        payload = (env.pop(SECRET_ENV) + "\n").encode()
        prefix = f"IFS= read -r {SECRET_ENV}; export {SECRET_ENV}; "
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote_cwd = cwd or os.getcwd()
    if remote_middleman:
        # orphan-reaping middleman on the far side: EOF on the ssh
        # channel's stdin (launcher death, dropped connection) reaps the
        # training process tree (run/safe_exec.py)
        command = ["python3", "-m", "horovod_tpu.run.safe_exec",
                   "--watch-stdin", "--"] + list(command)
    remote = (prefix + f"cd {shlex.quote(remote_cwd)} && env {exports} " +
              " ".join(shlex.quote(c) for c in command))
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    ssh += [hostname, f"exec /bin/sh -c {shlex.quote(remote)}"]
    return ssh, {}, payload


def spawn(hostname, command, env, ssh_port=None, stdout=None,
          middleman=False):
    """Build + Popen one host process, handling the env merge and the
    secret-over-stdin contract in one place (used by the training launch
    and the discovery pre-flight).

    With ``middleman=True`` the command runs behind the orphan-reaping
    middleman (run/safe_exec.py): if this launcher process dies — even
    SIGKILL — the training tree is terminated rather than orphaned
    (reference safe_shell_exec.py). Locally the middleman watches an
    inherited death pipe whose write end lives in this process; over ssh
    it watches the channel's stdin for EOF."""
    local = hostname in LOCAL_HOSTS
    death_r = death_w = None
    if middleman and local:
        death_r, death_w = os.pipe()
        command = [sys.executable, "-m", "horovod_tpu.run.safe_exec",
                   str(death_r), "--"] + list(command)
    cmd, proc_env, payload = build_command(
        hostname, command, env, ssh_port=ssh_port,
        remote_middleman=middleman and not local)
    full_env = dict(os.environ)
    full_env.update(proc_env if cmd[0] == "ssh" else env)
    hold_stdin = middleman and not local  # ssh stdin EOF = launcher died
    proc = subprocess.Popen(
        cmd, env=full_env, stdout=stdout,
        stderr=subprocess.STDOUT if stdout else None,
        stdin=subprocess.PIPE if (payload or hold_stdin) else None,
        pass_fds=(death_r,) if death_r is not None else ())
    if payload:
        proc.stdin.write(payload)
        proc.stdin.flush()
    if proc.stdin is not None and not hold_stdin:
        proc.stdin.close()
    if death_r is not None:
        os.close(death_r)
        # the write end must live exactly as long as this process: keep a
        # reference on the Popen so GC can't close it early
        proc._hvd_death_w = death_w
    return proc


class Job:
    """A running multi-process job."""

    def __init__(self):
        self.procs = []
        self.slots = []  # Slot per proc (same order); chaos host targets
        self._failed = threading.Event()
        self.first_failure = None
        self.exit_codes = {}
        self._lock = threading.Lock()

    def kill_all(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass

    def escalate_after_grace(self, grace=None, clock=time.monotonic,
                             sleep=time.sleep):
        """Wait up to ``grace`` seconds (``HOROVOD_GRACE_SECONDS``) for
        every process to exit, then SIGKILL the survivors. Returns the
        list of ranks killed. ``clock``/``sleep`` are injectable for
        fake-clock tests."""
        grace = grace_seconds() if grace is None else grace
        deadline = clock() + grace
        while clock() < deadline:
            if all(p.poll() is not None for p in self.procs):
                return []
            sleep(min(0.2, max(0.01, deadline - clock())))
        killed = []
        for rank, p in enumerate(self.procs):
            if p.poll() is None:
                try:
                    p.kill()
                    killed.append(rank)
                except OSError:
                    pass
        if killed:
            sys.stderr.write(
                f"hvdrun: rank(s) {killed} survived SIGTERM past the "
                f"{grace:.0f}s grace deadline; SIGKILLed\n")
        return killed

    def _monitor(self, rank, proc):
        rc = proc.wait()
        with self._lock:
            self.exit_codes[rank] = rc
        # release this worker's middleman death-pipe write end (spawn());
        # without this a long-lived driver leaks one fd per worker launch
        death_w = getattr(proc, "_hvd_death_w", None)
        if death_w is not None:
            try:
                os.close(death_w)
            except OSError:
                pass
            proc._hvd_death_w = None
        if rc != 0 and not self._failed.is_set():
            with self._lock:
                if self.first_failure is None:
                    self.first_failure = (rank, rc)
            self._failed.set()
            self.kill_all()

    def wait(self):
        """Block until all processes exit; raise on any failure
        (reference gloo_run.py:253-259)."""
        self.join()
        if self.first_failure is not None:
            rank, rc = self.first_failure
            raise RuntimeError(
                f"hvdrun: process with rank {rank} exited with code {rc}; "
                f"remaining processes were terminated")

    def join(self):
        """Like :meth:`wait`, but return ``{rank: exit_code}`` instead of
        raising. The kill-on-first-failure fan-out still applies; the
        elastic driver inspects ``first_failure`` to decide whom to blame
        (only the FIRST failing rank — the rest died from our own
        SIGTERM)."""
        threads = [threading.Thread(target=self._monitor, args=(r, p))
                   for r, p in enumerate(self.procs)]
        for t in threads:
            t.start()
        try:
            for t in threads:
                t.join()
        except KeyboardInterrupt:
            self.kill_all(signal.SIGINT)
            for t in threads:
                t.join()
            raise
        return dict(self.exit_codes)


def this_host_addr():
    """This machine's address as remote workers should dial it."""
    import socket
    return socket.gethostbyname(socket.gethostname())


def repo_pythonpath(base_env=None):
    """PYTHONPATH that puts this checkout first, preserving whatever the
    caller had (shared by the programmatic and cluster launch paths)."""
    root = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir, os.pardir))
    existing = [p for p in (base_env or os.environ).get(
        "PYTHONPATH", "").split(os.pathsep) if p]
    return os.pathsep.join([root] + existing)


def launcher_addr(slots):
    """Address where workers can reach services running on the LAUNCHER
    machine (the KV/rendezvous server): loopback for all-local jobs, this
    host's address otherwise."""
    if all(s.hostname in LOCAL_HOSTS for s in slots):
        return "127.0.0.1"
    return this_host_addr()


def launch(slots, command, controller_addr, controller_port,
           rendezvous_addr=None, rendezvous_port=None, extra_env=None,
           ssh_port=None, stdout=None, output_dir=None, middleman=True):
    """Spawn one process per slot and return a Job. Every worker runs
    behind the orphan-reaping middleman unless ``middleman=False``."""
    job = Job()
    if rendezvous_port is not None and rendezvous_addr is None:
        rendezvous_addr = launcher_addr(slots)
    if output_dir and not (extra_env or {}).get("HOROVOD_FLIGHTREC_DIR"):
        # flight-recorder dumps belong next to the per-rank logs they
        # explain (elastic epochs get per-epoch dirs for free); the
        # hvdrun CLI only pre-sets the var when there is NO output dir
        extra_env = dict(extra_env or {})
        extra_env["HOROVOD_FLIGHTREC_DIR"] = output_dir
    for slot in slots:
        env = slot_env(slot, controller_addr, controller_port,
                       rendezvous_addr=rendezvous_addr,
                       rendezvous_port=rendezvous_port, extra_env=extra_env)
        out = stdout
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            out = open(os.path.join(output_dir, f"rank.{slot.rank}.log"),
                       "wb")
        job.procs.append(spawn(slot.hostname, command, env,
                               ssh_port=ssh_port, stdout=out,
                               middleman=middleman))
        job.slots.append(slot)
    # fan out SIGINT/SIGTERM (only from the main thread of the CLI)
    if threading.current_thread() is threading.main_thread():
        def _forward(signum, frame):
            job.kill_all(signum)
            # escalation on its OWN NON-daemon thread: the handler must
            # stay non-blocking (HVD-SIGSAFE), and the thread must
            # survive the SystemExit below — the interpreter waits for
            # non-daemon threads, which is exactly what lets it SIGKILL
            # a worker that ignores the forwarded SIGTERM; the thread
            # self-terminates within the grace budget either way
            threading.Thread(target=job.escalate_after_grace,
                             name="hvd_tpu_grace").start()
            sys.exit(128 + signum)
        try:
            signal.signal(signal.SIGTERM, _forward)
        except ValueError:
            pass
    return job
