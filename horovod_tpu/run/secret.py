"""Per-run secret keys and HMAC request signing.

Rebuilds the role of ``horovod/run/common/util/secret.py:1-36`` (per-run
32-byte key, HMAC-SHA256 digests, constant-time comparison) for this
framework's HTTP control plane.  Where the reference frames raw-TCP
messages as ``digest || len || cloudpickle``, we sign HTTP requests with
an ``X-HVD-Auth`` header over ``method \\n path \\n body`` — same
guarantee (no unauthenticated writes reach the run's control services),
realized idiomatically for the HTTP KV/rendezvous plane.

The key travels to workers the same way the reference distributes it: an
environment variable (reference ``_HOROVOD_SECRET_KEY``), hex-encoded.

Threat-model note: signatures cover ``(method, path, body)`` but carry
no nonce or timestamp, so an on-path observer who captures a signed
request can REPLAY it verbatim for the lifetime of the run (e.g.
re-PUT a stale key/value). This matches the reference's guarantee level
— its framed digests are equally replayable — and is acceptable because
keys are per-run and the control plane is idempotent puts/gets; if a
deployment needs replay resistance, fold a per-run random context string
plus a monotonic counter into the signed message.
"""

import hashlib
import hmac
import os

SECRET_LENGTH = 32  # bytes
SECRET_ENV = "HOROVOD_SECRET_KEY"


def make_secret_key():
    """A fresh per-run key (reference secret.py:27-28)."""
    return os.urandom(SECRET_LENGTH)


def encode_key(key):
    return key.hex()


def decode_key(text):
    return bytes.fromhex(text)


def key_from_env(env=None):
    """The run's key from the environment, or None when the run is
    unauthenticated (single-host loopback jobs)."""
    val = (env or os.environ).get(SECRET_ENV)
    return decode_key(val) if val else None


def sign(key, method, path, body=b""):
    """Hex HMAC-SHA256 over the request triple."""
    msg = method.encode() + b"\n" + path.encode() + b"\n" + body
    return hmac.new(key, msg, hashlib.sha256).hexdigest()


def verify(key, method, path, body, digest_hex):
    """Constant-time check (reference secret.py:35-36). Compares as
    bytes: compare_digest on str raises for non-ASCII input, which a
    hostile header could otherwise use to crash the handler thread."""
    if not digest_hex:
        return False
    expected = sign(key, method, path, body)
    return hmac.compare_digest(expected.encode(), digest_hex.encode())
