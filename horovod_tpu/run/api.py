"""Programmatic launcher: ``run(fn, args=(), np=N)``.

Rebuilds the reference's interactive API (``horovod.run.run()``,
``horovod/run/run.py:857-953``): pickle a function, ship it to N freshly
launched worker processes through the KV server, execute it under the full
env contract, collect per-rank results in rank order.
"""

import os
import pickle
import sys

from horovod_tpu.run import allocation, launcher
from horovod_tpu.run.rendezvous import KVStoreServer, kv_wait

try:  # cloudpickle handles closures/lambdas; stdlib pickle is the fallback
    import cloudpickle as _pickler
except ImportError:  # pragma: no cover
    _pickler = pickle


def run(fn, args=(), kwargs=None, np=1, hosts=None, extra_env=None,
        timeout=300, use_jax_coordinator=False):
    """Run ``fn(*args, **kwargs)`` in ``np`` horovod_tpu processes and
    return the list of per-rank return values (rank order)."""
    kwargs = kwargs or {}
    host_list = (allocation.parse_hosts(hosts) if hosts
                 else [allocation.HostSlots("localhost", np)])
    slots = allocation.allocate(host_list, np)

    controller_addr = slots[0].hostname
    if controller_addr in launcher.LOCAL_HOSTS:
        controller_addr = "127.0.0.1"
    controller_port = 0  # rank 0 binds + publishes via the KV server

    kv = KVStoreServer()
    rendezvous_port = kv.start()
    kv.put("runfunc/func", _pickler.dumps((fn, args, kwargs)))

    env = dict(extra_env or {})
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.abspath(os.path.join(os.path.dirname(__file__),
                                      os.pardir, os.pardir))] +
        os.environ.get("PYTHONPATH", "").split(os.pathsep))
    if use_jax_coordinator:
        env["HOROVOD_COORDINATOR_ADDR"] = (
            f"{controller_addr}:{free_port()}")

    command = [sys.executable, "-m", "horovod_tpu.run.run_task"]
    job = launcher.launch(slots, command, controller_addr, controller_port,
                          rendezvous_port=rendezvous_port, extra_env=env)
    try:
        job.wait()
        results = []
        for r in range(np):
            payload = kv_wait("127.0.0.1", rendezvous_port,
                              f"runfunc/result/{r}", timeout=timeout)
            ok, value = pickle.loads(payload)
            if not ok:
                raise RuntimeError(f"rank {r} raised: {value}")
            results.append(value)
        return results
    finally:
        kv.stop()
