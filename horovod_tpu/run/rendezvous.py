"""HTTP rendezvous / KV store server.

Rebuilds ``horovod/run/http/http_server.py`` (RendezvousServer /
KVStoreServer): an in-memory key-value store over HTTP GET/PUT/DELETE,
scoped by path (``/scope/key``). Used by the launcher to pass pickled
functions and collect results (``horovod.run.run()`` pattern) and
available to external tooling as a rendezvous point. GET on a missing key
returns 404 so clients can poll (reference http_server.py:40-60).
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Handler(BaseHTTPRequestHandler):
    store = None  # class attribute set by the server
    lock = None

    def log_message(self, *args):  # quiet
        pass

    def _key(self):
        return self.path.lstrip("/")

    def do_GET(self):
        with self.lock:
            val = self.store.get(self._key())
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        with self.lock:
            self.store[self._key()] = body
        self.send_response(200)
        self.end_headers()

    def do_DELETE(self):
        with self.lock:
            self.store.pop(self._key(), None)
        self.send_response(200)
        self.end_headers()


class KVStoreServer:
    """Threaded HTTP KV server; ``port=0`` binds an ephemeral port.

    Binds loopback by default — the store carries pickled functions, so it
    must not be reachable from the network unless the job actually spans
    hosts (pass ``host="0.0.0.0"`` then)."""

    def __init__(self, port=0, host="127.0.0.1"):
        handler = type("Handler", (_Handler,),
                       {"store": {}, "lock": threading.Lock()})
        self._handler_cls = handler
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join()

    # direct access for in-process use
    def get(self, key):
        with self._handler_cls.lock:
            return self._handler_cls.store.get(key)

    def put(self, key, value):
        with self._handler_cls.lock:
            self._handler_cls.store[key] = value


def kv_get(addr, port, key, timeout=5.0):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://{addr}:{port}/{key}", timeout=timeout) as r:
            return r.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def kv_put(addr, port, key, value):
    import urllib.request
    req = urllib.request.Request(f"http://{addr}:{port}/{key}",
                                 data=value, method="PUT")
    urllib.request.urlopen(req, timeout=5.0).read()


def kv_wait(addr, port, key, timeout=60.0, poll=0.1):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = kv_get(addr, port, key)
        if v is not None:
            return v
        time.sleep(poll)
    raise TimeoutError(f"key {key} not published within {timeout}s")
