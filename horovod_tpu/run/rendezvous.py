"""HTTP rendezvous / KV store server.

Rebuilds ``horovod/run/http/http_server.py`` (RendezvousServer /
KVStoreServer): an in-memory key-value store over HTTP GET/PUT/DELETE,
scoped by path (``/scope/key``). Used by the launcher to pass pickled
functions and collect results (``horovod.run.run()`` pattern) and
available to external tooling as a rendezvous point. GET on a missing key
returns 404 so clients can poll (reference http_server.py:40-60).

When constructed with ``auth_key``, every request must carry a valid
``X-HVD-Auth`` HMAC header (see run/secret.py) or it is rejected with
403 — the HTTP realization of the reference's HMAC-signed service RPC
(``run/common/util/network.py:61-86`` Wire, ``secret.py``). The store
carries pickled functions, so multi-host runs must always authenticate.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from horovod_tpu.run import secret as _secret

AUTH_HEADER = "X-HVD-Auth"


class _Handler(BaseHTTPRequestHandler):
    store = None  # class attribute set by the server
    lock = None
    auth_key = None

    def log_message(self, *args):  # quiet
        pass

    def _key(self):
        return self.path.lstrip("/")

    def _authorized(self, body=b""):
        if self.auth_key is None:
            return True
        return _secret.verify(self.auth_key, self.command, self.path, body,
                              self.headers.get(AUTH_HEADER))

    def _reject(self):
        self.send_response(403)
        self.end_headers()

    def do_GET(self):
        if not self._authorized():
            return self._reject()
        with self.lock:
            val = self.store.get(self._key())
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    # Body cap: legitimate payloads (pickled fns, addresses, results) stay
    # far below this. The signature covers the body, so verification
    # can't precede the read — the cap plus the header-shape precheck
    # bound what a garbage request can make us buffer; they don't defend
    # against a determined flood (that needs a firewall, not a KV).
    MAX_BODY = 64 << 20

    def _header_plausible(self):
        sig = self.headers.get(AUTH_HEADER, "")
        return len(sig) == 64 and all(c in "0123456789abcdef" for c in sig)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        if length > self.MAX_BODY or (
                self.auth_key is not None and not self._header_plausible()):
            return self._reject()
        body = self.rfile.read(length)
        if not self._authorized(body):
            return self._reject()
        with self.lock:
            self.store[self._key()] = body
        self.send_response(200)
        self.end_headers()

    def do_DELETE(self):
        if not self._authorized():
            return self._reject()
        with self.lock:
            self.store.pop(self._key(), None)
        self.send_response(200)
        self.end_headers()


class KVStoreServer:
    """Threaded HTTP KV server; ``port=0`` binds an ephemeral port.

    Binds loopback by default — the store carries pickled functions, so it
    must not be reachable from the network unless the job actually spans
    hosts (pass ``host="0.0.0.0"`` then)."""

    def __init__(self, port=0, host="127.0.0.1", auth_key=None):
        handler = type("Handler", (_Handler,),
                       {"store": {}, "lock": threading.Lock(),
                        "auth_key": auth_key})
        self._handler_cls = handler
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join()

    # direct access for in-process use
    def get(self, key):
        with self._handler_cls.lock:
            return self._handler_cls.store.get(key)

    def put(self, key, value):
        with self._handler_cls.lock:
            self._handler_cls.store[key] = value

    def delete(self, key):
        with self._handler_cls.lock:
            self._handler_cls.store.pop(key, None)


def _headers(auth_key, method, key, body=b""):
    if auth_key is None:
        return {}
    return {AUTH_HEADER: _secret.sign(auth_key, method, "/" + key, body)}


def kv_get(addr, port, key, timeout=5.0, auth_key=None):
    import urllib.error
    import urllib.request
    if auth_key is None:
        auth_key = _secret.key_from_env()
    req = urllib.request.Request(
        f"http://{addr}:{port}/{key}",
        headers=_headers(auth_key, "GET", key))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def kv_put(addr, port, key, value, auth_key=None):
    import urllib.request
    if auth_key is None:
        auth_key = _secret.key_from_env()
    req = urllib.request.Request(
        f"http://{addr}:{port}/{key}", data=value, method="PUT",
        headers=_headers(auth_key, "PUT", key, value))
    urllib.request.urlopen(req, timeout=5.0).read()


def kv_wait(addr, port, key, timeout=60.0, poll=0.1, auth_key=None):
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = kv_get(addr, port, key, auth_key=auth_key)
        if v is not None:
            return v
        time.sleep(poll)
    raise TimeoutError(f"key {key} not published within {timeout}s")
