"""Middleman process: no orphans survive the launcher.

Rebuilds ``horovod/run/common/util/safe_shell_exec.py``: the reference
forks a *middleman* between launcher and training process so that when
the launcher dies — SIGKILL, machine reboot of the launch host, dropped
ssh — every descendant of the training command is terminated instead of
orphaning onto the machine. Detection rides a pipe: the launcher holds
the write end; when it exits for any reason the kernel closes it, the
middleman's blocking read returns EOF, and the middleman reaps the tree
(graceful SIGTERM, then SIGKILL after a grace period).

Differences from the reference realization: the middleman here is an
exec'd module (works over ssh, where fork() can't cross the wire), the
executor runs in its own session so one ``killpg`` catches the whole
group, and escapees that called setsid() themselves are found by walking
``/proc`` (the image has no psutil).

Modes:

* ``python -m horovod_tpu.run.safe_exec <death_fd> -- cmd...`` — local:
  ``death_fd`` is the inherited read end of the launcher's pipe.
* ``python -m horovod_tpu.run.safe_exec --watch-stdin -- cmd...`` —
  remote: EOF on stdin (the ssh connection dying) triggers the reap;
  composes with the secret-over-stdin prefix, which consumes only the
  first line.
"""

import os
import signal
import subprocess
import sys
import threading
import time

GRACEFUL_TERMINATION_TIME_S = 5.0


def _children_of(pid_set):
    """Direct children of any pid in ``pid_set``, via /proc (PPid)."""
    kids = set()
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as f:
                stat = f.read()
            # field 4 (after the parenthesized comm, which may contain
            # spaces) is ppid
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            continue
        if ppid in pid_set:
            kids.add(int(entry))
    return kids


def descendants(pid):
    """All live descendants of ``pid``, recursively (psutil-free)."""
    seen = {pid}
    frontier = {pid}
    while frontier:
        frontier = _children_of(frontier) - seen
        seen |= frontier
    seen.discard(pid)
    return seen


def terminate_tree(proc, grace=GRACEFUL_TERMINATION_TIME_S, known=None):
    """SIGTERM the executor's whole tree, wait, then SIGKILL whatever is
    left — including processes that re-setsid'd out of the group
    (reference ``terminate_executor_shell_and_children``).

    ``known`` is a set of pids observed as descendants earlier (see the
    tracker in run_middleman): a /proc ppid walk alone cannot find an
    escapee whose intermediate parent already exited (it reparented to
    init), but the tracker saw it while the parent lived."""
    known = set(known or ())
    if proc.poll() is not None and not (descendants(proc.pid) | known):
        return
    tree = descendants(proc.pid) | known | {proc.pid}
    try:
        os.killpg(proc.pid, signal.SIGTERM)  # executor leads its session
    except ProcessLookupError:
        pass
    for p in tree:
        try:
            os.kill(p, signal.SIGTERM)
        except ProcessLookupError:
            pass
    deadline = time.time() + grace
    while time.time() < deadline:
        if proc.poll() is not None and not _alive_set(tree - {proc.pid}):
            break
        time.sleep(0.1)
    tree = descendants(proc.pid) | _alive_set(known) | {proc.pid}
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    for p in tree:
        try:
            os.kill(p, signal.SIGKILL)
        except ProcessLookupError:
            pass


def _alive_set(pids):
    out = set()
    for p in pids:
        try:
            os.kill(p, 0)
            out.add(p)
        except OSError:
            pass
    return out


def run_middleman(command, death_fd=None, watch_stdin=False, env=None):
    """Spawn ``command`` in its own session and guard it; returns the
    command's exit code (negative signal → 128+sig, shell style)."""
    proc = subprocess.Popen(command, env=env, start_new_session=True)
    fired = threading.Event()

    # remember every descendant ever seen, so escapees whose parent died
    # (reparented to init, invisible to a ppid walk) still get reaped
    known = set()
    known_lock = threading.Lock()

    def _track():
        while proc.poll() is None and not fired.is_set():
            seen = descendants(proc.pid)
            with known_lock:
                known.update(seen)
            time.sleep(1.0)

    threading.Thread(target=_track, daemon=True).start()

    def _reap():
        if not fired.is_set():
            fired.set()
            with known_lock:
                snapshot = set(known)
            terminate_tree(proc, known=snapshot)

    def _on_signal(signum, frame):
        threading.Thread(target=_reap, daemon=True).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    def _watch(fd):
        try:
            while os.read(fd, 1):
                pass  # discard until EOF
        except OSError:
            pass
        _reap()  # launcher is gone

    if death_fd is not None:
        threading.Thread(target=_watch, args=(death_fd,),
                         daemon=True).start()
    if watch_stdin:
        threading.Thread(target=_watch, args=(sys.stdin.fileno(),),
                         daemon=True).start()

    rc = proc.wait()
    return 128 - rc if rc < 0 else rc


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if "--" not in argv:
        print("usage: safe_exec (<death_fd>|--watch-stdin) -- cmd...",
              file=sys.stderr)
        return 2
    split = argv.index("--")
    opts, command = argv[:split], argv[split + 1:]
    death_fd = None
    watch_stdin = False
    for o in opts:
        if o == "--watch-stdin":
            watch_stdin = True
        else:
            death_fd = int(o)
    return run_middleman(command, death_fd=death_fd,
                         watch_stdin=watch_stdin)


if __name__ == "__main__":
    sys.exit(main())
