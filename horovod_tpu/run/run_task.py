"""Worker entry for the programmatic ``run(fn)`` API (reference:
``horovod/run/run_task.py`` + ``task_fn``): fetch the pickled function
from the launcher's KV server, execute under the env contract, publish the
result."""

import os
import pickle
import sys

from horovod_tpu.run.rendezvous import kv_put, kv_wait
from horovod_tpu.run.task_exec import exec_and_publish

try:
    import cloudpickle as _pickler  # noqa: F401
except ImportError:  # pragma: no cover
    pass  # plain pickle.loads handles cloudpickle payloads it can import


def main():
    addr = os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
    port = int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
    rank = int(os.environ["HOROVOD_RANK"])
    fn, args, kwargs = pickle.loads(
        kv_wait(addr, port, "runfunc/func", timeout=60))
    ok = exec_and_publish(
        fn, args, kwargs,
        lambda payload: kv_put(addr, port, f"runfunc/result/{rank}",
                               payload))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
