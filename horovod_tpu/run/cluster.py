"""Programmatic cluster integration: run ``fn`` on pre-existing executors.

Rebuilds the role of the reference Spark integration
(``horovod/spark/__init__.py:101-236``): the cluster (Spark, or any
scheduler) owns N already-placed task slots; we cannot spawn processes
where we like, so instead each cluster task *calls us back*:

1. the driver creates a per-run secret + signed KV server and ships the
   pickled ``fn`` into it,
2. a pluggable :class:`ClusterBackend` starts ``cluster_task`` in each
   executor (Spark: one per partition; tests: local subprocesses),
3. every task registers its NIC map + host hash and ring-probes its
   successor (reusing run/discovery.py — the same protocol the reference
   shares between ``horovod.run`` and ``horovod.spark``),
4. the driver groups task indices by host hash, barrel-shifts so index 0
   lands on the first host, and assigns **contiguous ranks per host**
   (reference ``spark/__init__.py:190-203``) — that's what makes
   hierarchical/ICI-local collectives line up with physical placement,
5. each task receives its env assignment (rank/local_rank/cross_rank +
   controller + rendezvous + secret), executes ``fn``, and puts the
   result back; the driver returns results in rank order.

The compute path inside ``fn`` is the ordinary horovod_tpu one (compiled
XLA collectives on TPU, native host core for CPU tensors) — the cluster
layer only decides *where processes already live* and *who gets which
rank*.
"""

import json
import os
import pickle
import sys

from horovod_tpu.run import allocation
from horovod_tpu.run import secret as _secret
from horovod_tpu.run import task_exec
from horovod_tpu.run.discovery import DriverService, TaskAgent
from horovod_tpu.run.rendezvous import (KVStoreServer, kv_get, kv_put,
                                        kv_wait)

try:
    import cloudpickle as _pickler
except ImportError:  # pragma: no cover
    _pickler = pickle

HOST_SALT_ENV = "HOROVOD_HOSTHASH_SALT"  # tests: fake distinct hosts


class ClusterBackend:
    """Something that can start ``num_tasks`` callbacks on a cluster.

    ``start_tasks(num_tasks, ctx)`` must arrange for
    ``cluster_task(index, num_tasks, ctx)`` to run in ``num_tasks``
    separate processes (one per executor slot). ``ctx`` is a small
    JSON-safe dict (KV address/port + hex key)."""

    def start_tasks(self, num_tasks, ctx):
        raise NotImplementedError

    def alive(self):
        """False once any task died abnormally (fails the run fast)."""
        return True

    def wait(self):
        pass

    def cancel(self):
        pass


class LocalProcessBackend(ClusterBackend):
    """Fake cluster for tests and single-machine use: each 'executor' is
    a local subprocess; ``host_salts`` simulates distinct hosts for the
    host-hash grouping (the reference tests fake clusters the same way,
    test/test_spark.py)."""

    def __init__(self, host_salts=None, env=None):
        self._salts = host_salts or {}
        self._env = env or {}
        self._procs = []

    def start_tasks(self, num_tasks, ctx):
        from horovod_tpu.run import launcher
        for i in range(num_tasks):
            env = dict(os.environ)
            env.update(self._env)
            env[_secret.SECRET_ENV] = ctx["key"]
            if i in self._salts:
                env[HOST_SALT_ENV] = self._salts[i]
            env["PYTHONPATH"] = launcher.repo_pythonpath(env)
            import subprocess
            self._procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.cluster_task",
                 str(i), str(num_tasks), ctx["kv_addr"],
                 str(ctx["kv_port"])], env=env))

    def alive(self):
        return not any(p.poll() not in (None, 0) for p in self._procs)

    def wait(self):
        for p in self._procs:
            p.wait()

    def cancel(self):
        for p in self._procs:
            if p.poll() is None:
                p.kill()


class SparkBackend(ClusterBackend):
    """Spark shim: one horovod task per Spark partition via
    ``mapPartitionsWithIndex`` (reference ``spark/__init__.py:72-99``).
    Requires an active SparkContext; runs the Spark job on a thread and
    relies on Spark RPC encryption to protect the key in transit, as the
    reference does. Exercised end-to-end against a stub SparkContext
    (tests/test_cluster.py — threads for partitions, the same shape the
    reference's test_spark.py gets from a local SparkSession); the full
    subprocess protocol underneath is covered by LocalProcessBackend
    tests."""

    def __init__(self, spark_context=None):
        if spark_context is None:
            import pyspark
            spark_context = pyspark.SparkContext._active_spark_context
        if spark_context is None:
            raise RuntimeError("no active SparkContext; start a PySpark "
                               "session before horovod_tpu.spark.run()")
        self._sc = spark_context
        self._thread = None
        self._error = []

    def start_tasks(self, num_tasks, ctx):
        import threading

        def _mapper(index, _it):
            # reraise_control_flow=False: under Spark a task EXCEPTION
            # means automatic task RETRY — which would re-run the whole
            # user fn against a completed rendezvous. cluster_task
            # swallows ONLY the control flow exec_and_publish has
            # already published (the launcher still raises on the
            # payload); an interrupt during rendezvous setup — nothing
            # published yet — still propagates and fails the job fast.
            yield cluster_task(index, num_tasks, ctx,
                               reraise_control_flow=False)

        def _run():
            try:
                self._sc.range(0, num_tasks, numSlices=num_tasks) \
                    .mapPartitionsWithIndex(_mapper).collect()
            # hvd-lint: disable=HVD-EXCEPT -- surfaces via alive()/wait(); the backend thread must not die
            except Exception as e:  # surfaces via alive()
                self._error.append(e)

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()

    def alive(self):
        return not self._error

    def wait(self):
        if self._thread:
            self._thread.join()
        if self._error:
            raise self._error[0]

    def cancel(self):
        self._sc.cancelAllJobs()


def cluster_task(index, num_tasks, ctx, reraise_control_flow=True):
    """Task-side protocol, runs inside a cluster executor.

    ``reraise_control_flow``: whether a KeyboardInterrupt/SystemExit
    escaping the user fn propagates after its failure payload is
    published. True for subprocess backends (process death keeps the
    signal's semantics); False for schedulers like Spark where a task
    exception means automatic retry — the one case where "swallow
    after publishing" is the correct plane semantic."""
    key = _secret.decode_key(ctx["key"])
    os.environ[_secret.SECRET_ENV] = ctx["key"]
    kv_addr, kv_port = ctx["kv_addr"], int(ctx["kv_port"])
    agent = TaskAgent(index, num_tasks, kv_addr, kv_port, key,
                      host_salt=os.environ.get(HOST_SALT_ENV))
    try:
        agent.register()
        agent.run_ring_probe(timeout=ctx.get("timeout", 600))
        agent.common_interfaces(timeout=ctx.get("timeout", 600))
        assign = json.loads(kv_wait(kv_addr, kv_port,
                                    f"cluster/assign/{index}",
                                    timeout=ctx.get("timeout", 600),
                                    auth_key=key))
    finally:
        agent.shutdown()
    os.environ.update({k: str(v) for k, v in assign.items()})
    rank = int(assign["HOROVOD_RANK"])
    fn, args, kwargs = _pickler.loads(
        kv_wait(kv_addr, kv_port, "runfunc/func", auth_key=key))
    try:
        task_exec.exec_and_publish(
            fn, args, kwargs,
            lambda payload: kv_put(kv_addr, kv_port,
                                   f"runfunc/result/{rank}", payload,
                                   auth_key=key))
    except BaseException:
        # only exec_and_publish's re-raised CONTROL FLOW reaches here —
        # its payload is already published, and plain Exceptions were
        # packaged inside it (never re-raised)
        if reraise_control_flow:
            raise
    return rank


def run_on_cluster(fn, args=(), kwargs=None, num_proc=2, backend=None,
                   start_timeout=600, kv_host="0.0.0.0", kv_addr=None,
                   extra_env=None):
    """Run ``fn`` across ``num_proc`` cluster executors; returns per-rank
    results in rank order (the reference's ``horovod.spark.run``
    contract)."""
    kwargs = kwargs or {}
    backend = backend or LocalProcessBackend()
    key = _secret.make_secret_key()
    kv = KVStoreServer(host=kv_host, auth_key=key)
    kv_port = kv.start()
    if kv_addr is None:
        from horovod_tpu.run import launcher
        kv_addr = ("127.0.0.1" if isinstance(backend, LocalProcessBackend)
                   else launcher.this_host_addr())
    try:
        kv.put("runfunc/func", _pickler.dumps((fn, args, kwargs)))
        ctx = {"kv_addr": kv_addr, "kv_port": kv_port,
               "key": _secret.encode_key(key), "timeout": start_timeout}
        backend.start_tasks(num_proc, ctx)

        driver = DriverService(num_proc, kv_addr, kv_port, key,
                               liveness=backend.alive)
        regs = driver.wait_for_registrations(timeout=start_timeout)
        common = driver.wait_for_probes(timeout=start_timeout)
        if not common:
            raise RuntimeError(
                "no common task-to-task interface across executors: "
                + str({i: list(r["addresses"]) for i, r in regs.items()}))

        # host-hash grouping; barrel-shift so index 0's host comes first
        # (reference spark/__init__.py:190-196) → index 0 becomes rank 0
        groups = driver.host_hash_indices(regs)
        hashes = sorted(groups)
        while 0 not in groups[hashes[0]]:
            hashes = hashes[1:] + hashes[:1]
        ranks_to_indices = [i for h in hashes for i in groups[h]]

        # contiguous ranks per host: reuse the launcher's slot math with
        # host-hash pseudo-hostnames
        hosts = [allocation.HostSlots(h, len(groups[h])) for h in hashes]
        slots = allocation.allocate(hosts, num_proc)

        controller_idx = ranks_to_indices[0]
        controller_ip = regs[controller_idx]["addresses"][common[0]][0][0]
        for rank, index in enumerate(ranks_to_indices):
            s = slots[rank]
            # each task advertises its OWN address on the first common
            # interface; the controller lives with rank 0
            own_ip = regs[index]["addresses"][common[0]][0][0]
            assign = {
                "HOROVOD_RANK": s.rank, "HOROVOD_SIZE": s.size,
                "HOROVOD_LOCAL_RANK": s.local_rank,
                "HOROVOD_LOCAL_SIZE": s.local_size,
                "HOROVOD_CROSS_RANK": s.cross_rank,
                "HOROVOD_CROSS_SIZE": s.cross_size,
                "HOROVOD_CONTROLLER_ADDR": controller_ip,
                "HOROVOD_CONTROLLER_PORT": 0,
                "HOROVOD_HOSTNAME": own_ip,
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": kv_addr,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": kv_port,
                "HOROVOD_COMMON_INTERFACES": ",".join(common),
            }
            if extra_env:
                assign.update(extra_env)
            kv.put(f"cluster/assign/{index}", json.dumps(assign).encode())

        results = []
        for rank in range(num_proc):
            # same liveness discipline as the discovery phase: a dead
            # executor fails the run now, not after start_timeout
            import time as _time
            deadline = _time.time() + start_timeout
            payload = None
            while _time.time() < deadline:
                payload = kv_get(kv_addr, kv_port,
                                 f"runfunc/result/{rank}", auth_key=key)
                if payload is not None:
                    break
                if not backend.alive():
                    raise RuntimeError(
                        f"a cluster executor died before rank {rank} "
                        f"reported its result")
                _time.sleep(0.2)
            if payload is None:
                raise TimeoutError(
                    f"rank {rank} result not published within "
                    f"{start_timeout}s")
            ok, value = pickle.loads(payload)
            if not ok:
                raise RuntimeError(f"rank {rank} raised:\n{value}")
            results.append(value)
        backend.wait()
        return results
    except BaseException:
        backend.cancel()
        raise
    finally:
        kv.stop()
