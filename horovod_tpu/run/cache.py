"""Launcher pre-flight cache (reference: ``run/util/cache.py``).

Multi-host launches re-probe NIC reachability on every invocation even
though the answer only changes when the cluster does. The reference
caches initialization-check results for 60 minutes under ``~/.horovod``
(``--disable-cache`` skips it); this is the same contract with JSON
instead of cloudpickle (the cached values are plain strings/lists — no
reason to deserialize executable pickles from a shared home directory).
"""

import json
import os
import tempfile
import time

DEFAULT_DIR = os.path.expanduser("~/.horovod_tpu")
DEFAULT_TTL = 60 * 60  # the reference's 60-minute staleness threshold


class Cache:
    """A tiny persistent {key: (timestamp, value)} store.

    Corrupt or unreadable cache files are treated as empty (a cache must
    never be able to fail a launch)."""

    def __init__(self, folder=DEFAULT_DIR, ttl=DEFAULT_TTL):
        self._path = os.path.join(folder, "cache.json")
        self._ttl = ttl

    def _load(self):
        try:
            with open(self._path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def get(self, key):
        """The cached value for ``key``, or None when absent/expired."""
        entry = self._load().get(key)
        if not entry:
            return None
        ts, value = entry
        if time.time() - ts > self._ttl:
            return None
        return value

    def put(self, key, value):
        data = self._load()
        data[key] = (time.time(), value)
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self._path))
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._path)  # atomic, like checkpoint writes
        except OSError:
            pass  # caching is best-effort
