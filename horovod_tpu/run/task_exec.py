"""Shared task-body execution for the shipped-function executors
(``run_task.py`` — the programmatic ``run(fn)`` worker — and
``cluster.py::cluster_task`` — the cluster-executor callback).

Both must publish a ``(ok, value-or-traceback)`` payload to the
launcher's KV store no matter how ``fn`` ends: a worker that dies
silently parks the launcher on ``kv_wait`` until its timeout. But the
two planes used to disagree on *control-flow* exceptions, and the
cluster side swallowed them outright: ``except BaseException`` turned a
KeyboardInterrupt / SystemExit inside ``fn`` into a published failure
followed by a NORMAL task return — the executor reported a clean exit
to its scheduler and kept running, exactly the "rank told to die keeps
running" shape hvd-lint's HVD-EXCEPT pass exists to reject. The one
policy now lives here: publish first (the launcher must learn the
outcome either way), then re-raise anything that is not a plain
``Exception`` so the signal keeps its meaning.
"""

import pickle
import traceback


def exec_and_publish(fn, args, kwargs, publish):
    """Run ``fn(*args, **kwargs)`` and hand ``publish`` the pickled
    ``(ok, value)`` payload. Returns True on success, False when ``fn``
    raised an ordinary ``Exception`` (traceback published). Control
    flow — ``KeyboardInterrupt``/``SystemExit``/any non-``Exception``
    ``BaseException`` — is published as a failure and then RE-RAISED.
    """
    try:
        payload = pickle.dumps((True, fn(*args, **kwargs)))
    # hvd-lint: disable=HVD-EXCEPT -- failure IS the result: published to the launcher; control flow re-raises below
    except Exception:
        publish(pickle.dumps((False, traceback.format_exc())))
        return False
    except BaseException:
        # publish-then-reraise: the launcher stops waiting on this
        # rank, and the executor still dies with the interrupt's
        # semantics instead of reporting a clean exit
        publish(pickle.dumps((False, traceback.format_exc())))
        raise
    publish(payload)
    return True
