"""The hvdrun CLI (reference: ``horovod/run/run.py`` + ``bin/horovodrun``).

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 4 --config-file cfg.yaml python train.py
"""

import argparse
import os
import socket
import sys

from horovod_tpu.run import allocation, config_parser, launcher
from horovod_tpu.run import cache as run_cache
from horovod_tpu.run import secret as _secret
from horovod_tpu.run.discovery import DriverService
from horovod_tpu.run.rendezvous import KVStoreServer


def _version():
    from horovod_tpu import __version__
    return f"hvdrun (horovod_tpu) {__version__}"


def check_build():
    """Print what this build supports (reference: ``horovodrun
    --check-build``, run.py:407 — its framework/controller/tensor-op
    checkboxes, mapped to this framework's planes and adapters)."""
    import importlib.util

    def have(mod):
        # full meta-path probe (editable installs register meta_path
        # finders that PathFinder alone would miss), without importing
        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    from horovod_tpu import _core
    core_ok = _core.core_available()
    lines = [
        _version(),
        "",
        "Available frameworks:",
        f"    [{'X' if have('jax') else ' '}] JAX (compiled XLA data plane)",
        f"    [{'X' if have('torch') else ' '}] PyTorch",
        f"    [{'X' if have('tensorflow') else ' '}] TensorFlow",
        f"    [{'X' if have('mxnet') else ' '}] MXNet",
        "",
        "Available controllers:",
        f"    [{'X' if core_ok else ' '}] TCP (native host core)",
        "",
        "Available tensor operations:",
        f"    [{'X' if have('jax') else ' '}] XLA collectives (ICI/DCN)",
        f"    [{'X' if core_ok else ' '}] host ring collectives "
        "(allreduce/allgatherv/broadcast/alltoall/reducescatter/Adasum)",
    ]
    try:
        print("\n".join(lines))
    except BrokenPipeError:  # `hvdrun -cb | head` closing early is fine
        pass


def build_parser():
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu training job "
                    "(one process per slot; no MPI required).")
    p.add_argument("-v", "--version", action="version",
                   version=_version(),
                   help="show the horovod_tpu version and exit")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print which frameworks/adapters and core "
                        "features this build supports, then exit "
                        "(reference: horovodrun --check-build)")
    p.add_argument("-np", "--num-proc", type=int,
                   help="total number of training processes")
    p.add_argument("-H", "--hosts", default=None,
                   help='host slots, e.g. "h1:4,h2:4" (default: localhost)')
    p.add_argument("--hostfile", default=None,
                   help="file with lines 'hostname slots=N'")
    p.add_argument("-p", "--ssh-port", type=int, default=None)
    p.add_argument("--start-timeout", type=int, default=600)
    p.add_argument("--output-dir", default=None,
                   help="write per-rank logs to this directory")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file", default=None,
                   help="YAML file providing any of the tuning params")
    p.add_argument("--jax-coordinator", action="store_true",
                   help="also start a jax.distributed coordinator so the "
                        "workers form one global TPU mesh")
    p.add_argument("--spmd-procs", type=int, default=None, metavar="N",
                   help="launch N real jax.distributed processes that "
                        "form ONE logical (dcn, data) mesh spanning all "
                        "of them (implies --jax-coordinator; defaults "
                        "-np to N) — docs/SCALING.md")
    p.add_argument("--spmd-local-devices", type=int, default=None,
                   metavar="K",
                   help="virtual CPU devices each --spmd-procs worker "
                        "contributes to the mesh (sets "
                        "HOROVOD_SPMD_LOCAL_DEVICES; the CPU stand-in "
                        "for a TPU host's local chips)")
    p.add_argument("--network-interface", "--nic", dest="nic", default=None,
                   help="restrict control-plane traffic to this interface "
                        "(skips automatic interface discovery)")
    p.add_argument("--no-interface-discovery", action="store_true",
                   help="skip the multi-host NIC discovery pre-flight")

    el = p.add_argument_group(
        "elastic (reference: horovodrun --min-np/--max-np/"
        "--host-discovery-script)")
    el.add_argument("--min-np", type=int, default=None,
                    help="minimum worker count: the job keeps running as "
                         "long as this many slots remain after failures/"
                         "blacklisting (enables elastic mode)")
    el.add_argument("--max-np", type=int, default=None,
                    help="maximum worker count when discovery reports "
                         "more slots than needed")
    el.add_argument("--host-discovery-script", default=None,
                    help="executable printing the current 'host:slots' "
                         "set, one per line; polled for membership "
                         "changes (enables elastic mode)")
    el.add_argument("--elastic-poll-interval", type=float, default=2.0,
                    help="seconds between host-discovery polls")

    tune = p.add_argument_group("tuning (sets HOROVOD_* env)")
    tune.add_argument("--fusion-threshold-mb", type=int, default=None)
    tune.add_argument("--cycle-time-ms", type=float, default=None)
    tune.add_argument("--cache-capacity", type=int, default=None)
    tune.add_argument("--disable-cache", action="store_true",
                      help="disable caching: the launcher's pre-flight "
                           "NIC-discovery cache (reference "
                           "--disable-cache semantics; forces a fresh "
                           "probe) AND the runtime response cache "
                           "(HOROVOD_CACHE_CAPACITY=0). To only refresh "
                           "the pre-flight cache, delete "
                           "~/.horovod_tpu/cache.json")
    tune.add_argument("--hierarchical-allreduce", action="store_true")
    tune.add_argument("--hierarchical-allgather", action="store_true")
    tune.add_argument("--autotune", action="store_true")
    tune.add_argument("--autotune-log-file", default=None)
    tune.add_argument("--autotune-warmup-samples", type=int, default=None)
    tune.add_argument("--autotune-steps-per-sample", type=int, default=None)
    tune.add_argument("--autotune-sample-repeats", type=int, default=None)
    tune.add_argument("--autotune-bayes-opt-max-samples", type=int,
                      default=None)
    tune.add_argument("--autotune-gaussian-process-noise", type=float,
                      default=None)
    tune.add_argument("--timeline-filename", default=None)
    tune.add_argument("--timeline-mark-cycles", action="store_true")
    tune.add_argument("--metrics-port", type=int, default=None,
                      help="base port for the per-rank Prometheus "
                           "/metrics + /healthz + /profile endpoints "
                           "(telemetry plane): rank with local_rank L on "
                           "each host serves on metrics-port + L; 0 = "
                           "each rank binds an ephemeral port. Scrape "
                           "targets are printed at launch "
                           "(docs/OBSERVABILITY.md)")
    tune.add_argument("--metrics-addr", default=None,
                      help="bind address for the metrics endpoints "
                           "(default 127.0.0.1; the endpoints are "
                           "unauthenticated — see the security note in "
                           "docs/OBSERVABILITY.md before exposing them)")
    tune.add_argument("--no-stall-check", action="store_true")
    tune.add_argument("--stall-warning-time-seconds", type=float,
                      default=None)
    tune.add_argument("--stall-shutdown-time-seconds", type=float,
                      default=None)
    tune.add_argument("--log-level", default=None,
                      choices=["trace", "debug", "info", "warning",
                               "error", "fatal"])

    p.add_argument("--chaos", metavar="SPEC", default=None,
                   help="arm a seeded fault injector against this job's "
                        "worker processes (chaos soak, docs/ELASTIC.md): "
                        "SPEC is a JSON plan file or an inline "
                        "'seed=7,interval=2.5,kinds=sigterm+sigkill,"
                        "count=6' knob list; kinds are sigterm/sigkill/"
                        "stall/slow_disk. In elastic mode the remaining "
                        "injections retarget each new epoch's workers")
    p.add_argument("--doctor", metavar="LOGDIR", default=None,
                   help="aggregate the flight-recorder dumps "
                        "(flightrec.rank*.json) under LOGDIR into one "
                        "hang/crash report (dead ranks, last common "
                        "collective_seq, the collective each straggler "
                        "is parked in, probable cause), then exit — same "
                        "as python -m horovod_tpu.diag.doctor")
    p.add_argument("--no-doctor", action="store_true",
                   help="do not auto-run the doctor when a job exits "
                        "non-zero with flight-recorder dumps present")
    p.add_argument("--goodput-report", metavar="LOGDIR", default=None,
                   help="aggregate the goodput-ledger dumps "
                        "(goodput.rank*.json, written next to the "
                        "flight-recorder dumps at shutdown) under LOGDIR "
                        "into the end-of-run time-attribution report "
                        "(per-rank and fleet-wide phase breakdown, "
                        "dominant time sink), then exit — same as "
                        "hvd-doctor perf / python -m "
                        "horovod_tpu.telemetry.report")
    p.add_argument("--merge-timeline", metavar="OUT", default=None,
                   help="merge per-rank Chrome trace files into one "
                        "Perfetto-loadable trace with aligned clocks and "
                        "per-rank pids, then exit: hvdrun "
                        "--merge-timeline merged.json trace.rank*.json "
                        "(same as python -m horovod_tpu.telemetry.merge)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, e.g. python train.py")
    return p


def parse_args(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]  # `hvdrun -np 4 -- python ...`
    if args.config_file:
        defaults = {a.dest: a.default for a in parser._actions}
        config_parser.load_config_file(args.config_file, args, defaults)
    args.elastic = _validate_elastic_args(parser, args)
    if args.spmd_procs is not None:
        if args.spmd_procs < 1:
            parser.error(f"--spmd-procs must be >= 1 "
                         f"(got {args.spmd_procs})")
        if args.elastic:
            parser.error("--spmd-procs is fixed-size: the "
                         "jax.distributed world cannot resize mid-job; "
                         "drop the elastic flags")
        if args.num_proc is None:
            args.num_proc = args.spmd_procs
        elif args.num_proc != args.spmd_procs:
            parser.error(f"--spmd-procs ({args.spmd_procs}) must equal "
                         f"-np ({args.num_proc}): one jax.distributed "
                         "process per launched rank")
        args.jax_coordinator = True
    elif args.spmd_local_devices is not None:
        parser.error("--spmd-local-devices requires --spmd-procs")
    if args.chaos is not None:
        from horovod_tpu.chaos import parse_spec
        try:
            parse_spec(args.chaos)  # reject malformed specs pre-launch
        except ValueError as e:
            parser.error(str(e))
    # after the config overlay: the YAML may supply num-proc
    if (not args.check_build and not args.elastic
            and args.merge_timeline is None and args.doctor is None
            and args.goodput_report is None
            and args.num_proc is None):
        parser.error("-np/--num-proc is required")
    return args


def _validate_elastic_args(parser, args):
    """Reject invalid elastic flag combinations with actionable errors;
    returns True when the job is elastic (any elastic flag present) and
    normalizes min/max/np defaults."""
    elastic = (args.min_np is not None or args.max_np is not None
               or args.host_discovery_script is not None)
    if not elastic:
        return False
    if args.host_discovery_script is not None:
        if args.hosts or args.hostfile:
            parser.error("--host-discovery-script replaces -H/--hostfile: "
                         "the script is the source of truth for the host "
                         "set; pass one or the other")
        script = args.host_discovery_script
        if not os.path.isfile(script):
            parser.error(f"--host-discovery-script {script!r} does not "
                         "exist")
        if not os.access(script, os.X_OK):
            parser.error(f"--host-discovery-script {script!r} is not "
                         "executable (chmod +x it)")
    if args.min_np is None:
        if args.num_proc is None:
            parser.error("elastic mode requires --min-np (or -np, which "
                         "defaults --min-np)")
        args.min_np = args.num_proc
    if args.min_np < 1:
        parser.error(f"--min-np must be >= 1 (got {args.min_np})")
    if args.max_np is not None and args.max_np < args.min_np:
        parser.error(f"--max-np ({args.max_np}) must be >= --min-np "
                     f"({args.min_np})")
    if args.num_proc is not None:
        if args.num_proc < args.min_np:
            parser.error(f"-np ({args.num_proc}) must be >= --min-np "
                         f"({args.min_np})")
        if args.max_np is not None and args.num_proc > args.max_np:
            parser.error(f"-np ({args.num_proc}) must be <= --max-np "
                         f"({args.max_np})")
    else:
        args.num_proc = args.min_np
    return True


def free_port():
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _check_metrics_ports(args, slots):
    """Per-rank metrics ports (base + local_rank), collision-checked the
    same way the service ports are (a bind probe — only meaningful for
    local slots; remote hosts fail loudly at worker bind time). Prints
    the scrape targets so an operator can paste them into a Prometheus
    config. Returns the (host, port) target list."""
    if args.metrics_port is None:
        return []
    targets = [(s.hostname, args.metrics_port + s.local_rank)
               for s in slots]
    if args.metrics_port > 0:
        for host, port in targets:
            if host not in launcher.LOCAL_HOSTS:
                continue
            probe = socket.socket()
            try:
                probe.bind((args.metrics_addr or "127.0.0.1", port))
            except OSError as e:
                raise RuntimeError(
                    f"hvdrun: metrics port {port} (base "
                    f"{args.metrics_port} + local_rank) is not bindable "
                    f"on {host}: {e}; pick another --metrics-port")
            finally:
                probe.close()
        print("hvdrun: metrics scrape targets: "
              + ", ".join(f"{h}:{p}" for h, p in targets),
              file=sys.stderr)
    else:
        print("hvdrun: metrics on ephemeral ports (base 0); check each "
              "rank's log for its bound port", file=sys.stderr)
    return targets


def _discover_interfaces(hosts, auth_key, kv_port, args, extra_env):
    """Multi-host pre-flight (reference gloo_run driver/task services):
    run one task_fn per host, ring-probe, and return the interface names
    routable between every pair of adjacent hosts."""
    launcher_ip = launcher.this_host_addr()
    env = {_secret.SECRET_ENV: _secret.encode_key(auth_key),
           "PYTHONPATH": extra_env.get("PYTHONPATH",
                                       os.environ.get("PYTHONPATH", ""))}
    procs = []
    for idx, h in enumerate(hosts):
        cmd = [sys.executable, "-m", "horovod_tpu.run.task_fn",
               str(idx), str(len(hosts)), launcher_ip, str(kv_port),
               str(args.start_timeout)]
        procs.append(launcher.spawn(h.hostname, cmd, env,
                                    ssh_port=args.ssh_port))

    def _alive():  # a non-zero exit means ssh/startup failure
        return not any(p.poll() not in (None, 0) for p in procs)

    driver = DriverService(len(hosts), launcher_ip, kv_port, auth_key,
                           liveness=_alive)
    try:
        driver.wait_for_registrations(timeout=args.start_timeout)
        common = driver.wait_for_probes(timeout=args.start_timeout)
        if not common:
            raise RuntimeError(
                "interface discovery found NO interface routable across "
                "all hosts (interfaces must share a name on every host; "
                "NAT'ed paths are rejected)")
    except (TimeoutError, RuntimeError) as e:
        for p in procs:
            p.kill()
        raise RuntimeError(
            f"hvdrun: interface discovery failed: {e}\n"
            f"Check ssh connectivity and interface naming, or pass "
            f"--network-interface / --no-interface-discovery") from e
    for p in procs:
        p.wait()
    if args.verbose:
        print(f"hvdrun: common routable interfaces: {common}",
              file=sys.stderr)
    return common


def _nic_cache_key(hosts):
    """Cache key for the NIC pre-flight: keyed by the LAUNCHER host too —
    the elected set is an intersection over paths from this machine, so a
    shared home directory must not let launcher A serve launcher B's
    answer (ADVICE round 5)."""
    return ("nics:" + socket.gethostname() + ":"
            + ",".join(sorted({h.hostname for h in hosts})))


def _common_interfaces(args, hosts, discover_fn):
    """Same host set within the TTL -> same routable NICs: serve the
    pre-flight from the launcher cache (reference run/util/cache.py
    behavior; --disable-cache forces a fresh probe). Both the cached and
    the fresh path return ``sorted(common)`` so first and subsequent
    launches export identical HOROVOD_COMMON_INTERFACES."""
    cache_key = _nic_cache_key(hosts)
    nic_cache = run_cache.Cache()
    common = (None if getattr(args, "disable_cache", False)
              else nic_cache.get(cache_key))
    if common is None:
        common = sorted(discover_fn())
        nic_cache.put(cache_key, common)
    elif args.verbose:
        print(f"hvdrun: cached routable interfaces: {common}",
              file=sys.stderr)
    return common


def _host_list_from_args(args):
    """The -H / --hostfile / localhost-default host list (shared by the
    fixed-size and elastic launch paths)."""
    if args.hostfile:
        return allocation.parse_hostfile(args.hostfile)
    if args.hosts:
        return allocation.parse_hosts(args.hosts)
    return [allocation.HostSlots("localhost", args.num_proc)]


def _start_kv(all_local):
    """The launch-time KV server with the shared auth policy: multi-host
    runs get a per-run HMAC key and a network bind; all-local runs bind
    loopback unauthenticated (reference secret.py + network.py Wire).
    Returns ``(kv, auth_key, port)``."""
    auth_key = None if all_local else _secret.make_secret_key()
    kv = KVStoreServer(host="127.0.0.1" if all_local else "0.0.0.0",
                       auth_key=auth_key)
    return kv, auth_key, kv.start()


def _base_worker_env(args, auth_key, all_local, hosts, rendezvous_port):
    """extra_env shared by both launch paths: tuning knobs, the run
    secret, and HOROVOD_COMMON_INTERFACES (explicit --nic, or the cached
    / fresh NIC pre-flight for multi-host jobs)."""
    extra_env = config_parser.args_to_env(args)
    if auth_key is not None:
        extra_env[_secret.SECRET_ENV] = _secret.encode_key(auth_key)
    if args.nic:
        extra_env["HOROVOD_COMMON_INTERFACES"] = args.nic
    elif not all_local and hosts and not args.no_interface_discovery:
        common = _common_interfaces(
            args, hosts,
            lambda: _discover_interfaces(hosts, auth_key, rendezvous_port,
                                         args, extra_env))
        if common:
            extra_env["HOROVOD_COMMON_INTERFACES"] = ",".join(common)
    return extra_env


def _flightrec_dir(args, extra_env):
    """Where this run's flight-recorder dumps land (diag/): the
    --output-dir when given (launcher.launch plumbs it next to the rank
    logs), an explicitly exported HOROVOD_FLIGHTREC_DIR, else a
    run-scoped temp dir so the post-failure auto-doctor always has a
    place to look. Returns ``(dir, created_tmp_dir_or_None)``."""
    import tempfile
    if args.output_dir:
        return args.output_dir, None
    explicit = os.environ.get("HOROVOD_FLIGHTREC_DIR")
    if explicit:
        return explicit, None
    d = tempfile.mkdtemp(prefix="hvdrun_flightrec_")
    extra_env["HOROVOD_FLIGHTREC_DIR"] = d
    return d, d


def _maybe_doctor(args, dump_dir, multi_host=False):
    """Auto-run the desync doctor over this run's dumps after a failed
    job (opt out: --no-doctor): the report that names the dead rank and
    the collective the survivors are parked in, printed right where the
    operator is already looking. ``multi_host`` jobs only have the
    launcher host's dumps visible here, so missing ranks must not be
    read as dead — the caveat is printed and the no-dump verdict is
    left to an explicit doctor run over the collected dumps."""
    if getattr(args, "no_doctor", False) or not dump_dir:
        return
    try:
        from horovod_tpu.diag import doctor as doctor_mod
        if not doctor_mod.find_dumps(dump_dir):
            return
        print(f"hvdrun: flight-recorder dumps found in {dump_dir}; "
              "doctor report (suppress with --no-doctor):",
              file=sys.stderr)
        if multi_host:
            print("hvdrun: MULTI-HOST job — only this host's dumps are "
                  "visible below; ranks on other hosts may be wrongly "
                  "listed as DEAD. Collect each host's dump dir into one "
                  "place and rerun `hvdrun --doctor <dir>` for the real "
                  "verdict.", file=sys.stderr)
        doctor_mod.run(dump_dir, expected_size=args.num_proc,
                       stream=sys.stderr)
    # hvd-lint: disable=HVD-EXCEPT -- the doctor report must never mask the real failure
    except Exception as e:  # the report must never mask the real failure
        print(f"hvdrun: doctor failed: {e}", file=sys.stderr)


def _cleanup_tmp_flightrec(tmp_dir):
    """A clean run's temp dump dir (clean-exit dumps only) is noise —
    remove it; failed runs keep theirs (the doctor names the path)."""
    if not tmp_dir:
        return
    import shutil
    shutil.rmtree(tmp_dir, ignore_errors=True)


def _start_chaos(args):
    """Build (but do not arm) the fault injector for --chaos: the monkey
    starts its clock at the first ``attach()``, i.e. once workers exist."""
    if args.chaos is None:
        return None
    from horovod_tpu.chaos import ChaosMonkey, parse_spec
    return ChaosMonkey(parse_spec(args.chaos))


def _run(args):
    if not args.command:
        raise SystemExit("hvdrun: no training command given")
    if args.elastic:
        return _run_elastic(args)
    hosts = _host_list_from_args(args)
    slots = allocation.allocate(hosts, args.num_proc)

    # the native-core coordinator lives in rank 0's process on the first
    # host; port 0 = rank 0 binds an ephemeral port on ITS host and
    # publishes it through the rendezvous KV (services.py) — no launcher-
    # side probing that could collide on a remote machine
    controller_addr = slots[0].hostname
    if controller_addr in launcher.LOCAL_HOSTS:
        controller_addr = "127.0.0.1"
    controller_port = 0

    all_local = all(s.hostname in launcher.LOCAL_HOSTS for s in slots)
    kv, auth_key, rendezvous_port = _start_kv(all_local)
    extra_env = _base_worker_env(args, auth_key, all_local, hosts,
                                 rendezvous_port)
    if args.jax_coordinator:
        # probing is only sound for a local rank 0; remote gets a random
        # high port (collision unlikely, bind failure is loud)
        import random
        jport = (free_port() if controller_addr == "127.0.0.1"
                 else random.randint(23000, 43000))
        extra_env["HOROVOD_COORDINATOR_ADDR"] = f"{controller_addr}:{jport}"
    if args.spmd_procs is not None:
        extra_env["HOROVOD_SPMD_PROCS"] = str(args.spmd_procs)
        if args.spmd_local_devices:
            extra_env["HOROVOD_SPMD_LOCAL_DEVICES"] = \
                str(args.spmd_local_devices)

    _check_metrics_ports(args, slots)
    dump_dir, tmp_dump_dir = _flightrec_dir(args, extra_env)
    if args.verbose:
        print(f"hvdrun: launching {args.num_proc} processes: "
              f"{[ (s.rank, s.hostname, s.local_rank) for s in slots ]}",
              file=sys.stderr)
    job = launcher.launch(slots, args.command, controller_addr,
                          controller_port, rendezvous_port=rendezvous_port,
                          extra_env=extra_env, ssh_port=args.ssh_port,
                          output_dir=args.output_dir)
    monkey = _start_chaos(args)
    if monkey is not None:
        monkey.attach(job)
    try:
        job.wait()
        _cleanup_tmp_flightrec(tmp_dump_dir)
    except RuntimeError:
        _maybe_doctor(args, dump_dir, multi_host=not all_local)
        raise
    finally:
        if monkey is not None:
            monkey.stop()
        kv.stop()


def _run_elastic(args):
    """The elastic launch path: an ElasticDriver owns discovery,
    blacklisting and per-epoch rendezvous; each epoch launches
    ``args.command`` through the normal launcher with the elastic env
    contract on top (HOROVOD_ELASTIC / _EPOCH / _MIN_NP / _MAX_NP)."""
    from horovod_tpu.elastic.discovery import FixedHosts, ScriptDiscovery
    from horovod_tpu.elastic.driver import ElasticDriver, default_launch_fn

    if args.host_discovery_script:
        discovery = ScriptDiscovery(args.host_discovery_script)
    else:
        discovery = FixedHosts(_host_list_from_args(args))
    initial_hosts = discovery.find_available_hosts_and_slots()

    # dynamic membership may add remote hosts later, so only a fixed
    # all-local set gets the loopback-bound, unauthenticated KV
    all_local = (not args.host_discovery_script and
                 all(h in launcher.LOCAL_HOSTS for h in initial_hosts))
    kv, auth_key, rendezvous_port = _start_kv(all_local)
    # NIC pre-flight against the INITIAL host set; hosts that join later
    # are assumed to share the elected interface naming (docs/ELASTIC.md)
    initial_host_list = [allocation.HostSlots(h, s)
                         for h, s in sorted(initial_hosts.items())]
    extra_env = _base_worker_env(args, auth_key, all_local,
                                 initial_host_list, rendezvous_port)

    if args.metrics_port is not None and args.metrics_port > 0:
        print(f"hvdrun: elastic metrics base port {args.metrics_port}: "
              "each epoch's scrape targets are host:(base + local_rank) "
              "over that epoch's slot assignment", file=sys.stderr)

    # without an explicit --max-np the job never grows beyond -np: the
    # requested size is the ceiling, elasticity only rides out losses
    max_np = args.max_np if args.max_np is not None else args.num_proc
    driver = ElasticDriver(
        discovery, args.min_np, max_np=max_np, kv=kv,
        auth_key=auth_key, poll_interval=args.elastic_poll_interval,
        start_timeout=args.start_timeout)
    launch = default_launch_fn(
        args.command, controller_port=0,
        rendezvous_addr=("127.0.0.1" if all_local
                         else launcher.this_host_addr()),
        rendezvous_port=rendezvous_port, extra_env=extra_env,
        ssh_port=args.ssh_port, output_dir=args.output_dir,
        jax_coordinator=args.jax_coordinator)
    monkey = _start_chaos(args)
    if monkey is not None:
        inner_launch = launch

        def launch(slots, epoch, env):
            # retarget the remaining injections at THIS epoch's workers
            job = inner_launch(slots, epoch, env)
            monkey.attach(job)
            return job
    dump_dir, tmp_dump_dir = _flightrec_dir(args, extra_env)
    try:
        epochs = driver.run_job(launch)
        if args.verbose:
            print(f"hvdrun: elastic job completed after {epochs} epoch(s)",
                  file=sys.stderr)
        _cleanup_tmp_flightrec(tmp_dump_dir)
    except (RuntimeError, TimeoutError):
        _maybe_doctor(args, dump_dir, multi_host=not all_local)
        raise
    finally:
        if monkey is not None:
            monkey.stop()
        driver.stop()
        kv.stop()


def main(argv=None):
    args = parse_args(argv)
    if args.check_build:
        check_build()
        return 0
    if args.doctor is not None:
        from horovod_tpu.diag import doctor as doctor_mod
        argv_d = [args.doctor]
        if args.num_proc:
            argv_d += ["--expected-size", str(args.num_proc)]
        return doctor_mod.main(argv_d)
    if args.goodput_report is not None:
        from horovod_tpu.telemetry import report as report_mod
        return report_mod.main([args.goodput_report])
    if args.merge_timeline is not None:
        from horovod_tpu.telemetry import merge as merge_mod
        traces = [c for c in args.command if c != "--"]
        if not traces:
            print("hvdrun: --merge-timeline needs the per-rank trace "
                  "files as the command arguments", file=sys.stderr)
            return 1
        return merge_mod.main(["-o", args.merge_timeline] + traces)
    try:
        _run(args)
    except (RuntimeError, TimeoutError) as e:
        print(str(e), file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 130
    return 0


if __name__ == "__main__":
    sys.exit(main())
