"""CLI/config-file knobs -> HOROVOD_* env mapping.

Rebuilds ``horovod/run/common/util/config_parser.py``: every tuning flag
maps onto the same env var the core reads (SURVEY.md §5.6 — three config
layers all converge on env vars).
"""

# arg name -> env var (reference config_parser.py constants)
ARG_TO_ENV = {
    "fusion_threshold_mb": "HOROVOD_FUSION_THRESHOLD",
    "cycle_time_ms": "HOROVOD_CYCLE_TIME",
    "cache_capacity": "HOROVOD_CACHE_CAPACITY",
    "hierarchical_allreduce": "HOROVOD_HIERARCHICAL_ALLREDUCE",
    "hierarchical_allgather": "HOROVOD_HIERARCHICAL_ALLGATHER",
    "timeline_filename": "HOROVOD_TIMELINE",
    "timeline_mark_cycles": "HOROVOD_TIMELINE_MARK_CYCLES",
    "no_stall_check": "HOROVOD_STALL_CHECK_DISABLE",
    "stall_warning_time_seconds": "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "stall_shutdown_time_seconds": "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "autotune": "HOROVOD_AUTOTUNE",
    "autotune_log_file": "HOROVOD_AUTOTUNE_LOG",
    "autotune_warmup_samples": "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
    "autotune_steps_per_sample": "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
    "autotune_sample_repeats": "HOROVOD_AUTOTUNE_SAMPLE_REPEATS",
    "autotune_bayes_opt_max_samples":
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
    "autotune_gaussian_process_noise":
        "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
    "log_level": "HOROVOD_LOG_LEVEL",
    # telemetry plane: the launcher value is the BASE port; each rank
    # serves on base + local_rank (run/launcher.py slot_env)
    "metrics_port": "HOROVOD_METRICS_PORT",
    "metrics_addr": "HOROVOD_METRICS_ADDR",
}


def args_to_env(args):
    """Build the env-var dict from parsed args (set_env_from_args)."""
    env = {}
    for arg, var in ARG_TO_ENV.items():
        val = getattr(args, arg, None)
        # identity checks: 0/0.0 are legitimate explicit values (0 == False)
        if val is None or val is False:
            continue
        if arg == "fusion_threshold_mb":
            val = int(val) * 1024 * 1024
        if val is True:
            val = "1"
        env[var] = str(val)
    if getattr(args, "disable_cache", False):  # reference --disable-cache
        env["HOROVOD_CACHE_CAPACITY"] = "0"
    return env


def load_config_file(path, args, parser_defaults):
    """Overlay a YAML config file onto args that were left at their
    defaults (CLI wins over file, reference run.py:609-613)."""
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    flat = {}

    def _flatten(d, prefix=""):
        for k, v in d.items():
            key = (prefix + "_" + k if prefix else k).replace("-", "_")
            if isinstance(v, dict):
                _flatten(v, key)
            else:
                flat[key] = v

    _flatten(cfg)
    for key, val in flat.items():
        if hasattr(args, key) and getattr(args, key) == \
                parser_defaults.get(key):
            setattr(args, key, val)
    return args
