"""MXNet adapter (reference: ``horovod/mxnet/__init__.py:40-153``).

The Horovod MXNet contract over the native core's host data plane:
``DistributedOptimizer`` allreduces gradients inside ``update()``,
``DistributedTrainer`` (gluon) allreduces in ``_allreduce_grads``,
``broadcast_parameters`` syncs initial state from root.

MXNet is not part of this image's baked environment, so the module
import-gates: everything works when mxnet is installed, and the adapter
logic itself is exercised in-image against a numpy-backed stand-in
(``tests/test_mxnet_adapter.py`` — see README for what ran in-image).
"""

try:
    import mxnet as mx
except ImportError as e:  # pragma: no cover - mxnet absent in this image
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet, which is not installed. On "
        "TPU, prefer the JAX-native API (import horovod_tpu as hvd) — it "
        "is the compiled, first-class path.") from e

from horovod_tpu.basics import (cross_rank, cross_size, init,
                                is_initialized, local_rank, local_size,
                                mpi_threads_supported, rank, shutdown, size)
from horovod_tpu.mxnet.mpi_ops import (Adasum, Average, Max, Min, Sum,
                                       allgather, allgather_async,
                                       allreduce, allreduce_,
                                       allreduce_async, allreduce_async_,
                                       broadcast, broadcast_,
                                       broadcast_async, broadcast_async_)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "mpi_threads_supported",
    "Sum", "Average", "Adasum", "Min", "Max",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_",
    "DistributedOptimizer", "DistributedTrainer", "broadcast_parameters",
]


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wraps an ``mx.optimizer.Optimizer``: every ``update`` first
    averages the gradient across ranks (reference
    ``mxnet/__init__.py:40-77``)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return
        if isinstance(index, (tuple, list)):
            for i in range(len(index)):
                allreduce_(grad[i], average=True,
                           name=f"gradient.{index[i]}")
        else:
            allreduce_(grad, average=True, name=f"gradient.{index}")

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)


def _make_distributed_trainer():
    """gluon Trainer subclass, defined lazily so environments exposing
    only the symbolic API still import."""
    if not hasattr(mx, "gluon"):
        return None

    class DistributedTrainer(mx.gluon.Trainer):
        """gluon Trainer whose gradient aggregation is a cross-rank
        allreduce (reference ``mxnet/__init__.py:85-108``)."""

        def __init__(self, params, optimizer, optimizer_params=None):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params,
                             kvstore=None)
            # Horovod contract: scale_ divides by local batch only; the
            # allreduce averages across ranks
            self._scale /= size()

        def _allreduce_grads(self):
            if size() == 1:
                return
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    allreduce_(param.list_grad()[0], average=False,
                               name=f"gradient.{i}")

    return DistributedTrainer


DistributedTrainer = _make_distributed_trainer()


def _append_broadcast_init(param, root_rank, name):
    """Arm a deferred-init gluon parameter so that the moment the engine
    materializes it (first forward shapes it and calls ``_init_impl``),
    its freshly initialized value is broadcast from ``root_rank`` —
    without this, each rank keeps its own random init and the model
    silently diverges (reference ``mxnet/__init__.py:118-153``)."""
    import types as _types

    init_impl = param._init_impl  # bound method of this parameter

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank, name=f"bp.deferred.{name}")
        data = self.data()
        if hasattr(data, "wait_to_read"):
            # block until the broadcast write-back lands before the
            # engine's first use of the parameter
            data.wait_to_read()

    param._init_impl = _types.MethodType(wrapped_init_impl, param)


def broadcast_parameters(params, root_rank=0):
    """Sync model parameters from root at startup (reference
    ``mxnet/__init__.py:118-153``). Accepts a plain ``dict`` of NDArrays
    or a gluon ``ParameterDict``. Deferred-init parameters (shape not
    known yet) are armed to broadcast at materialization via
    ``_append_broadcast_init``."""
    deferred_exc = getattr(getattr(mx.gluon, "parameter", mx.gluon),
                           "DeferredInitializationError", None)
    tensors = []
    if isinstance(params, dict):
        tensors = sorted(params.items())
    elif hasattr(params, "items"):  # gluon ParameterDict
        for name, p in sorted(params.items()):
            if deferred_exc is not None:
                try:
                    tensors.append((name, p.data()))
                except deferred_exc:
                    _append_broadcast_init(p, root_rank, name)
            else:
                tensors.append((name, p.data()))
    else:
        raise ValueError("invalid params type: " + str(type(params)))
    handles = [broadcast_async_(t, root_rank, name=f"bp.{name}")
               for name, t in tensors]
    for h in handles:
        h.synchronize()
