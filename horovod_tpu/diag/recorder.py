"""Per-rank flight recorder: a bounded ring of structured events.

Design constraints (the hot path is the collective dispatch path):

* Recording is a ``collections.deque(maxlen=...)`` append plus a
  ``zlib.crc32`` update — no I/O, no locks. The GIL makes the append
  atomic; the ring bounds memory to ``capacity`` events forever.
* The schedule digest must be comparable ACROSS ranks, so it is a CRC
  chain over ``op|name|shape|dtype`` (``hash()`` is salted per process
  and useless here). Two ranks that dispatched the same collective
  schedule hold the same ``(seq, digest)`` pair; the first divergent
  dispatch forks the chain forever — the trace-time mirror of the
  reference controller's shape/dtype mismatch checks
  (``controller.cc:55-346``).
* Dumps are atomic (tempfile + ``os.replace``) and idempotent: a second
  signal landing mid-teardown rewrites the same path and can never leave
  a torn file; every dump reason is appended to the header so the doctor
  sees the full trigger history.

Signal story (why there is a watcher thread): Python-level signal
handlers only run on the MAIN thread between bytecodes. A rank parked in
a native collective (``_core.hvdc_wait``) never reaches another
bytecode, so a plain ``signal.signal`` handler would neither dump nor
die — the launcher's SIGTERM fan-out would hang. ``install()`` therefore
also routes signals through ``signal.set_wakeup_fd`` to a daemon watcher
thread: the C-level handler writes the signal number to a pipe
regardless of what the main thread is doing, the watcher dumps from its
own thread, and — when the previous disposition was the default
(terminate) — SIGKILLs the process after a short grace so the fan-out
still kills a wedged rank.
"""

import atexit
import collections
import dataclasses
import json
import logging
import os
import signal
import sys
import threading
import time
import zlib

logger = logging.getLogger("horovod_tpu")

DUMP_PREFIX = "flightrec.rank"
DEFAULT_CAPACITY = 4096
# (seq, digest) checkpoints kept for cross-rank comparison; the KV
# heartbeat ships the most recent DIGEST_PUBLISH of them
DIGEST_HISTORY = 128
DIGEST_PUBLISH = 16
# seconds between the watcher's dump and its failsafe SIGKILL when the
# default disposition should have terminated the process already
FAILSAFE_GRACE_S = 2.0


def _crc(h, *parts):
    for p in parts:
        h = zlib.crc32(str(p).encode(), h)
    return h & 0xFFFFFFFF


# knobs that legitimately differ per rank (identity, per-rank ports/
# paths) — everything else differing across ranks is a desync hazard
_PER_RANK_KEYS = frozenset({
    "rank", "local_rank", "cross_rank", "metrics_port", "flightrec_dir",
    "timeline", "controller_port", "autotune_log", "profile_dir"})


def config_fingerprint(cfg):
    """Stable CRC over the config snapshot — lets the doctor flag ranks
    that ran with mismatched knobs (a classic source of desyncs).
    Per-rank identity fields are excluded, so equal fingerprints mean
    "same knobs", not "same process"."""
    try:
        items = sorted(dataclasses.asdict(cfg).items())
    except TypeError:
        items = sorted(vars(cfg).items())
    h = 0
    for k, v in items:
        if k in _PER_RANK_KEYS:
            continue
        h = _crc(h, k, v)
    return h


class FlightRecorder:
    """One rank's black box.

    ``clock``/``wall_clock`` are injectable so unit tests can drive
    wraparound, dump idempotency and digest divergence without sleeping
    (the same discipline as ``runtime/stall.py``).
    """

    def __init__(self, capacity=DEFAULT_CAPACITY, rank=0, size=1,
                 dump_dir=None, clock=time.monotonic, wall_clock=time.time,
                 config=None):
        self.capacity = max(1, int(capacity))
        self.rank = rank
        self.size = size
        self.dump_dir = dump_dir or os.environ.get(
            "HOROVOD_FLIGHTREC_DIR") or _default_dump_dir()
        self._clock = clock
        self._wall = wall_clock
        self._events = collections.deque(maxlen=self.capacity)
        self._events_total = 0
        self.collective_seq = 0        # collectives ENTERED on this rank
        self.last_completed_seq = 0    # collectives EXITED on this rank
        self._digest = 0
        self._digest_hist = collections.deque(maxlen=DIGEST_HISTORY)
        self._open = {}                # seq -> op of entered-not-exited
        self._dump_lock = threading.Lock()
        self.dump_reasons = []
        self.config_snapshot = None
        self.config_crc = None
        if config is not None:
            try:
                self.config_snapshot = {
                    k: v for k, v in dataclasses.asdict(config).items()}
            except TypeError:
                self.config_snapshot = dict(vars(config))
            self.config_crc = config_fingerprint(config)
        self.record("start", pid=os.getpid(),
                    host=os.environ.get("HOROVOD_HOSTNAME"))

    # -- recording (the hot path) -------------------------------------------
    def record(self, etype, **fields):
        """Bounded append of one structured event (``etype`` is the
        event kind, stored as ``k``). Safe from any thread; never raises
        into the caller."""
        ev = {"k": etype, "t": self._wall(), "m": self._clock()}
        if fields:
            ev.update(fields)
        self._events.append(ev)
        self._events_total += 1
        return ev

    def collective_enter(self, op, name=None, shape=None, dtype=None,
                         nbytes=0, mode="eager", hash_shape=True):
        """Advance ``collective_seq``, extend the schedule digest, record
        the entry. Returns the seq so the matching :meth:`collective_exit`
        can close it. ``mode`` is ``"eager"`` (one event per executed
        call, bracketed B/E so a rank parked inside the call leaves a
        dangling B) or ``"trace"`` (one event per collective baked into a
        compiled program, recorded at trace time as a single ``T`` marker
        — there is no per-execution exit on the compiled plane, so trace
        entries are never "open")."""
        self.collective_seq += 1
        seq = self.collective_seq
        # hash_shape=False for variable-length collectives (eager
        # allgatherv semantics): per-rank first dims legitimately
        # differ, and hashing them would fork the cross-rank digest
        # chain forever — a false DESYNC on a correct program
        self._digest = _crc(self._digest, op, name,
                            shape if hash_shape else "<varlen>", dtype)
        self._digest_hist.append((seq, self._digest))
        if mode == "eager":
            self._open[seq] = op
        self.record("coll", ph="B" if mode == "eager" else "T",
                    seq=seq, op=op, name=name,
                    shape=list(shape) if shape is not None else None,
                    dtype=str(dtype) if dtype is not None else None,
                    nbytes=int(nbytes), mode=mode)
        return seq

    def collective_exit(self, op, seq, ok=True):
        self._open.pop(seq, None)
        if ok and seq > self.last_completed_seq:
            self.last_completed_seq = seq
        self.record("coll", ph="E", seq=seq, op=op, ok=bool(ok))

    def step_begin(self, step):
        self.record("step", ph="B", step=int(step))

    def step_end(self, step):
        self.record("step", ph="E", step=int(step))

    def heartbeat(self, step=None):
        self.record("heartbeat", step=step)

    def epoch(self, epoch):
        """Rendezvous epoch marker (elastic membership changes)."""
        self.record("epoch", epoch=int(epoch))

    # -- digests (the desync plane) -----------------------------------------
    def digest(self):
        """Compact rolling digest for the KV heartbeat: current ``seq``
        and schedule hash plus the last few ``(seq, hash)`` checkpoints so
        the driver can line ranks up at a common seq."""
        return {"seq": self.collective_seq, "hash": self._digest,
                "hist": [list(p) for p in
                         list(self._digest_hist)[-DIGEST_PUBLISH:]]}

    # -- snapshots / dumps ---------------------------------------------------
    def _snapshot_events(self):
        # list(deque) can race a concurrent append ("deque mutated during
        # iteration"); retry — the ring is bounded so this converges
        for _ in range(8):
            try:
                return list(self._events)
            except RuntimeError:
                continue
        return []

    def snapshot(self, reason=None):
        now_m, now_w = self._clock(), self._wall()
        return {
            "flightrec": 1,
            "rank": self.rank,
            "size": self.size,
            "pid": os.getpid(),
            "host": os.environ.get("HOROVOD_HOSTNAME"),
            "capacity": self.capacity,
            "events_total": self._events_total,
            "collective_seq": self.collective_seq,
            "last_completed_seq": self.last_completed_seq,
            "open_collectives": {str(s): op
                                 for s, op in sorted(self._open.items())},
            "digest": self.digest(),
            "config_crc": self.config_crc,
            "config": self.config_snapshot,
            # both clocks at snapshot time: wall = mono + offset lets the
            # doctor align per-rank monotonic stamps on one wall axis
            "clock": {"monotonic": now_m, "wall": now_w,
                      "wall_minus_monotonic": now_w - now_m},
            "dump_reasons": list(self.dump_reasons) + (
                [reason] if reason else []),
            "events": self._snapshot_events(),
        }

    def dump_path(self):
        return os.path.join(self.dump_dir, f"{DUMP_PREFIX}{self.rank}.json")

    def dump(self, reason="on_demand", path=None):
        """Write the black box to disk. Atomic, idempotent, re-entrant:
        a dump racing another dump (double signal) skips — the first
        writer's file is complete and the reasons history is preserved
        on the next successful dump."""
        if not self._dump_lock.acquire(blocking=False):
            return None
        try:
            self.record("dump", reason=reason)
            self.dump_reasons.append(reason)
            out = path or self.dump_path()
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            tmp = f"{out}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f)
            os.replace(tmp, out)
            return out
        # hvd-lint: disable=HVD-EXCEPT -- dump runs inside signal/atexit hooks; must not throw
        except Exception:
            logger.warning("flight recorder dump failed", exc_info=True)
            return None
        finally:
            self._dump_lock.release()

    def wait_for_dump(self, timeout=5.0):
        """Block until an in-flight :meth:`dump` on ANOTHER thread has
        finished. A thread about to terminate the process after its own
        dump() skipped (the non-blocking lock was held) must wait here
        first: the signal wakeup-fd watcher and the main-thread signal
        handler both fire on one signal, and the loser re-raising the
        fatal default disposition would otherwise kill the winner
        mid-``json.dump`` — a torn tmp file and no black box at all."""
        if self._dump_lock.acquire(timeout=timeout):
            self._dump_lock.release()


def _default_dump_dir():
    import tempfile
    return os.path.join(tempfile.gettempdir(), "horovod_tpu_flightrec")


# ---------------------------------------------------------------------------
# Module-level hooks: the emission sites (ops/collective, ops/fusion,
# training) call these unconditionally; with no recorder installed each is
# one global load + None check.
# ---------------------------------------------------------------------------

_recorder = None

# signum -> [callable]: consumers that CLAIM a signal (graceful eviction,
# elastic/preempt.py). A claimed signal changes the termination contract:
# the main-thread handler dumps and returns (no re-raise of the fatal
# default), the watcher dumps, runs every listener on its own thread
# (free to block — it is not a signal context), and skips the failsafe
# SIGKILL. The listener owns process termination from that point.
_signal_listeners = {}


def add_signal_listener(signum, fn):
    """Register ``fn(signum)`` to run on the wakeup-fd WATCHER thread
    when ``signum`` arrives. This is how the graceful-eviction handler
    rides the recorder's signal path: the C-level handler writes the
    signal number to the pipe regardless of what the main thread is
    doing, so a rank parked in a native collective still runs its
    bounded grace commit. Registering claims the signal (see
    ``_signal_listeners``)."""
    _signal_listeners.setdefault(int(signum), []).append(fn)


def remove_signal_listener(signum, fn):
    fns = _signal_listeners.get(int(signum))
    if not fns:
        return
    try:
        fns.remove(fn)
    except ValueError:
        return
    if not fns:
        _signal_listeners.pop(int(signum), None)


def _listeners_for(signum):
    return list(_signal_listeners.get(int(signum), ()))


def signal_watcher_active():
    """True when the wakeup-fd watcher thread is installed and alive —
    the precondition for :func:`add_signal_listener` actually firing.
    Consumers fall back to their own ``signal.signal`` path otherwise."""
    hooks = _hooks
    t = hooks.get("watcher") if hooks else None
    return t is not None and t.is_alive()


def get_recorder():
    return _recorder


def collective_enter(op, x=None, name=None, nbytes=0, mode="eager",
                     hash_shape=True):
    r = _recorder
    if r is None:
        return 0
    shape = dtype = None
    if x is not None:
        try:
            import numpy as np
            shape = tuple(np.shape(x))
            dtype = getattr(x, "dtype", None)
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
        except Exception:
            pass
    try:
        return r.collective_enter(op, name=name, shape=shape, dtype=dtype,
                                  nbytes=nbytes, mode=mode,
                                  hash_shape=hash_shape)
    # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
    except Exception:
        return 0


def collective_exit(op, seq, ok=True):
    r = _recorder
    if r is None or not seq:
        return
    try:
        r.collective_exit(op, seq, ok=ok)
    # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
    except Exception:
        pass


def step_begin(step):
    r = _recorder
    if r is not None:
        try:
            r.step_begin(step)
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
        except Exception:
            pass


def step_end(step):
    r = _recorder
    if r is not None:
        try:
            r.step_end(step)
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
        except Exception:
            pass


def record_event(etype, **fields):
    r = _recorder
    if r is not None:
        try:
            r.record(etype, **fields)
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
        except Exception:
            pass


def current_digest():
    r = _recorder
    if r is None:
        return None
    try:
        return r.digest()
    # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the training path
    except Exception:
        return None


def dump_now(reason="on_demand"):
    """Dump the installed recorder (no-op without one). Used by the
    stall inspector when its warning fires and by the ``/flightrec``
    endpoint."""
    r = _recorder
    if r is None:
        return None
    return r.dump(reason=reason)


# ---------------------------------------------------------------------------
# install / uninstall: crash-dump triggers.
# ---------------------------------------------------------------------------

_hooks = None  # state of the installed trigger set


def install(capacity=DEFAULT_CAPACITY, dump_dir=None, rank=0, size=1,
            config=None, signals=(signal.SIGTERM, signal.SIGABRT),
            handle_signals=True):
    """Create and install the process flight recorder + dump triggers:
    ``sys.excepthook``, ``atexit``, and (``handle_signals=True``) the
    SIGTERM/SIGABRT path described in the module docstring. Idempotent —
    a second install returns the existing recorder. Must be called from
    the main thread (signal API constraint)."""
    global _recorder, _hooks
    if _recorder is not None:
        return _recorder
    rec = FlightRecorder(capacity=capacity, rank=rank, size=size,
                         dump_dir=dump_dir, config=config)
    _recorder = rec
    hooks = {"signals": {}, "wakeup": None, "pipe": None,
             "excepthook": sys.excepthook, "watcher": None,
             "stop": threading.Event()}

    def _excepthook(tp, val, tb):
        try:
            rec.record("exception", type=getattr(tp, "__name__", str(tp)),
                       value=repr(val)[:500])
            rec.dump(reason="exception")
        finally:
            hooks["excepthook"](tp, val, tb)

    sys.excepthook = _excepthook
    hooks["installed_excepthook"] = _excepthook

    def _atexit_dump():
        if _recorder is rec:
            rec.dump(reason="exit")

    atexit.register(_atexit_dump)
    hooks["atexit"] = _atexit_dump

    if handle_signals:
        try:
            _install_signal_path(rec, hooks, signals)
        except (ValueError, OSError):
            # not the main thread / restricted env: the excepthook +
            # atexit + stall triggers still work
            logger.debug("flight recorder signal triggers unavailable",
                         exc_info=True)
    _hooks = hooks
    return rec


def _install_signal_path(rec, hooks, signals):
    r_fd, w_fd = os.pipe()
    os.set_blocking(w_fd, False)
    hooks["pipe"] = (r_fd, w_fd)
    hooks["wakeup"] = signal.set_wakeup_fd(w_fd, warn_on_full_buffer=False)

    prev = {}
    for sig in signals:
        prev[sig] = signal.getsignal(sig)

        def _handler(signum, frame, _prev=prev[sig]):
            # main-thread path: dump, then hand over to the previous
            # behavior (user handler, ignore, or default termination).
            # The watcher thread races us on the same signal via the
            # wakeup fd — if it holds the dump lock our dump() skips,
            # and we must let its write FINISH before re-raising a
            # fatal disposition that would tear it mid-file.
            rec.record("signal", signum=int(signum))
            rec.dump(reason=f"signal:{signum}")
            rec.wait_for_dump()
            if _listeners_for(signum):
                # a listener claimed this signal (graceful eviction):
                # the watcher runs it and the listener owns termination
                # — re-raising the fatal default here would kill the
                # process mid-grace-commit
                return
            if _prev is signal.SIG_IGN:
                return  # the app chose to survive this signal; honor it
            if callable(_prev):
                _prev(signum, frame)
                return
            try:
                signal.signal(signum, signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            os.kill(os.getpid(), signum)

        signal.signal(sig, _handler)
        hooks["signals"][sig] = prev[sig]

    fatal_by_default = {int(s) for s in signals
                        if prev[s] in (signal.SIG_DFL, None)}

    def _watch():
        while True:
            try:
                data = os.read(r_fd, 64)
            except OSError:
                return
            if not data or hooks["stop"].is_set():
                return
            for b in data:
                if b not in {int(s) for s in signals}:
                    continue
                rec.record("signal", signum=int(b), via="watcher")
                rec.dump(reason=f"signal:{b}")
                listeners = _listeners_for(b)
                for fn in listeners:
                    try:
                        fn(int(b))
                    # hvd-lint: disable=HVD-EXCEPT -- a listener must not kill the watcher
                    except Exception:
                        logger.warning("signal listener failed",
                                       exc_info=True)
                if listeners:
                    # the listener owns termination (bounded grace
                    # commit, then exit) — no failsafe kill
                    continue
                if b in fatal_by_default:
                    # the default disposition should already have killed
                    # us; if the main thread is parked in native code the
                    # Python handler can never run — honor the signal's
                    # intent after a grace so the launcher's fan-out
                    # still terminates this rank
                    hooks["stop"].wait(FAILSAFE_GRACE_S)
                    if not hooks["stop"].is_set():
                        os.kill(os.getpid(), signal.SIGKILL)

    t = threading.Thread(target=_watch, daemon=True,
                         name="hvd_tpu_flightrec")
    t.start()
    hooks["watcher"] = t


def uninstall(dump=True, reason="shutdown"):
    """Tear down the recorder and restore every hook it installed.
    ``dump=True`` writes one final dump (so a cleanly-exiting rank leaves
    evidence that it exited cleanly — the doctor distinguishes 'no dump'
    = hard-killed from 'dump with shutdown reason' = clean)."""
    global _recorder, _hooks
    rec, hooks = _recorder, _hooks
    if rec is None:
        return
    if dump:
        rec.dump(reason=reason)
    _recorder = None
    _hooks = None
    _signal_listeners.clear()
    if hooks is None:
        return
    hooks["stop"].set()
    if sys.excepthook is hooks.get("installed_excepthook"):
        sys.excepthook = hooks["excepthook"]
    try:
        atexit.unregister(hooks["atexit"])
    # hvd-lint: disable=HVD-EXCEPT -- teardown: the hook may already be unregistered
    except Exception:
        pass
    for sig, prev in hooks["signals"].items():
        try:
            signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
        except (ValueError, OSError):
            pass
    if hooks["wakeup"] is not None or hooks["pipe"] is not None:
        try:
            signal.set_wakeup_fd(hooks["wakeup"]
                                 if hooks["wakeup"] is not None else -1)
        except (ValueError, OSError):
            pass
    if hooks["pipe"] is not None:
        # write end first: EOF wakes the watcher's blocking read before
        # the read end goes away under it
        r_fd, w_fd = hooks["pipe"]
        for fd in (w_fd, r_fd):
            try:
                os.close(fd)
            except OSError:
                pass
