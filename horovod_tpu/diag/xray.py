"""``hvd-doctor xray`` — where did my compiled step go.

The device-side twin of ``hvd-doctor perf``: where the perf doctor
attributes HOST wall time from goodput-ledger dumps, this one
attributes DEVICE time inside the jitted GSPMD step from a
``jax.profiler`` capture (``telemetry/xprof.py`` does the parsing).
It accepts either:

* a directory holding ``xray.rank<r>.json`` summaries (what
  ``step.xray(k)`` / ``bench.py --spmd`` wrote next to their capture) —
  reprinted without re-parsing; or
* a raw profiler dump (``/profile?seconds=N``'s output dir, or any dir
  with ``plugins/profile/<run>/*.trace.json[.gz]``) — parsed fresh;
  pass ``--hlo <file>`` (compiled HLO text, e.g. ``step.lower(...)
  .compile().as_text()`` saved to disk) to join per-collective bytes
  and get effective-bandwidth rows.

Output: the verdict (comms-bound / compute-bound / overlap-broken /
copy-bound / idle-bound / empty-capture), the per-category device-time
table gated by ``bucketed_fraction``, and the per-collective
exposed-vs-overlapped + bandwidth table. ``--json`` prints the summary
dict on stdout (report prose moves to stderr), the same convention the
other doctor subcommands follow.

CLI::

    hvd-doctor xray <dir> [--steps K] [--hlo compiled.txt] [--json]
"""

import argparse
import glob
import json
import os
import sys

from horovod_tpu.telemetry import xprof

_PCT = "{:5.1f}%"


def find_summaries(directory):
    """``xray.rank*.json`` paths directly under ``directory`` or its
    capture subdirs (non-recursive beyond the profiler layout)."""
    pats = [os.path.join(glob.escape(directory),
                         f"{xprof.SUMMARY_PREFIX}*.json"),
            os.path.join(glob.escape(directory), "plugins", "profile",
                         "*", f"{xprof.SUMMARY_PREFIX}*.json")]
    return sorted(p for pat in pats for p in glob.glob(pat)
                  if ".tmp" not in p)


def load_summaries(directory):
    """Parse the checked summaries — ``[(path, summary)]``, skipping
    files that are not X-ray summaries."""
    out = []
    for path in find_summaries(directory):
        try:
            with open(path) as f:
                d = json.load(f)
            if d.get("xray"):
                out.append((path, d))
        except (OSError, ValueError):
            continue
    return out


def format_summary(summary, source=None):
    lines = []
    add = lines.append
    add("==== horovod_tpu compiled-step x-ray " + "=" * 28)
    if source:
        add(f"capture: {source}")
    total = sum(summary["device_seconds"].values())
    add(f"device lanes: {summary['device_lanes']}; window "
        f"{summary['window_seconds'] * 1e3:.2f}ms"
        + (f"; steps {summary['steps']}" if summary.get("steps") else ""))
    gate = summary["bucketed_fraction"]
    flag = "" if gate >= xprof.BUCKETED_GATE else \
        f"  << BELOW {xprof.BUCKETED_GATE:.0%} GATE"
    add(f"bucketed: {gate:.1%} of device time named{flag}")
    for cat in xprof.CATEGORIES:
        s = summary["device_seconds"].get(cat, 0.0)
        if s <= 0:
            continue
        pct = 100.0 * s / total if total > 0 else 0.0
        add(f"  {cat:<20} {s * 1e3:>10.3f}ms  {pct:5.1f}%")
    colls = summary.get("collectives", {})
    if colls:
        add("collectives (exposed = not hidden behind compute):")
        for op, c in sorted(colls.items()):
            row = (f"  {op:<20} {c['seconds'] * 1e3:>8.3f}ms  "
                   f"exposed {c['exposed_seconds'] * 1e3:>8.3f}ms  "
                   f"overlapped {c['overlapped_seconds'] * 1e3:>8.3f}ms")
            if "effective_gbps" in c:
                row += (f"  {c['effective_gbps']:>7.2f} GB/s "
                        f"({c.get('bytes_per_step', 0)} B/step/device)")
            add(row)
    if summary.get("torn_files"):
        add(f"torn trace files skipped: {len(summary['torn_files'])}")
    sink_cat, sink_s = xprof.dominant_sink(summary)
    if sink_cat is not None:
        pct = 100.0 * sink_s / total if total > 0 else 0.0
        add(f"dominant sink: {sink_cat} — {sink_s * 1e3:.3f}ms "
            f"({pct:.1f}% of device time)")
    add(f"VERDICT: {summary['verdict']}")
    add("=" * 66)
    return "\n".join(lines)


def run(directory, steps=None, hlo=None, stream=None):
    """Summaries if present, else parse the raw capture. Returns the
    list of ``(source, summary)`` printed, or None when the directory
    holds neither."""
    stream = stream or sys.stderr
    found = load_summaries(directory)
    if found:
        for path, summary in found:
            print(format_summary(summary, source=path), file=stream)
        return found
    try:
        summary = xprof.analyze_capture(directory, steps=steps)
    except ValueError as e:
        print(f"xray: {e}", file=stream)
        return None
    if hlo:
        try:
            with open(hlo) as f:
                text = f.read()
            from horovod_tpu.parallel.gspmd import collective_bytes_from_hlo
            xprof.join_collective_bytes(
                summary, collective_bytes_from_hlo(text), steps=steps)
        except OSError as e:
            print(f"xray: --hlo unreadable, bandwidth rows skipped: {e}",
                  file=stream)
    print(format_summary(summary, source=summary.get("capture_dir")),
          file=stream)
    return [(summary.get("capture_dir"), summary)]


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="hvd-doctor xray",
        description="Attribute compiled-step device time from a "
                    "jax.profiler capture: per-category buckets, "
                    "exposed vs overlapped collective time, effective "
                    "exchange bandwidth, and a verdict.")
    p.add_argument("dir", help="profiler dump dir (/profile output or "
                               "step.xray's profile_dir), or a dir "
                               "holding xray.rank*.json summaries")
    p.add_argument("--steps", type=int, default=None,
                   help="steps the capture covers (scales the "
                        "bandwidth join; summaries carry their own)")
    p.add_argument("--hlo", default=None,
                   help="compiled HLO text file to join per-collective "
                        "bytes from (raw captures only)")
    p.add_argument("--json", action="store_true",
                   help="print the summary JSON on stdout (report "
                        "prose moves to stderr)")
    args = p.parse_args(argv)
    found = run(args.dir, steps=args.steps, hlo=args.hlo,
                stream=sys.stderr if args.json else sys.stdout)
    if found is None:
        return 2
    if args.json:
        payload = ([s for _src, s in found] if len(found) > 1
                   else found[0][1])
        print(json.dumps(payload, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
