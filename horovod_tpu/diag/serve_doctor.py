"""``hvd-doctor serve`` — the tail-latency doctor for the serve fleet.

The serving twin of ``hvd-doctor perf``: where the perf doctor loads
goodput-ledger dumps and names each rank's dominant time sink, this one
loads per-request trace dumps (``servetrace*.ndjson``, written by
``serve/tracing.py``) and names each SLOW request's dominant phase:

* ``queue``                 — router/engine queue + dispatch scoring
* ``kv_backpressure``       — admission head blocked on KV blocks
* ``prefill_starved``       — admitted but waiting for prefill turns
* ``decode_batch_dilation`` — waiting between decode iterations
* ``weight_swap_stall``     — rolling-reload windows it overlapped
* ``redispatch_hop``        — cut by an eviction, resumed elsewhere

plus the compute phases (``prefill``, ``decode``, ``stream``) that are
work, not stalls. "Slow" is latency >= the SLO when one is given, else
the p99. Span time inside a hop window (a ``cut`` event until the
first token on the survivor) is re-attributed to ``redispatch_hop`` —
the survivor-side requeue, re-admission and re-prefill of a cut stream
all happened BECAUSE of the eviction, whatever their span kind says.

Every span kind ``serve/tracing.py`` can emit must have an entry in
:data:`PHASE_OF_KIND` and vice versa — hvd-lint HVD-METRIC asserts the
table and this classifier agree both ways (analysis/rules/metric.py),
the same drift contract the metric catalogue has.

CLI::

    hvd-doctor serve <dir-or-ndjson> [--slo-ms 250] [--json]
"""

import argparse
import glob as _glob
import json
import os
import sys

DUMP_GLOB = "servetrace*.ndjson"

# span kind (serve/tracing.py SPAN_KINDS + the unattributed residue)
# -> report phase. Several kinds may share a phase; the doctor reports
# phases, the trace keeps the finer kinds.
PHASE_OF_KIND = {
    "queue": "queue",
    "dispatch": "queue",
    "kv_wait": "kv_backpressure",
    "prefill": "prefill",
    "prefill_wait": "prefill_starved",
    "decode": "decode",
    "decode_wait": "decode_batch_dilation",
    "weight_swap": "weight_swap_stall",
    "redispatch": "redispatch_hop",
    "stream": "stream",
}

# the phases that are STALLS — a slow request's verdict is its largest
# stall, never its (necessary) compute
STALL_PHASES = ("queue", "kv_backpressure", "prefill_starved",
                "decode_batch_dilation", "weight_swap_stall",
                "redispatch_hop")

UNATTRIBUTED = "unattributed"


def find_dumps(path):
    """``servetrace*.ndjson`` files under a directory (recursively), or
    the file itself."""
    if os.path.isfile(path):
        return [path]
    return sorted(_glob.glob(os.path.join(path, "**", DUMP_GLOB),
                             recursive=True))


def load_traces(paths):
    """Parse every trace line; a half-written trailing line (a fleet
    killed mid-dump) is skipped, not fatal."""
    traces, skipped = [], 0
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    traces.append(json.loads(line))
                except json.JSONDecodeError:
                    skipped += 1
    return traces, skipped


def phase_totals(trace):
    """Seconds per report phase for one trace. Span time overlapping a
    hop window is charged to ``redispatch_hop`` regardless of kind."""
    windows = trace.get("hop_windows") or []
    totals = {}
    for sp in trace.get("spans", ()):
        t0, t1 = float(sp["t0"]), float(sp["t1"])
        dur = max(0.0, t1 - t0)
        if dur <= 0.0:
            continue
        in_hop = 0.0
        for w0, w1 in windows:
            in_hop += max(0.0, min(t1, w1) - max(t0, w0))
        in_hop = min(in_hop, dur)
        phase = PHASE_OF_KIND.get(sp["kind"], UNATTRIBUTED)
        if in_hop > 0.0:
            totals["redispatch_hop"] = \
                totals.get("redispatch_hop", 0.0) + in_hop
        if dur - in_hop > 0.0:
            totals[phase] = totals.get(phase, 0.0) + (dur - in_hop)
    return totals


def dominant_stall(totals):
    """(phase, seconds) of the largest stall; ("none", 0.0) for a
    request that never waited."""
    best, best_s = "none", 0.0
    for phase in STALL_PHASES:
        s = totals.get(phase, 0.0)
        if s > best_s:
            best, best_s = phase, s
    return best, best_s


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def aggregate(traces, slo_ms=None):
    """The fleet tail report: per-request phase totals, the slow bucket
    (>= SLO, else >= p99), each slow request's dominant stall, and the
    fleet-wide verdict."""
    requests = []
    for tr in traces:
        totals = phase_totals(tr)
        dom, dom_s = dominant_stall(totals)
        latency_ms = float(tr.get("latency_s", 0.0)) * 1e3
        requests.append({
            "request_id": tr.get("request_id"),
            "latency_ms": latency_ms,
            "hops": int(tr.get("hops", 0)),
            "attributed_fraction":
                float(tr.get("attributed_fraction", 0.0)),
            "dominant_phase": dom,
            "dominant_ms": dom_s * 1e3,
            "phases_ms": {k: v * 1e3 for k, v in sorted(totals.items())},
        })
    lat = sorted(r["latency_ms"] for r in requests)
    p50 = _percentile(lat, 0.50)
    p99 = _percentile(lat, 0.99)
    threshold = float(slo_ms) if slo_ms is not None else p99
    slow = [r for r in requests if r["latency_ms"] >= threshold]
    phase_counts = {}
    slow_totals = {}
    for r in slow:
        phase_counts[r["dominant_phase"]] = \
            phase_counts.get(r["dominant_phase"], 0) + 1
        for phase, ms in r["phases_ms"].items():
            slow_totals[phase] = slow_totals.get(phase, 0.0) + ms
    verdict = max(phase_counts.items(),
                  key=lambda kv: (kv[1], kv[0]))[0] if phase_counts \
        else "none"
    return {
        "requests": len(requests),
        "p50_ms": p50,
        "p99_ms": p99,
        "slow_threshold_ms": threshold,
        "slow_threshold_kind": "slo" if slo_ms is not None else "p99",
        "slow": sorted(slow, key=lambda r: -r["latency_ms"]),
        "slow_dominant_counts": dict(sorted(phase_counts.items())),
        "slow_phase_totals_ms": dict(sorted(slow_totals.items())),
        "verdict": verdict,
        "min_attributed_fraction":
            min((r["attributed_fraction"] for r in requests),
                default=0.0),
        "per_request": requests,
    }


def format_report(report):
    lines = ["== hvd-doctor serve: request tail report =="]
    lines.append(
        f"requests: {report['requests']} traced, "
        f"p50 {report['p50_ms']:.1f} ms, p99 {report['p99_ms']:.1f} ms, "
        f"min attributed {report['min_attributed_fraction'] * 100:.1f}%")
    kind = report["slow_threshold_kind"]
    lines.append(
        f"slow bucket (latency >= {report['slow_threshold_ms']:.1f} ms "
        f"[{kind}]): {len(report['slow'])} request(s)")
    for r in report["slow"]:
        lines.append(
            f"  {r['request_id']}: {r['latency_ms']:.1f} ms, "
            f"{r['hops']} hop(s), dominant {r['dominant_phase']} "
            f"({r['dominant_ms']:.1f} ms), attributed "
            f"{r['attributed_fraction'] * 100:.1f}%")
    if report["slow_phase_totals_ms"]:
        totals = ", ".join(
            f"{k} {v:.1f}" for k, v in sorted(
                report["slow_phase_totals_ms"].items(),
                key=lambda kv: -kv[1]))
        lines.append(f"slow-bucket phase totals (ms): {totals}")
    counts = report["slow_dominant_counts"]
    n_slow = max(1, len(report["slow"]))
    lines.append(
        f"verdict: {report['verdict']} dominates "
        f"{counts.get(report['verdict'], 0)}/{n_slow} slow request(s)")
    return "\n".join(lines)


def run(path, slo_ms=None, stream=None):
    """Load dumps under ``path`` and print the tail report. Returns the
    report dict, or None when there is nothing to report."""
    stream = stream or sys.stderr
    paths = find_dumps(path)
    if not paths:
        print(f"serve doctor: no {DUMP_GLOB} dumps under {path}",
              file=stream)
        return None
    traces, skipped = load_traces(paths)
    if skipped:
        print(f"serve doctor: skipped {skipped} unparseable trace "
              f"line(s)", file=stream)
    if not traces:
        print(f"serve doctor: no traces in {len(paths)} dump file(s)",
              file=stream)
        return None
    report = aggregate(traces, slo_ms=slo_ms)
    print(format_report(report), file=stream)
    return report


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="hvd-doctor serve",
        description="Name each slow request's dominant phase from "
                    "per-request serve trace dumps "
                    "(servetrace*.ndjson).")
    p.add_argument("path", help="trace dump directory (searched "
                                "recursively) or one ndjson file")
    p.add_argument("--slo-ms", type=float, default=None,
                   help="slow threshold in ms (default: the p99)")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON instead")
    args = p.parse_args(argv)
    if args.json:
        paths = find_dumps(args.path)
        traces, _ = load_traces(paths)
        if not traces:
            print(f"serve doctor: no traces under {args.path}",
                  file=sys.stderr)
            return 2
        print(json.dumps(aggregate(traces, slo_ms=args.slo_ms),
                         indent=2))
        return 0
    report = run(args.path, slo_ms=args.slo_ms, stream=sys.stdout)
    return 2 if report is None else 0


if __name__ == "__main__":
    sys.exit(main())
