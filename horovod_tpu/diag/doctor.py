"""The desync doctor: aggregate per-rank flight-recorder dumps into one
hang report.

    hvdrun --doctor <logdir>
    python -m horovod_tpu.diag.doctor <logdir>

The doctor answers, from dumps alone (no live processes needed): which
ranks never dumped (hard-killed — SIGKILL and OOM leave no black box),
the last ``collective_seq`` every surviving rank completed, the
collective each straggler is parked in, whether the collective schedules
diverged (desync), and a probable-cause classification:

* ``dead rank``    — expected ranks left no dump; survivors are parked in
  a collective the dead rank never joined (the post-mortem analogue of
  the reference stall inspector's "missing ranks" warning,
  ``stall_inspector.cc``).
* ``desync``       — all ranks alive but their op/name/shape schedules
  forked (the mismatch the reference controller would have rejected at
  negotiation time, ``controller.cc:55-346``).
* ``data stall``   — a rank finished its step and never started the next
  one (input pipeline starved) while peers wait in a collective.
* ``compile stall``— a rank entered a step and emitted no collective
  since (stuck in compilation / first dispatch) while peers progressed.
* ``graceful eviction`` — rank(s) ran the preemption drain path
  (``elastic/preempt.py``): a spot notice / SIGTERM triggered a bounded
  grace commit and a clean exit. NOT a failure — the verdict exists so a
  drained host is never misreported as a dead rank.
* ``healthy``      — every rank dumped via clean exit paths with nothing
  left open.

``hvdrun`` runs this automatically when a job exits non-zero and dumps
are present (opt out with ``--no-doctor``).
"""

import argparse
import json
import os
import sys

from horovod_tpu.diag import desync as desync_lib
from horovod_tpu.diag.recorder import DUMP_PREFIX

TIMELINE_EVENTS_PER_RANK = 12
CLEAN_REASONS = ("exit", "shutdown")


def find_dumps(logdir):
    """All ``flightrec.rank*.json`` paths under ``logdir`` (recursive —
    elastic jobs write per-epoch subdirectories)."""
    out = []
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if f.startswith(DUMP_PREFIX) and f.endswith(".json") \
                    and ".tmp." not in f:
                out.append(os.path.join(root, f))
    return sorted(out)


def load_dumps(logdir):
    """Parse dumps; on duplicate ranks (elastic epochs) keep the most
    recent by wall clock. Returns ``(dumps_by_rank, skipped_paths)``."""
    dumps, skipped = {}, []
    for path in find_dumps(logdir):
        try:
            with open(path) as f:
                d = json.load(f)
            if not d.get("flightrec"):
                raise ValueError("not a flight-recorder dump")
        except (OSError, ValueError) as e:
            skipped.append((path, str(e)))
            continue
        d["_path"] = path
        r = int(d.get("rank", -1))
        prev = dumps.get(r)
        if prev is None or (d.get("clock", {}).get("wall", 0)
                            >= prev.get("clock", {}).get("wall", 0)):
            dumps[r] = d
    return dumps, skipped


def _parked(dump):
    """(seq, op) of the collective this rank is parked in, or None: the
    highest-seq eager entry without a matching exit."""
    open_c = dump.get("open_collectives") or {}
    if not open_c:
        return None
    seq = max(int(s) for s in open_c)
    return seq, open_c[str(seq)]


def _last_event(dump, kinds=None):
    for ev in reversed(dump.get("events") or []):
        if kinds is None or ev.get("k") in kinds:
            return ev
    return None


def _data_state(dump):
    """The input-pipeline picture of one rank's dump: the producer batch
    still open (``data`` B with no matching E — the prefetch thread was
    mid-assembly when the dump fired) and the consumer stall still open
    (``data_wait`` B with no E — the TRAINING thread was starved). The
    loaders emit both (horovod_tpu/data/loader.py), which is what lets
    a data-stall verdict indict a named producer instead of guessing."""
    open_batch = open_wait = None
    for ev in dump.get("events") or []:
        k = ev.get("k")
        if k == "data":
            open_batch = ev if ev.get("ph") == "B" else None
        elif k == "data_wait":
            open_wait = ev if ev.get("ph") == "B" else None
    return open_batch, open_wait


def _open_ckpt_saves(dump):
    """Checkpoint steps this rank BEGAN saving (``ckpt`` ph=B) with no
    matching commit/failure (ph=E) in the ring: saves the crash
    interrupted. Their manifest was never written, so restore falls back
    to the previous complete step — worth saying out loud. Paired in
    event order, not by set membership: a step saved twice (failed or
    torn once, re-saved after restore) is open again after its later
    B, no matter how its first attempt ended."""
    open_ = {}
    for ev in dump.get("events") or []:
        if ev.get("k") != "ckpt":
            continue
        if ev.get("ph") == "B" and ev.get("step") is not None:
            open_[ev["step"]] = ev
        elif ev.get("ph") == "E":
            open_.pop(ev.get("step"), None)
    return sorted(open_)


def diagnose(dumps, expected_size=None):
    """Build the report dict from ``{rank: dump}`` (see
    :func:`load_dumps`). Pure function of the dumps — unit-testable with
    synthesized recorders on a fake clock."""
    ranks = sorted(dumps)
    expected = expected_size or max(
        [d.get("size", 0) for d in dumps.values()] + [len(dumps)])
    dead = [r for r in range(expected) if r not in dumps]

    per_rank = {}
    for r in ranks:
        d = dumps[r]
        last = _last_event(d, kinds=("coll", "step", "epoch", "heartbeat"))
        failed = None
        if (last and last.get("k") == "coll" and last.get("ph") == "E"
                and last.get("ok") is False):
            failed = (last.get("seq"), last.get("op"))
        open_batch, open_wait = _data_state(d)
        preempt_ev = _last_event(d, kinds=("preempt",))
        evicted_rank = (preempt_ev is not None
                        or "preempt" in (d.get("dump_reasons") or []))
        per_rank[r] = {
            "seq": d.get("collective_seq", 0),
            "completed": d.get("last_completed_seq", 0),
            "parked": _parked(d),
            "failed": failed,
            "last_event": last,
            "data_open": open_batch,
            "data_wait_open": open_wait,
            "preempt": preempt_ev,
            "evicted": evicted_rank,
            "dump_reasons": d.get("dump_reasons") or [],
            "config_crc": d.get("config_crc"),
            "host": d.get("host"),
            "path": d.get("_path"),
        }

    completed = [i["completed"] for i in per_rank.values()]
    entered = [i["seq"] for i in per_rank.values()]
    last_common = (min(completed) if any(completed)
                   else (min(entered) if entered else 0))

    digest_view = desync_lib.cross_check(
        {r: dumps[r].get("digest") or {} for r in ranks})

    crcs = {i["config_crc"] for i in per_rank.values()
            if i["config_crc"] is not None}
    config_mismatch = sorted(crcs) if len(crcs) > 1 else None

    parked = {r: i["parked"] for r, i in per_rank.items() if i["parked"]}
    clean = [r for r, i in per_rank.items()
             if not i["parked"]
             and any(x in CLEAN_REASONS for x in i["dump_reasons"])]

    evicted = sorted(r for r, i in per_rank.items() if i["evicted"])
    cause, why = _classify(expected, dead, digest_view, per_rank, parked,
                           clean, evicted)

    interrupted_saves = {}
    for r in ranks:
        pend = _open_ckpt_saves(dumps[r])
        if pend:
            interrupted_saves[r] = pend

    timeline = []
    for r in ranks:
        for ev in (dumps[r].get("events") or [])[-TIMELINE_EVENTS_PER_RANK:]:
            timeline.append({"rank": r, **ev})
    timeline.sort(key=lambda ev: ev.get("t", 0))

    return {
        "expected_size": expected,
        "ranks_with_dumps": ranks,
        "dead_ranks": dead,
        "last_common_seq": last_common,
        "per_rank": per_rank,
        "desync": digest_view,
        "config_mismatch": config_mismatch,
        "classification": cause,
        "explanation": why,
        "evicted_ranks": evicted,
        "interrupted_saves": interrupted_saves,
        "timeline": timeline,
    }


def _classify(expected, dead, digest_view, per_rank, parked, clean,
              evicted=()):
    parked_ops = sorted({op for _s, op in parked.values()})
    failed = {r: i["failed"] for r, i in per_rank.items()
              if i.get("failed")}
    if dead:
        why = f"rank(s) {dead} left no flight-recorder dump (hard-killed: " \
              "SIGKILL/OOM leave no black box)"
        if parked:
            seqs = sorted({s for s, _op in parked.values()})
            why += (f"; surviving rank(s) {sorted(parked)} are parked in "
                    f"{'/'.join(parked_ops)} (seq {seqs[-1]}) waiting for "
                    "them")
        if failed:
            ops = sorted({op for _s, op in failed.values()})
            why += (f"; rank(s) {sorted(failed)} saw {'/'.join(ops)} fail "
                    "under them when the peer vanished")
        return "dead rank", why
    if digest_view.get("desynced"):
        return "desync", digest_view.get("detail") or (
            f"ranks {digest_view['desynced']} diverged from the majority "
            "collective schedule")
    if evicted:
        # planned drain, not a failure: the eviction dump is the proof
        # the rank exited on purpose — never report it as dead/hung
        kinds, outcomes = [], []
        for r in evicted:
            ev = per_rank[r].get("preempt") or {}
            if ev.get("kind"):
                kinds.append(str(ev["kind"]))
            if ev.get("outcome"):
                outcomes.append(f"rank {r}: {ev['outcome']}")
        why = (f"rank(s) {list(evicted)} ran the graceful-eviction path "
               "(preemption notice -> bounded grace commit -> clean "
               "exit; elastic/preempt.py)")
        if kinds:
            why += f"; notice kind(s): {'/'.join(sorted(set(kinds)))}"
        if outcomes:
            why += f"; commit outcome(s): {', '.join(outcomes)}"
        bystanders = sorted(set(parked) - set(evicted))
        if bystanders:
            why += (f"; rank(s) {bystanders} were parked in "
                    f"{'/'.join(parked_ops)} awaiting the next rendezvous "
                    "when their dump fired")
        return "graceful eviction", why
    if len(clean) == len(per_rank) and per_rank:
        return "healthy", "every rank dumped on a clean exit path with " \
                          "no collective left open"
    if parked and len(parked) < len(per_rank):
        idle = sorted(set(per_rank) - set(parked))
        for r in idle:
            last = per_rank[r]["last_event"] or {}
            if last.get("k") == "step" and last.get("ph") == "B":
                return "compile stall", (
                    f"rank {r} entered step {last.get('step')} and emitted "
                    f"no collective since, while rank(s) {sorted(parked)} "
                    f"wait in {'/'.join(parked_ops)}: stuck compiling or "
                    "dispatching")
        detail = []
        for r in idle:
            wait = per_rank[r].get("data_wait_open")
            prod = per_rank[r].get("data_open")
            if wait:
                detail.append(
                    f"rank {r}'s training thread was starved waiting on "
                    f"batch {wait.get('batch')} of epoch "
                    f"{wait.get('epoch')} from its "
                    f"{wait.get('source')} producer")
            if prod:
                detail.append(
                    f"rank {r}'s producer ({prod.get('source')}) was "
                    f"still assembling epoch {prod.get('epoch')} batch "
                    f"{prod.get('batch')} when the dump fired")
        why = (
            f"rank(s) {idle} finished their last step and never entered "
            f"the next collective (input pipeline starved) while rank(s) "
            f"{sorted(parked)} wait in {'/'.join(parked_ops)}")
        if detail:
            why += "; " + "; ".join(detail)
        return "data stall", why
    if parked:
        seqs = sorted({s for s, _op in parked.values()})
        return "collective hang", (
            f"every rank is parked in {'/'.join(parked_ops)} "
            f"(seq {seqs[-1]}) with no dead or desynced rank: suspect the "
            "transport/runtime under the collective")
    return "unknown", "no dead, desynced, parked or cleanly-exited " \
                      "pattern matched; read the timeline below"


def _fmt_event(ev):
    parts = [f"{ev.get('t', 0):.6f}", f"rank {ev.get('rank')}",
             str(ev.get("k"))]
    for key in ("ph", "seq", "op", "name", "step", "reason", "signum",
                "epoch", "batch", "source", "kind", "outcome"):
        if ev.get(key) is not None:
            parts.append(f"{key}={ev[key]}")
    if ev.get("ok") is False:
        parts.append("ERROR")
    return "  ".join(parts)


def format_report(report):
    lines = []
    add = lines.append
    add("==== horovod_tpu doctor report " + "=" * 34)
    add(f"ranks expected: {report['expected_size']}, dumps found: "
        f"{len(report['ranks_with_dumps'])} "
        f"(ranks {report['ranks_with_dumps']})")
    if report["dead_ranks"]:
        add("DEAD (no flight-recorder dump): rank(s) "
            + ", ".join(str(r) for r in report["dead_ranks"]))
    add(f"last common collective_seq: {report['last_common_seq']}")
    for r, info in sorted(report["per_rank"].items()):
        state = ""
        if info.get("evicted"):
            ev = info.get("preempt") or {}
            state = ("EVICTED"
                     + (f" ({ev.get('kind')}" if ev.get("kind") else "")
                     + (f", commit {ev['outcome']})" if ev.get("outcome")
                        else (")" if ev.get("kind") else "")))
        elif info["parked"]:
            seq, op = info["parked"]
            state = f"PARKED in {op} (seq {seq})"
        elif info.get("failed"):
            seq, op = info["failed"]
            state = f"FAILED in {op} (seq {seq})"
        else:
            last = info["last_event"] or {}
            state = (f"last event: {last.get('k')}"
                     + (f" {last.get('ph')}" if last.get("ph") else "")
                     + (f" step={last.get('step')}"
                        if last.get("step") is not None else ""))
        add(f"rank {r}: seq entered {info['seq']}, completed "
            f"{info['completed']}; {state}; dump reasons "
            f"{info['dump_reasons']}")
    if report["desync"].get("desynced"):
        add("DESYNC: " + (report["desync"].get("detail") or
                          str(report["desync"]["desynced"])))
    if report.get("config_mismatch"):
        add("CONFIG MISMATCH: ranks ran with differing config "
            f"fingerprints {report['config_mismatch']} — check HOROVOD_* "
            "env parity")
    for r, steps in sorted((report.get("interrupted_saves") or {}).items()):
        add(f"INTERRUPTED CHECKPOINT SAVE: rank {r} was mid-save of "
            f"step(s) {steps} when the job died — no manifest was "
            "committed, so restore falls back to the last complete "
            "checkpoint (the torn dir is ignored and later GC'd)")
    add(f"probable cause: {report['classification']} — "
        f"{report['explanation']}")
    add("timeline (clock-aligned, last events per rank):")
    for ev in report["timeline"]:
        add("  " + _fmt_event(ev))
    add("=" * 66)
    return "\n".join(lines)


def run(logdir, expected_size=None, stream=None):
    """Load dumps under ``logdir``, print the report. Returns the report
    dict, or None when no dumps exist."""
    stream = stream or sys.stderr
    dumps, skipped = load_dumps(logdir)
    for path, err in skipped:
        print(f"doctor: skipping {path}: {err}", file=stream)
    if not dumps:
        print(f"doctor: no {DUMP_PREFIX}*.json dumps under {logdir}",
              file=stream)
        return None
    report = diagnose(dumps, expected_size=expected_size)
    print(format_report(report), file=stream)
    return report


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.diag.doctor",
        description="Aggregate per-rank flight-recorder dumps into a "
                    "hang/crash report.")
    p.add_argument("logdir", help="directory containing "
                                  "flightrec.rank*.json dumps (searched "
                                  "recursively)")
    p.add_argument("--expected-size", type=int, default=None,
                   help="world size to check for missing ranks (default: "
                        "from the dumps)")
    p.add_argument("--json", action="store_true",
                   help="print the report dict as JSON on stdout (the "
                        "human-readable report moves to stderr)")
    args = p.parse_args(argv)
    report = run(args.logdir, expected_size=args.expected_size,
                 stream=sys.stderr if args.json else sys.stdout)
    if report is not None and args.json:
        import json as _json
        print(_json.dumps(report, indent=2, sort_keys=True, default=str))
    return 2 if report is None else 0


def _perf_main(argv):
    from horovod_tpu.telemetry import report
    return report.main(argv)


def _serve_main(argv):
    from horovod_tpu.diag import serve_doctor
    return serve_doctor.main(argv)


def _xray_main(argv):
    from horovod_tpu.diag import xray
    return xray.main(argv)


# ONE dispatch table for every doctor, all sharing the same
# conventions: a dump-dir positional, --json for machine output (report
# prose moves to stderr), exit 2 when the dir holds nothing readable
SUBCOMMANDS = {
    "hang": main,          # flight-recorder hang/crash report (default)
    "perf": _perf_main,    # goodput-ledger host-time attribution
    "serve": _serve_main,  # per-request tail-latency attribution
    "xray": _xray_main,    # compiled-step device-time attribution
}


def doctor_cli(argv=None):
    """The ``hvd-doctor`` entry point — ``hvd-doctor <subcommand>
    <dir> [--json]`` with the subcommands in :data:`SUBCOMMANDS`;
    a bare ``hvd-doctor <dir>`` keeps meaning ``hang`` (the original
    interface)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in SUBCOMMANDS:
        return SUBCOMMANDS[argv[0]](argv[1:])
    return main(argv)


if __name__ == "__main__":
    sys.exit(main())
