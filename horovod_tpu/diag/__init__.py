"""Black-box crash forensics: flight recorder + collective desync doctor.

The reference's sharpest debugging tools only speak while the process is
alive: the stall inspector names the ranks missing from a pending
collective (``horovod/common/stall_inspector.cc``) and the controller
negotiation rejects shape/dtype mismatches before they hang
(``controller.cc:55-346``). The telemetry plane (``horovod_tpu.telemetry``)
has the same limitation — a SIGKILLed rank or a wedged TPU runtime takes
its metrics with it. This package is the piece every production trainer
needs and live telemetry cannot provide: post-mortem answers to *which
rank died, in which collective, and who was left waiting* without
re-running the job.

Three parts:

* :mod:`~horovod_tpu.diag.recorder` — a per-rank bounded, lock-cheap ring
  buffer of structured events (collective entry/exit with op/name/shape/
  dtype and a per-rank ``collective_seq``, step boundaries, rendezvous
  epochs, heartbeats, config fingerprint), dumped to
  ``flightrec.rank<r>.json`` on crash (``sys.excepthook`` +
  SIGTERM/SIGABRT + ``atexit``), on stall-inspector firing, and on demand
  via the telemetry endpoint ``GET /flightrec``.
* :mod:`~horovod_tpu.diag.desync` — ranks publish a compact rolling digest
  (``seq`` + a hash of the op/name/shape schedule) on the elastic KV
  heartbeats; the driver's cluster view cross-checks digests so a rank
  that diverged in collective order (or stopped advancing) is named
  *while the job hangs*.
* :mod:`~horovod_tpu.diag.doctor` — ``hvdrun --doctor <logdir>`` (and
  ``python -m horovod_tpu.diag.doctor``) aggregates per-rank dumps into
  one human-readable hang report: last common ``collective_seq``, the
  collective each straggler is parked in, ranks with no dump
  (hard-killed), a clock-aligned last-event timeline, and a
  probable-cause classification (dead rank / desync / data stall /
  compile stall).

Hot-path discipline: recording is a bounded deque append plus a CRC
update — no I/O, no locks — and the recorder never touches the traced
computation, so compiled programs are byte-identical whether the
recorder is installed or not (asserted by ``tests/test_diag.py``).
"""

from horovod_tpu.diag.recorder import (FlightRecorder, config_fingerprint,
                                       dump_now, get_recorder, install,
                                       uninstall)
from horovod_tpu.diag import desync

# NOTE: doctor is deliberately NOT imported here — `python -m
# horovod_tpu.diag.doctor` must not find the module pre-imported by its
# own package (runpy RuntimeWarning); import it as
# `from horovod_tpu.diag import doctor`.

__all__ = ["FlightRecorder", "config_fingerprint", "dump_now",
           "get_recorder", "install", "uninstall", "desync"]
