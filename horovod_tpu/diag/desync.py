"""Cross-rank digest comparison: name the diverged rank while it hangs.

Every rank's flight recorder maintains a rolling CRC chain over the
op/name/shape/dtype sequence of its collective dispatches
(:mod:`~horovod_tpu.diag.recorder`). Two ranks that dispatched the same
schedule hold identical ``(seq, hash)`` pairs; the first divergent
dispatch forks the chain forever after. Ranks publish the compact digest
on the elastic KV heartbeats (``elastic/worker.py``); the driver's
cluster view feeds the collected digests through :func:`cross_check` —
the launcher-side mirror of the reference controller's shape/dtype
mismatch rejection (``controller.cc:55-346``), but one that works
post-hoc and for the compiled plane (whose schedule is recorded at trace
time).

The same function powers the doctor's offline analysis of per-rank
dumps, so online (hang in progress) and post-mortem (dumps on disk)
diagnosis cannot disagree about what a desync is.
"""


def _hist_map(digest):
    """``seq -> hash`` for one rank's digest (history + current)."""
    out = {}
    for pair in digest.get("hist") or []:
        try:
            s, h = pair
            out[int(s)] = int(h)
        except (TypeError, ValueError):
            continue
    if digest.get("seq") is not None and digest.get("hash") is not None:
        out[int(digest["seq"])] = int(digest["hash"])
    return out


def cross_check(digests, prev=None):
    """Compare per-rank schedule digests.

    ``digests`` is ``{rank: {"seq", "hash", "hist"}}``; ``prev`` is the
    previous call's ``digests`` (optional) for stopped-advancing
    detection. Returns::

        {"seqs": {rank: seq},
         "last_common_seq": int | None,   # highest seq seen by ALL ranks
         "desynced": [rank, ...],         # hash minority at that seq
         "stuck": [rank, ...],            # seq frozen while others moved
         "detail": str | None}

    Desync naming is majority-vote: at the highest seq present in every
    rank's (bounded) history, ranks whose hash disagrees with the largest
    agreeing group are named. Ranks so far apart that their histories no
    longer overlap produce no hash verdict — they show up through
    ``stuck``/progress instead.
    """
    digests = {int(r): d for r, d in digests.items() if d}
    out = {"seqs": {r: int(d.get("seq", 0)) for r, d in digests.items()},
           "last_common_seq": None, "desynced": [], "stuck": [],
           "detail": None}
    if len(digests) < 2:
        return out
    maps = {r: _hist_map(d) for r, d in digests.items()}
    common = set.intersection(*[set(m) for m in maps.values()])
    common.discard(0)
    if common:
        s = max(common)
        out["last_common_seq"] = s
        groups = {}
        for r, m in maps.items():
            groups.setdefault(m[s], []).append(r)
        if len(groups) > 1:
            # the largest group is "the schedule"; deterministic
            # tie-break by lowest member rank
            majority = max(groups.values(),
                           key=lambda rs: (len(rs), -min(rs)))
            out["desynced"] = sorted(r for rs in groups.values()
                                     if rs is not majority for r in rs)
            out["detail"] = (
                f"collective schedules diverged at seq {s}: "
                + "; ".join(
                    f"ranks {sorted(rs)} hash {h:#010x}"
                    for h, rs in sorted(groups.items(),
                                        key=lambda kv: sorted(kv[1]))))
    if prev:
        prev_seqs = {int(r): int(d.get("seq", 0))
                     for r, d in prev.items() if d}
        moved = [r for r, s in out["seqs"].items()
                 if s > prev_seqs.get(r, 0)]
        if moved:
            out["stuck"] = sorted(
                r for r, s in out["seqs"].items()
                if r in prev_seqs and s == prev_seqs[r] and r not in moved)
    return out
