"""Training callbacks + LR schedule helpers.

Rebuilds the reference's Keras callback suite
(``horovod/_keras/callbacks.py:20-185``) in two idiomatic forms:

* **Callback objects** with ``on_train_begin/on_epoch_begin/on_epoch_end``
  hooks for imperative loops (the torch adapter, or custom JAX loops).
  LR-mutating callbacks operate on anything exposing ``param_groups``
  (torch optimizers, incl. our DistributedOptimizer wrapper).
* **optax schedule builders** (``warmup_schedule``, ``lr_schedule``) — the
  compiled-world equivalent: the schedule is baked into the optimizer
  rather than mutated per epoch.
"""

import numpy as np


class Callback:
    def on_train_begin(self, ctx=None):
        pass

    def on_epoch_begin(self, epoch, ctx=None):
        pass

    def on_epoch_end(self, epoch, metrics=None, ctx=None):
        return metrics

    def on_batch_begin(self, batch, ctx=None):
        pass

    def on_batch_end(self, batch, ctx=None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial model/optimizer state from root at train start
    (reference ``_keras/callbacks.py:20-45``; torch equivalent
    ``broadcast_parameters``). ``ctx`` is a dict with any of
    ``model`` (torch nn.Module) / ``optimizer`` / ``params`` (pytree)."""

    def __init__(self, root_rank=0):
        self.root_rank = root_rank

    def on_train_begin(self, ctx=None):
        ctx = ctx or {}
        model = ctx.get("model")
        if model is not None:
            from horovod_tpu import torch as hvd_torch
            hvd_torch.broadcast_parameters(model.state_dict(),
                                           self.root_rank)
        optimizer = ctx.get("optimizer")
        if optimizer is not None:
            from horovod_tpu import torch as hvd_torch
            hvd_torch.broadcast_optimizer_state(optimizer, self.root_rank)
        params = ctx.get("params")
        if params is not None:
            from horovod_tpu import hvd_jax
            ctx["params"] = hvd_jax.broadcast_variables(
                params, root_rank=self.root_rank)
        return ctx


class MetricAverageCallback(Callback):
    """Average epoch metrics over all ranks (reference
    ``_keras/callbacks.py:46-85``).

    Delegates to ``hvd.allreduce_metrics`` so nested metric pytrees and
    non-numeric values (which pass through unchanged) behave identically
    on both surfaces; numeric leaves come back as Python floats like the
    reference callback writes back into ``logs``."""

    def on_epoch_end(self, epoch, metrics=None, ctx=None):
        if not metrics:
            return metrics
        from horovod_tpu import hvd_jax

        reduced = hvd_jax.allreduce_metrics(metrics)

        def _to_float(x):
            return (float(np.asarray(x))
                    if hasattr(x, "dtype") or isinstance(x, (int, float))
                    else x)

        import jax
        return jax.tree_util.tree_map(_to_float, reduced)


def _set_lr(optimizer, lr):
    for group in optimizer.param_groups:
        group["lr"] = lr


class LearningRateWarmupCallback(Callback):
    """Ramp LR from ``initial_lr`` to ``initial_lr * size`` over the first
    ``warmup_epochs`` (the linear-scaling warmup of Goyal et al., reference
    ``_keras/callbacks.py:86-140``). Interpolates within epochs when
    ``steps_per_epoch`` is given."""

    def __init__(self, optimizer, initial_lr, warmup_epochs=5,
                 steps_per_epoch=None, verbose=False):
        from horovod_tpu import basics
        self.optimizer = optimizer
        self.initial_lr = initial_lr
        self.target_lr = initial_lr * basics.size()
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._epoch = 0

    def _lr_at(self, progress):
        if progress >= self.warmup_epochs:
            return self.target_lr
        frac = progress / self.warmup_epochs
        return self.initial_lr + (self.target_lr - self.initial_lr) * frac

    def on_epoch_begin(self, epoch, ctx=None):
        self._epoch = epoch
        if self.steps_per_epoch is None:
            _set_lr(self.optimizer, self._lr_at(epoch))

    def on_batch_begin(self, batch, ctx=None):
        if self.steps_per_epoch is not None:
            _set_lr(self.optimizer,
                    self._lr_at(self._epoch + batch / self.steps_per_epoch))


class LearningRateScheduleCallback(Callback):
    """Multiply base LR by ``multiplier(epoch)`` from ``start_epoch`` on
    (reference ``_keras/callbacks.py:141-185``)."""

    def __init__(self, optimizer, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True):
        self.optimizer = optimizer
        self.multiplier = (multiplier if callable(multiplier)
                           else (lambda _: multiplier))
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.base_lr = optimizer.param_groups[0]["lr"]

    def on_epoch_begin(self, epoch, ctx=None):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        e = int(epoch) if self.staircase else epoch
        _set_lr(self.optimizer, self.base_lr * self.multiplier(e))


# ---------------------------------------------------------------------------
# optax schedule builders — the compiled-path equivalents
# ---------------------------------------------------------------------------


def warmup_schedule(base_lr, size=None, warmup_steps=1000):
    """optax schedule: linear ramp from base_lr to base_lr*size, then flat
    (LearningRateWarmupCallback, compiled)."""
    import optax

    from horovod_tpu import basics
    if size is None:
        size = basics.size() if basics.is_initialized() else 1
    return optax.join_schedules(
        [optax.linear_schedule(base_lr, base_lr * size, warmup_steps),
         optax.constant_schedule(base_lr * size)],
        boundaries=[warmup_steps])


def lr_schedule(base_lr, boundaries_and_scales):
    """optax schedule: piecewise-constant decay
    (LearningRateScheduleCallback, compiled). ``boundaries_and_scales``
    maps step -> multiplicative scale, e.g. {30_000: 0.1, 60_000: 0.1}."""
    import optax
    return optax.piecewise_constant_schedule(base_lr, boundaries_and_scales)
