"""Keras-on-TF helpers (reference: ``horovod/keras/__init__.py``).

``DistributedOptimizer`` wraps a keras optimizer so its gradients are
allreduced before the update (reference ``_impl.create_distributed_
optimizer``, ``horovod/_keras/__init__.py:23-55``), and ``load_model``
restores a saved model while transparently re-wrapping whatever
optimizer it was trained with (reference ``keras/__init__.py:117-150``)
— that is what makes rank-0-restore + broadcast resume work for Keras
models, since the optimizer slot weights come back with the model.

Keras 3 removed ``get_gradients`` from optimizers; the update hook is
``apply(grads, trainable_variables)`` (``apply_gradients`` delegates to
it, and ``model.fit``'s traced train step calls ``apply_gradients``), so
on Keras 3 the distributed subclass overrides ``apply``. Legacy Keras 2
optimizers (and the test fake) still expose ``get_gradients``, which is
overridden when present. Wrapping mutates the optimizer's class in
place (``__class__`` swap to a dynamic subclass) instead of rebuilding
it via ``from_config``, so slot variables and iteration counters of an
already-live optimizer survive wrapping.

The gradient allreduce itself rides ``horovod_tpu.tensorflow.allreduce``
which is graph-capable on real TF (``tf.numpy_function`` +
``tf.custom_gradient``), so wrapped optimizers work inside the
``tf.function``-compiled ``model.fit`` path.
"""

import tensorflow as tf

from horovod_tpu.ops.reduction import Average
# allgather/allreduce/broadcast/broadcast_global_variables are re-exported
# here the way the reference's keras namespace does (keras/__init__.py),
# so pure-Keras scripts never import horovod.tensorflow directly
from horovod_tpu.tensorflow import (Compression,  # noqa: F401
                                    _allreduce_grads, allgather, allreduce,
                                    broadcast, broadcast_global_variables,
                                    size)

from horovod_tpu.tensorflow import callbacks  # noqa: F401  (re-export)


def _make_distributed_class(cls, op=Average, compression=Compression.none,
                            sparse_as_dense=False, name=None):
    """Build a ``Distributed<Opt>`` subclass of ``cls`` whose gradient
    hook allreduces before delegating. Also used as the deserialization
    target in ``load_model`` (a real class, so Keras 3's
    ``deserialize_keras_object`` can call ``from_config`` on it)."""
    prefix = name or f"Distributed{cls.__name__}"
    ns = {"_hvd_wrapped": cls}

    def _reduce(grads):
        return _allreduce_grads(list(grads), op, compression,
                                sparse_as_dense, prefix)

    if hasattr(cls, "apply"):  # Keras 3
        def apply(self, grads, trainable_variables=None):
            if size() <= 1:
                return super(dist_cls, self).apply(grads,
                                                   trainable_variables)
            return super(dist_cls, self).apply(_reduce(grads),
                                               trainable_variables)
        ns["apply"] = apply

    if hasattr(cls, "get_gradients"):  # Keras 2 / legacy
        def get_gradients(self, loss, params):
            grads = super(dist_cls, self).get_gradients(loss, params)
            if size() <= 1:
                return grads
            return _reduce(grads)
        ns["get_gradients"] = get_gradients

    dist_cls = type(prefix, (cls,), ns)
    return dist_cls


def DistributedOptimizer(optimizer, name=None, op=Average,
                         compression=Compression.none,
                         sparse_as_dense=False):
    """Wrap a keras optimizer in place: its class becomes a dynamic
    ``Distributed*`` subclass whose update hook allreduces gradients.
    All existing state (slot variables, iterations) is preserved —
    unlike a ``from_config`` rebuild, this is safe on an optimizer that
    has already taken steps."""
    if getattr(type(optimizer), "_hvd_wrapped", None) is not None:
        return optimizer  # already wrapped
    optimizer.__class__ = _make_distributed_class(
        type(optimizer), op=op, compression=compression,
        sparse_as_dense=sparse_as_dense, name=name)
    return optimizer


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """``tf.keras.models.load_model`` with every known optimizer class
    mapped to its Distributed subclass (reference
    ``keras/__init__.py:146-150`` ``wrap_optimizer``). Both the bare
    name (``SGD``) and the wrapped name (``DistributedSGD``) resolve, so
    models saved before or after wrapping round-trip."""
    objects = {}

    def add(cls):
        dist = _make_distributed_class(cls, compression=compression)
        objects[cls.__name__] = dist
        objects[dist.__name__] = dist

    opt_mod = tf.keras.optimizers
    for attr in dir(opt_mod):
        cls = getattr(opt_mod, attr)
        if isinstance(cls, type) and not attr.startswith("_"):
            add(cls)
    for cls in (custom_optimizers or []):
        add(cls)
    objects.update(custom_objects or {})
    return tf.keras.models.load_model(filepath, custom_objects=objects)
