"""TensorFlow adapter (reference: ``horovod/tensorflow/__init__.py``).

Eager-mode TF2 + TF1-style optimizer wrapping over the native core's host
data plane, mirroring the torch adapter: tensors bridge through numpy
into the name-negotiated queue (reference role: the custom
``HorovodAllreduceOp`` kernels, ``tensorflow/mpi_ops.cc:287-460``).

Covered contracts:

* ``allreduce`` with the **IndexedSlices → two-allgathers** fallback
  (reference ``__init__.py:43-118``: sparse gradients allgather values
  and indices instead of reducing dense zeros),
* fp16 wire compression on the dense path (reference Compression),
* **graph mode with registered gradients**: on a real TF, the dense
  collectives route through ``tf.numpy_function`` wrapped in
  ``tf.custom_gradient``, so they work inside ``tf.function`` (Keras 3
  traces ``model.fit``'s train step) and are differentiable — the role
  of the reference's ``AsyncOpKernel`` + gradient registrations
  (``tensorflow/mpi_ops.cc:287-460``, ``mpi_ops.py``):
  grad(allreduce) = allreduce(grad); grad(allgather) = allreduce(grad)
  sliced to the local rows; grad(broadcast) = summed grad on root,
  zeros elsewhere,
* ``DistributedOptimizer`` overriding ``compute_gradients`` (reference
  ``__init__.py:266-311``) with ``sparse_as_dense`` option,
* ``DistributedGradientTape`` for TF2 eager (``__init__.py:475-531``),
* ``broadcast_variables`` / ``broadcast_global_variables``
  (``__init__.py:139-188``),
* ``horovod_tpu.tensorflow.keras.load_model`` wrapping saved optimizers
  in DistributedOptimizer (reference ``keras/__init__.py:117-150``).

The adapter runs against real TF (``tests/test_tf_real.py`` — eager,
``tf.function``, Keras 3 ``model.fit``) when tensorflow is importable,
and against the numpy-backed stand-in (``tests/fake_tensorflow.py``)
otherwise; the fake keeps in-image coverage when TF is absent.
"""

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover - TF absent in this image
    raise ImportError(
        "horovod_tpu.tensorflow requires tensorflow, which is not "
        "installed. On TPU, prefer the JAX-native API (import horovod_tpu "
        "as hvd) — it is the compiled, first-class path.") from e

import numpy as np

from horovod_tpu.basics import (cross_rank, cross_size, init,
                                is_initialized, local_rank, local_size,
                                rank, shutdown, size)
from horovod_tpu.ops.reduction import Adasum, Average, Max, Min, Sum

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size",
    "Sum", "Average", "Adasum", "Min", "Max", "Compression",
    "allreduce", "allgather", "broadcast", "broadcast_variables",
    "broadcast_global_variables", "BroadcastGlobalVariablesHook",
    "DistributedGradientTape",
    "DistributedOptimizer",
]


class Compression:
    """fp16 wire compression (reference ``tensorflow/compression.py``)."""

    class none:
        @staticmethod
        def compress(arr):
            return arr, arr.dtype

        @staticmethod
        def decompress(arr, dtype):
            return arr

    class fp16:
        @staticmethod
        def compress(arr):
            if arr.dtype in (np.float32, np.float64):
                return arr.astype(np.float16), arr.dtype
            return arr, arr.dtype

        @staticmethod
        def decompress(arr, dtype):
            return arr.astype(dtype) if arr.dtype != dtype else arr


def _ensure_core():
    from horovod_tpu import _core, basics
    if not basics.is_initialized():
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init()")
    if not _core.is_initialized():
        _core.init(rank=0, size=1)
    return _core

_counters = {}


def _auto_name(kind, name):
    if name is not None:
        return name
    n = _counters.get(kind, 0)
    _counters[kind] = n + 1
    return f"tf.{kind}.{n}"


def _to_numpy(tensor):
    if hasattr(tensor, "numpy"):
        return np.asarray(tensor.numpy())
    return np.asarray(tensor)


# Real TF exposes the three pieces the graph bridge needs; the test fake
# does not, and falls back to the plain eager numpy path below.
_GRAPH_OK = all(hasattr(tf, a) for a in
                ("numpy_function", "custom_gradient", "executing_eagerly"))


def _bridge(host_fn, x, out_shape, *extra):
    """Run ``host_fn(np.ndarray, ...) -> np.ndarray`` on ``x`` (plus any
    ``extra`` tensors) in either execution mode: direct in eager, via
    ``tf.numpy_function`` under ``tf.function`` (the host data plane is
    CPU-side either way, exactly like the reference's AsyncOpKernel
    handing the tensor to the background loop)."""
    if tf.executing_eagerly():
        return tf.convert_to_tensor(host_fn(
            np.asarray(x.numpy()),
            *(np.asarray(e.numpy()) for e in extra)))
    y = tf.numpy_function(host_fn, [x, *extra], x.dtype)
    y.set_shape(out_shape)
    return y


def _graph_allreduce(tensor, name, op, compression):
    """Differentiable allreduce (reference ``_allreduce_grad``,
    ``tensorflow/mpi_ops.py``: the gradient of an allreduce is an
    allreduce of the gradient with the same op)."""
    core = _ensure_core()

    def _host(arr, wire_name):
        arr = np.asarray(arr)
        c, dt = compression.compress(arr)
        out = core.allreduce(c, wire_name, op=op)
        # the host core flattens 0-d tensors to (1,); restore the shape
        return np.asarray(compression.decompress(np.asarray(out), dt),
                          dtype=arr.dtype).reshape(arr.shape)

    @tf.custom_gradient
    def _fn(x):
        y = _bridge(lambda a: _host(a, name), x, x.shape)

        def grad(dy):
            return _bridge(lambda a: _host(a, name + ".grad"), dy,
                           dy.shape)
        return y, grad

    return _fn(tf.convert_to_tensor(tensor))


def _graph_allgather(tensor, name):
    """Differentiable allgather. Backward is the reference's
    ``HorovodAllgatherGrad``: allreduce-sum the gathered-output gradient,
    then slice out the rows this rank contributed."""
    core = _ensure_core()

    def _host_fwd(arr):
        return np.asarray(core.allgather(np.asarray(arr), name))

    def _host_grad(dy, xshape):
        # shape metadata comes from the input's dynamic shape flowing
        # through THIS execution (a tiny int vector passed as a second
        # op input), never from trace-time closure state — concurrent
        # invocations of one traced function each see their own shapes,
        # and the full forward activation is never retained for it
        dy = np.asarray(dy)
        xshape = tuple(int(d) for d in np.asarray(xshape))
        nrows = xshape[0] if xshape else 1
        sizes = np.asarray(core.allgather(
            np.array([nrows], np.int64), name + ".grad.nrows"))
        g = np.asarray(core.allreduce(dy, name + ".grad", op=Sum))
        offset = int(sizes[:rank()].sum())
        return np.ascontiguousarray(
            g[offset:offset + nrows]).reshape(xshape)

    @tf.custom_gradient
    def _fn(x):
        y = _bridge(_host_fwd, x, [None] + list(x.shape[1:]))

        def grad(dy):
            return _bridge(_host_grad, dy, x.shape, tf.shape(x))
        return y, grad

    return _fn(tf.convert_to_tensor(tensor))


def _graph_broadcast(tensor, name, root_rank):
    """Differentiable broadcast: every rank allreduce-sums the upstream
    gradient, the root keeps it, the others zero it (reference
    ``_broadcast_grad``)."""
    core = _ensure_core()

    def _host_fwd(arr):
        arr = np.asarray(arr)
        out = np.asarray(core.broadcast(arr, name, root_rank=root_rank))
        return out.reshape(arr.shape)  # 0-d safety, as in allreduce

    def _host_grad(dy):
        dy = np.asarray(dy)
        g = np.asarray(core.allreduce(dy, name + ".grad",
                                      op=Sum)).reshape(dy.shape)
        return g if rank() == root_rank else np.zeros_like(g)

    @tf.custom_gradient
    def _fn(x):
        y = _bridge(_host_fwd, x, x.shape)

        def grad(dy):
            return _bridge(_host_grad, dy, dy.shape)
        return y, grad

    return _fn(tf.convert_to_tensor(tensor))


def allreduce(tensor, average=None, name=None, op=None,
              compression=Compression.none):
    """Allreduce a tf.Tensor — or allgather an ``tf.IndexedSlices``
    (sparse gradients reduce as gathered (values, indices) pairs, the
    reference's bandwidth answer for embeddings,
    ``tensorflow/__init__.py:74-89``)."""
    if op is None:
        op = Average if (average is None or average) else Sum
    if isinstance(tensor, tf.IndexedSlices):
        if op not in (Sum, Average):
            # the gathered (values, indices) pairs ARE the sum/average of
            # the represented tensor; no other reduction holds
            raise NotImplementedError(
                f"{op} does not support sparse tensors; pass "
                "sparse_as_dense=True to DistributedOptimizer")
        # distinct wire names per component: one tensor name must map to
        # one (shape, dtype) stream or the response cache re-negotiates
        # every step (cxx/src/response_cache.cc:9-14)
        values = allgather(tensor.values,
                           name=None if name is None else name + ".values")
        indices = allgather(tensor.indices,
                            name=None if name is None else name + ".indices")
        if op == Average:
            values = values / float(size())
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    wire = _auto_name("allreduce", name)
    if _GRAPH_OK:
        return _graph_allreduce(tensor, wire, op, compression)
    core = _ensure_core()
    arr = _to_numpy(tensor)
    compressed, dtype = compression.compress(arr)
    out = core.allreduce(compressed, wire, op=op)
    return tf.convert_to_tensor(compression.decompress(np.asarray(out),
                                                       dtype))


def allgather(tensor, name=None):
    wire = _auto_name("allgather", name)
    if _GRAPH_OK:
        return _graph_allgather(tensor, wire)
    core = _ensure_core()
    out = core.allgather(_to_numpy(tensor), wire)
    return tf.convert_to_tensor(np.asarray(out))


def broadcast(tensor, root_rank=0, name=None):
    wire = _auto_name("broadcast", name)
    if _GRAPH_OK:
        return _graph_broadcast(tensor, wire, root_rank)
    core = _ensure_core()
    out = core.broadcast(_to_numpy(tensor), wire, root_rank=root_rank)
    return tf.convert_to_tensor(np.asarray(out))


def broadcast_variables(variables, root_rank=0):
    """Assign every variable rank ``root_rank``'s value (reference
    ``broadcast_variables``, ``tensorflow/__init__.py:139``)."""
    # convert_to_tensor (not v.value()) so Keras-3 variables — where
    # .value is a property, not a method — work alongside tf.Variable
    for i, v in enumerate(variables):
        v.assign(broadcast(tf.convert_to_tensor(v), root_rank,
                           name=f"bv.{i}"))


def broadcast_global_variables(root_rank=0):
    """TF1-compat alias over every trainable variable TF tracks
    (reference ``tensorflow/__init__.py:157-170``); in TF2 eager there
    is no global collection, so this requires an explicit registry."""
    coll = getattr(tf.compat.v1, "global_variables", None) \
        if hasattr(tf, "compat") else None
    variables = coll() if coll is not None else []
    if not variables:
        # TF2 eager populates no global collections — a silent no-op here
        # would leave ranks unsynchronized, which is worse than an error
        raise NotImplementedError(
            "broadcast_global_variables needs TF1 global collections "
            "(none found); in TF2 call "
            "broadcast_variables(model.variables) instead")
    broadcast_variables(variables, root_rank)


_SessionRunHook = object
if hasattr(tf, "compat") and hasattr(tf.compat.v1, "train"):
    _SessionRunHook = tf.compat.v1.train.SessionRunHook


class BroadcastGlobalVariablesHook(_SessionRunHook):
    """``tf.compat.v1`` SessionRunHook that broadcasts all global
    variables from ``root_rank`` right after session creation — the
    TF1/estimator-era startup sync (reference
    ``tensorflow/__init__.py:194-227``). Keras/TF2 flows use
    ``callbacks.BroadcastGlobalVariablesCallback`` instead.
    """

    def __init__(self, root_rank=0, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.device = device
        self.bcast_op = None

    def begin(self):
        graph = tf.compat.v1.get_default_graph()
        if self.bcast_op is None or self.bcast_op.graph is not graph:
            import contextlib
            dev = tf.device(self.device) if self.device \
                else contextlib.nullcontext()
            with dev:
                assigns = [
                    tf.compat.v1.assign(
                        v, broadcast(v.read_value(), self.root_rank,
                                     name=f"bgvh.{i}"))
                    for i, v in enumerate(
                        tf.compat.v1.global_variables())]
                self.bcast_op = tf.group(*assigns)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


def _sparse_to_dense(tensor):
    if not isinstance(tensor, tf.IndexedSlices):
        return tensor
    if _GRAPH_OK:
        # real TF scatter-adds IndexedSlices in its converter, and this
        # stays symbolic-safe inside tf.function
        return tf.convert_to_tensor(tensor)
    values = _to_numpy(tensor.values)
    indices = _to_numpy(tensor.indices).astype(np.int64)
    shape = tensor.dense_shape
    if shape is None:
        raise ValueError("sparse_as_dense needs a dense_shape")
    dense = np.zeros(tuple(int(d) for d in _to_numpy(shape)),
                     dtype=values.dtype)
    np.add.at(dense, indices, values)
    return tf.convert_to_tensor(dense)


def _allreduce_grads(grads, op, compression, sparse_as_dense, prefix):
    out = []
    for i, g in enumerate(grads):
        if g is None:
            out.append(None)
            continue
        if sparse_as_dense:
            g = _sparse_to_dense(g)
        out.append(allreduce(g, op=op, name=f"{prefix}.{i}",
                             compression=compression))
    return out


class DistributedOptimizer:
    """TF1-style optimizer wrapper: ``compute_gradients`` allreduces
    before returning (reference ``_DistributedOptimizer``,
    ``tensorflow/__init__.py:266-311``); everything else delegates."""

    def __init__(self, optimizer, name=None, op=Average,
                 compression=Compression.none, sparse_as_dense=False):
        self._optimizer = optimizer
        # deterministic default prefix: stable across steps AND ranks so
        # the response cache hits and negotiation never diverges. When
        # wrapping optimizers for several models in one job, pass a
        # distinct name= per model or their gradient names collide.
        self._name = name or f"Distributed{type(optimizer).__name__}"
        self._op = op
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense

    def compute_gradients(self, *args, **kwargs):
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if size() <= 1 or not gradients:
            return gradients
        grads, variables = zip(*gradients)
        avg = _allreduce_grads(grads, self._op, self._compression,
                               self._sparse_as_dense, self._name)
        return list(zip(avg, variables))

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def minimize(self, loss, global_step=None, var_list=None, **kwargs):
        """TF1 minimize contract: split the arguments between
        compute_gradients and apply_gradients (global_step belongs to
        the latter)."""
        grads_and_vars = self.compute_gradients(loss, var_list=var_list,
                                                **kwargs)
        if global_step is None:
            return self.apply_gradients(grads_and_vars)
        return self.apply_gradients(grads_and_vars,
                                    global_step=global_step)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)

    def get_config(self):
        return self._optimizer.get_config()


class DistributedGradientTape:
    """``tf.GradientTape`` wrapper whose ``gradient()`` allreduces,
    with the same sparse handling as DistributedOptimizer (reference
    ``tensorflow/__init__.py:475-531``)."""

    def __init__(self, tape, op=Average, compression=Compression.none,
                 sparse_as_dense=False, name="tape"):
        self._tape = tape
        self._op = op
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        # stable default: the TF2 idiom re-wraps the tape every step, so
        # the prefix must repeat or the response cache misses every step
        # and rank-dependent tape counts would desynchronize names. For
        # several models in one job pass a distinct name per model.
        self._name = name

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def watch(self, t):
        self._tape.watch(t)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        if size() <= 1:
            return grads
        return _allreduce_grads(grads, self._op, self._compression,
                                self._sparse_as_dense, self._name)
