"""TensorFlow adapter (reference: ``horovod/tensorflow/__init__.py``).

Eager-mode TF2 over the native core's host data plane, mirroring the torch
adapter: tensors bridge through numpy into the name-negotiated queue
(reference role: the ``HorovodAllreduceOp`` custom kernels,
``tensorflow/mpi_ops.cc:287-460``). TensorFlow is not part of this image's
baked environment, so the module import-gates: everything works when TF is
installed, and a clear error points JAX-first users to the native path.

``DistributedGradientTape`` wraps ``tf.GradientTape`` so ``gradient()``
returns allreduced gradients (reference ``__init__.py:475-531``);
``broadcast_variables`` syncs initial state (``__init__.py:139``).
"""

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover - TF absent in this image
    raise ImportError(
        "horovod_tpu.tensorflow requires tensorflow, which is not "
        "installed. On TPU, prefer the JAX-native API (import horovod_tpu "
        "as hvd) — it is the compiled, first-class path.") from e

import numpy as np

from horovod_tpu.basics import (cross_rank, cross_size, init,
                                is_initialized, local_rank, local_size,
                                rank, shutdown, size)
from horovod_tpu.ops.reduction import Adasum, Average, Max, Min, Sum

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size",
    "Sum", "Average", "Adasum", "Min", "Max",
    "allreduce", "allgather", "broadcast", "broadcast_variables",
    "DistributedGradientTape",
]


def _ensure_core():
    from horovod_tpu import _core, basics
    if not basics.is_initialized():
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init()")
    if not _core.is_initialized():
        _core.init(rank=0, size=1)
    return _core

_counters = {}


def _auto_name(kind, name):
    if name is not None:
        return name
    n = _counters.get(kind, 0)
    _counters[kind] = n + 1
    return f"tf.{kind}.{n}"


def allreduce(tensor, average=True, name=None, op=None):
    core = _ensure_core()
    op = op or (Average if average else Sum)
    out = core.allreduce(np.asarray(tensor), _auto_name("allreduce", name),
                         op=op)
    return tf.convert_to_tensor(out)


def allgather(tensor, name=None):
    core = _ensure_core()
    out = core.allgather(np.asarray(tensor), _auto_name("allgather", name))
    return tf.convert_to_tensor(out)


def broadcast(tensor, root_rank=0, name=None):
    core = _ensure_core()
    out = core.broadcast(np.asarray(tensor), _auto_name("broadcast", name),
                         root_rank=root_rank)
    return tf.convert_to_tensor(out)


def broadcast_variables(variables, root_rank=0):
    """Assign every variable rank ``root_rank``'s value (reference
    ``broadcast_variables``, ``tensorflow/__init__.py:139``)."""
    for i, v in enumerate(variables):
        v.assign(broadcast(v.value(), root_rank, name=f"bv.{i}"))


class DistributedGradientTape:
    """``tf.GradientTape`` wrapper whose ``gradient()`` allreduces
    (reference ``tensorflow/__init__.py:475-531``)."""

    def __init__(self, tape, op=Average):
        self._tape = tape
        self._op = op

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def watch(self, t):
        self._tape.watch(t)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return [None if g is None else
                allreduce(g, op=self._op, name=f"tape.{i}")
                for i, g in enumerate(grads)]
