"""Keras ``Callback`` classes for distributed ``model.fit`` (reference:
``horovod/_keras/callbacks.py:20-185`` + the thin ``tensorflow.keras.
callbacks`` shims over it).

These are real ``keras.callbacks.Callback`` subclasses, so they plug
straight into ``model.fit(callbacks=[...])`` on Keras 3. When running
against the test fake (which has no keras.callbacks), a minimal base
class with the same hook surface stands in — the hook logic is
identical either way.

* ``BroadcastGlobalVariablesCallback`` — after the first batch (so
  lazily-built variables exist), broadcast model + optimizer variables
  from the root rank. The first-batch timing is the reference's: Keras
  materializes weights during the first ``train_step``.
* ``MetricAverageCallback`` — on epoch end, replace every numeric log
  value with its allreduce-average across ranks, in sorted-key order so
  the wire names agree on every rank.
* ``LearningRateScheduleCallback`` — multiply the initial lr by
  ``multiplier(epoch)`` inside ``[start_epoch, end_epoch)``; staircase
  (first batch of each epoch) or smooth (every batch, fractional
  epoch). With ``momentum_correction``, while the lr is perturbed the
  optimizer's momentum is scaled by ``new_lr / old_lr`` for that batch
  and restored afterwards (Goyal et al., "Accurate, Large Minibatch
  SGD" — keeps the effective update magnitude continuous across lr
  steps).
* ``LearningRateWarmupCallback`` — smooth ramp from ``initial_lr /
  size`` to ``initial_lr`` over ``warmup_epochs`` (same paper).
"""

import numpy as np

import tensorflow as tf

import horovod_tpu.tensorflow as hvd

_KerasCallback = getattr(getattr(getattr(tf, "keras", None), "callbacks",
                                 None), "Callback", None)

if _KerasCallback is None:  # test fake: same hook surface, no keras
    class _KerasCallback:
        model = None
        params = None

        def set_model(self, model):
            self.model = model

        def set_params(self, params):
            self.params = params

        # keras.callbacks.Callback forwards the train-prefixed hooks to
        # the generic ones by default; the shim must do the same
        def on_batch_begin(self, batch, logs=None):
            pass

        def on_batch_end(self, batch, logs=None):
            pass

        def on_train_batch_begin(self, batch, logs=None):
            self.on_batch_begin(batch, logs=logs)

        def on_train_batch_end(self, batch, logs=None):
            self.on_batch_end(batch, logs=logs)


def _get_attr_lr(optimizer):
    # Keras 3 spells it learning_rate; Keras 2 and the fake spell it lr
    return ("learning_rate" if hasattr(optimizer, "learning_rate")
            else "lr")


def _get_lr(optimizer):
    return float(np.asarray(getattr(optimizer, _get_attr_lr(optimizer))))


def _set_lr(optimizer, value):
    # the Keras 3 learning_rate setter assigns through to the backing
    # variable, so this is safe inside a compiled training loop
    setattr(optimizer, _get_attr_lr(optimizer), float(value))


class BroadcastGlobalVariablesCallback(_KerasCallback):
    """Broadcast all model/optimizer variables from ``root_rank`` after
    the first batch (reference ``BroadcastGlobalVariablesCallbackImpl.
    on_batch_end``)."""

    def __init__(self, root_rank=0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        variables = list(getattr(self.model, "variables", None)
                         or getattr(self.model, "weights", []))
        opt = getattr(self.model, "optimizer", None)
        if opt is not None:
            opt_vars = getattr(opt, "variables", None)
            if callable(opt_vars):
                opt_vars = opt_vars()
            variables += list(opt_vars or [])
        hvd.broadcast_variables(variables, root_rank=self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(_KerasCallback):
    """Average epoch-end metrics across ranks in place (reference
    ``MetricAverageCallbackImpl._average_metrics_in_place``)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        # sorted order: every rank must enqueue the same wire names in
        # the same set, or negotiation never completes
        for metric in sorted(logs):
            value = logs[metric]
            if isinstance(value, (int, float, np.floating, np.integer)):
                out = hvd.allreduce(
                    tf.convert_to_tensor(np.float64(value)),
                    op=hvd.Average, name=f"metric.{metric}")
                # .reshape(-1)[0]: the non-graph core path widens 0-d
                # tensors to (1,), and float() of a (1,) array is a
                # NumPy deprecation on its way to a TypeError
                logs[metric] = float(np.asarray(out).reshape(-1)[0])


class LearningRateScheduleCallback(_KerasCallback):
    """Scale the lr by ``multiplier(epoch)`` during an epoch window
    (reference ``LearningRateScheduleCallbackImpl``)."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase if callable(multiplier) else True
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.restore_momentum = None
        self.current_epoch = None
        self._warned_momentum = False
        self.multiplier = (multiplier if callable(multiplier)
                           else (lambda epoch: multiplier))

    def _steps(self):
        if self.steps_per_epoch:
            return self.steps_per_epoch
        params = self.params or {}
        if params.get("steps"):
            return params["steps"]
        raise ValueError(
            f"{type(self).__name__} needs steps_per_epoch for a smooth "
            "(non-staircase) schedule; pass it explicitly")

    def _adjust(self, epoch):
        opt = self.model.optimizer
        old_lr = _get_lr(opt)
        new_lr = self.initial_lr * self.multiplier(epoch)
        _set_lr(opt, new_lr)
        if not self.momentum_correction or old_lr <= 0:
            return
        momentum = getattr(opt, "momentum", None)
        if momentum is None:
            return
        if hasattr(momentum, "assign"):
            # variable-backed momentum: assignment reaches the compiled
            # train step, so the scale-for-one-batch trick is sound
            self.restore_momentum = float(np.asarray(momentum))
            momentum.assign(self.restore_momentum * new_lr / old_lr)
        elif not self._warned_momentum:
            # Keras 3 stores momentum as a plain float that tf.function
            # bakes into the traced step as a constant — mutating the
            # attribute would either do nothing or permanently trace the
            # perturbed value, so correction is skipped instead
            self._warned_momentum = True
            import warnings
            warnings.warn(
                "momentum_correction skipped: this optimizer's momentum "
                "is a plain Python float (Keras 3), which is baked into "
                "the compiled train step at trace time and cannot be "
                "safely scaled per batch")

    def _restore(self):
        if self.restore_momentum is not None:
            self.model.optimizer.momentum.assign(self.restore_momentum)
            self.restore_momentum = None

    def on_train_begin(self, logs=None):
        self.initial_lr = _get_lr(self.model.optimizer)
        if not self.staircase:
            self.steps_per_epoch = self._steps()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        epoch = self.current_epoch or 0
        if epoch < self.start_epoch or (
                self.end_epoch is not None and epoch >= self.end_epoch):
            return
        if self.staircase:
            if batch == 0:
                self._adjust(epoch)
        else:
            self._adjust(epoch + float(batch) / self.steps_per_epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Smooth warmup from ``initial_lr / size`` to ``initial_lr`` over
    ``warmup_epochs`` (reference ``LearningRateWarmupCallbackImpl``)."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        def multiplier(epoch):
            # nudge so the ramp lands exactly on 1.0 at the end of the
            # last warmup epoch rather than one batch short
            epoch += 1.0 / self.steps_per_epoch
            size = hvd.size()
            return (1.0 / size) * (epoch * (size - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose:
            print(f"\nEpoch {epoch + 1}: finished learning rate warmup "
                  f"to {_get_lr(self.model.optimizer):g}.")
