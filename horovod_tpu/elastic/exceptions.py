"""Elastic control-flow exceptions.

Reference: ``horovod/common/exceptions.py`` (0.20+) — ``HorovodInternalError``
(a collective or peer failed; committed state must be restored) and
``HostsUpdatedInterrupt`` (membership changed; current state is still good,
the job only needs to re-rendezvous). The split matters: a failure rolls
the model back to the last ``commit()``, an update does not.
"""


class HostsUpdatedInterrupt(Exception):
    """Raised between batches (at ``State.commit()``) when the driver has
    signalled a host-membership change. Training state is NOT rolled back;
    the elastic loop re-syncs and continues under the new world.

    ``res`` records what changed ("added" / "removed" / "updated")."""

    def __init__(self, res="updated"):
        super().__init__(res)
        self.res = res


class WorkerFailureError(RuntimeError):
    """A peer worker (or a collective against it) failed mid-batch. The
    elastic loop restores the last committed state before retrying —
    partially-applied updates from the failed batch must not survive."""


# Reference-compatible alias (``horovod.common.exceptions``).
HorovodInternalError = WorkerFailureError
