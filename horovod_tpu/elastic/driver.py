"""Elastic driver: membership tracking, blacklisting, re-rendezvous.

Reference: ``horovod/run/elastic/driver.py`` — the launcher-side brain of
an elastic job. It owns

* a :class:`~horovod_tpu.elastic.discovery.HostDiscoveryPoller` whose
  diffs become worker interrupts (notification.py) + timeline
  ``MEMBERSHIP`` markers,
* a :class:`Blacklist` of repeatedly-failing hosts (exponential backoff,
  then permanent exclusion — reference ``blacklist_host`` semantics),
* the rendezvous loop: each *epoch* assigns ranks to the current
  non-excluded host set (reusing ``run/allocation.py``), publishes the
  assignment on the launcher KV, launches workers, and decides from exit
  codes whether the job is done, needs a plain re-rendezvous (graceful
  ``EXIT_RENDEZVOUS``), or a failure round (blame + retry).

Recovery model (docs/ELASTIC.md): workers are re-*launched* per epoch —
the state plane (elastic/state.py commit/restore/sync) provides
continuity, the driver provides membership. Worker liveness feeds in
through the KV heartbeats published by ``runtime/stall.py`` progress
hooks (elastic/worker.py).
"""

import json
import logging
import sys
import threading
import time

from horovod_tpu.diag import desync as desync_lib
from horovod_tpu.elastic.discovery import HostDiscoveryPoller
from horovod_tpu.elastic.notification import WorkerNotificationClient
from horovod_tpu.elastic.preempt import DOOMED_KEY_PREFIX, DOOMED_MARKER_KEY
from horovod_tpu.run import allocation
from horovod_tpu.telemetry import get_registry
from horovod_tpu.telemetry import instruments as _tele

logger = logging.getLogger("horovod_tpu")

# a worker whose median step time exceeds the cluster median by this
# factor gets flagged as a straggler in the driver's cluster view
STRAGGLER_THRESHOLD = 2.0

# Worker exit code meaning "re-rendezvous requested" (EX_TEMPFAIL): the
# elastic loop exits with it on HostsUpdatedInterrupt under a driver, so
# the driver can tell a graceful world change from a crash.
EXIT_RENDEZVOUS = 75

# A heartbeat younger than this marks its host "healthy" in the
# cluster view — the sustained-health evidence that decays blacklist
# failure counts.
HEALTHY_HEARTBEAT_S = 30.0

# Default unbroken-health window that forgives one below-threshold
# failure (driver-constructed Blacklists; pass your own to override).
BLACKLIST_DECAY_WINDOW_S = 300.0

# A doomed-host announcement older than this is stale: the spot host
# either already died (and discovery dropped it) or came back — it must
# not stay excluded forever on a leftover key.
DOOMED_TTL_S = 120.0


class Blacklist:
    """Failure accounting per host (reference ``ElasticDriver``'s
    blacklist + cooldown): each failure excludes the host for an
    exponentially growing backoff window; after ``threshold`` failures it
    is excluded permanently.

    Two refinements over the reference for spot capacity:

    * **drained ≠ crashed** — a host whose eviction was announced on the
      KV (``elastic/preempt.py``) departs via :meth:`record_drain`,
      which carries no penalty: preemption is the *plan* on spot
      capacity, and penalizing it would walk every host toward
      permanent exclusion.
    * **decay on sustained health** — with ``decay_window`` set, each
      unbroken window of observed health (:meth:`observe_health`, fed by
      the driver's ``cluster_view()`` heartbeat freshness) forgives one
      below-threshold failure, so a host that flapped once is not one
      failure from permanent exclusion for the life of a week-long run.
      Permanent blacklisting never decays.

    ``clock`` is injectable so tests can drive the backoff and the decay
    without sleeping."""

    def __init__(self, threshold=3, base_delay=5.0, max_delay=600.0,
                 clock=time.monotonic, decay_window=None):
        self._threshold = threshold
        self._base = base_delay
        self._max = max_delay
        self._clock = clock
        self._decay_window = decay_window
        self._failures = {}   # host -> count
        self._cooldown = {}   # host -> excluded-until timestamp
        self._drains = {}     # host -> graceful-departure count
        self._healthy_anchor = {}  # host -> start of current health streak

    def record_failure(self, host):
        n = self._failures.get(host, 0) + 1
        self._failures[host] = n
        self._healthy_anchor.pop(host, None)  # a failure breaks the streak
        delay = min(self._base * (2 ** (n - 1)), self._max)
        self._cooldown[host] = self._clock() + delay
        if n >= self._threshold:
            logger.warning("elastic: host %s blacklisted after %d failures",
                           host, n)
        else:
            logger.warning("elastic: host %s failed (%d/%d), backing off "
                           "%.1fs", host, n, self._threshold, delay)
        return n

    def record_drain(self, host):
        """A planned departure (graceful eviction announced on the KV):
        counted for observability, zero blacklist penalty."""
        n = self._drains.get(host, 0) + 1
        self._drains[host] = n
        logger.info("elastic: host %s drained gracefully (%d drain(s), "
                    "no penalty)", host, n)
        return n

    def observe_health(self, hosts, now=None):
        """Feed sustained-health evidence: ``hosts`` is the set observed
        healthy right now (fresh heartbeats in ``cluster_view()``). A
        host absent from consecutive observations loses its streak; each
        full ``decay_window`` of unbroken presence forgives one
        below-threshold failure. No-op without ``decay_window``."""
        if not self._decay_window:
            return
        now = self._clock() if now is None else now
        hosts = set(hosts)
        for host in list(self._healthy_anchor):
            if host not in hosts:
                del self._healthy_anchor[host]
        for host in hosts:
            anchor = self._healthy_anchor.setdefault(host, now)
            n = self._failures.get(host, 0)
            if n <= 0 or n >= self._threshold:
                continue
            if now - anchor >= self._decay_window:
                n -= 1
                self._healthy_anchor[host] = now
                if n <= 0:
                    self._failures.pop(host, None)
                    self._cooldown.pop(host, None)
                else:
                    self._failures[host] = n
                logger.info("elastic: host %s healthy for %.0fs — failure "
                            "count decayed to %d", host,
                            self._decay_window, n)

    def count(self, host):
        return self._failures.get(host, 0)

    def drains(self, host):
        return self._drains.get(host, 0)

    def blacklisted(self, host):
        """Permanently excluded (failure count reached the threshold)."""
        return self._failures.get(host, 0) >= self._threshold

    def excluded(self, host, now=None):
        """Excluded right now: blacklisted, or inside a backoff window."""
        if self.blacklisted(host):
            return True
        until = self._cooldown.get(host)
        if until is None:
            return False
        return (now if now is not None else self._clock()) < until

    @property
    def hosts(self):
        """The permanently blacklisted host set."""
        return {h for h, n in self._failures.items()
                if n >= self._threshold}


class ElasticDriver:
    """Launcher-side elastic controller.

    ``kv`` is the launcher's :class:`~horovod_tpu.run.rendezvous.
    KVStoreServer`; workers publish their notification endpoints and
    heartbeats there (elastic/worker.py) and the driver publishes each
    epoch's rank assignment under ``elastic/slots/<epoch>``.
    """

    def __init__(self, discovery, min_np, max_np=None, blacklist=None,
                 kv=None, auth_key=None, poll_interval=1.0, timeline=None,
                 start_timeout=600, hopeless_grace=30.0,
                 doomed_ttl=DOOMED_TTL_S):
        if min_np < 1:
            raise ValueError(f"min_np must be >= 1 (got {min_np})")
        if max_np is not None and max_np < min_np:
            raise ValueError(
                f"max_np ({max_np}) must be >= min_np ({min_np})")
        self.min_np = min_np
        self.max_np = max_np
        self.blacklist = blacklist if blacklist is not None else Blacklist(
            decay_window=BLACKLIST_DECAY_WINDOW_S)
        self._doomed_ttl = doomed_ttl
        self._kv = kv
        self._auth_key = auth_key
        self._timeline = timeline
        self._start_timeout = start_timeout
        self._hopeless_grace = hopeless_grace
        self._poll_interval = poll_interval
        self.epoch = 0
        self._current_slots = []
        self._membership_dirty = False
        self._flagged_stragglers = set()
        self._flagged_desync = set()
        self._last_digests = None
        self._poller = HostDiscoveryPoller(
            discovery, poll_interval=poll_interval,
            on_update=self._on_hosts_updated)
        # launcher-side telemetry (the driver has its own registry view;
        # worker metrics arrive through the KV heartbeats)
        reg = get_registry()
        self._m_epochs = reg.counter(
            _tele.RENDEZVOUS_EPOCHS, "Rendezvous epochs opened")
        self._m_blacklist = reg.gauge(
            _tele.BLACKLIST_HOSTS, "Hosts currently excluded "
            "(blacklisted or in a backoff window)")
        self._m_recovery = reg.histogram(
            _tele.RECOVERY_SECONDS, "Wall time from a worker failure to "
            "the next completed rendezvous")
        self._m_straggler = reg.gauge(
            _tele.STRAGGLER_RATIO, "Slowest/median per-rank median step "
            "time across the current epoch's workers")
        self._m_goodput = reg.gauge(
            _tele.GOODPUT_RATIO, "Fleet-wide goodput: summed compute "
            "seconds / summed attributed seconds across the workers' "
            "per-rank goodput ledgers (KV heartbeat snapshots)")
        self._m_preempt = reg.counter(
            _tele.PREEMPTIONS_TOTAL, "Preemption notices acted on, by "
            "source kind (docs/OBSERVABILITY.md)",
            label_names=("kind",))
        self._m_drain = reg.histogram(
            _tele.DRAIN_SECONDS, "Doomed-host announcement to the "
            "rendezvous that drained (or knowingly reused) the host — "
            "the wall cost of planned churn")

    # -- membership ----------------------------------------------------------
    def available_hosts(self):
        """Current discovery view minus excluded hosts."""
        hosts = self._poller.current()
        return {h: s for h, s in hosts.items()
                if s > 0 and not self.blacklist.excluded(h)}

    def available_slots(self):
        return sum(self.available_hosts().values())

    def wait_for_available_slots(self, count, timeout=None):
        """Block until at least ``count`` slots exist on non-excluded
        hosts (reference ``wait_for_available_slots``); TimeoutError
        names the shortfall and the blacklist."""
        timeout = timeout if timeout is not None else self._start_timeout
        deadline = time.monotonic() + timeout
        hopeless_deadline = None
        while True:
            hosts = self.available_hosts()
            if sum(hosts.values()) >= count:
                return hosts
            # hosts in a backoff window come back on their own, but
            # permanently blacklisted ones never do: when even counting
            # the cooled-down hosts the target is unreachable, only NEW
            # hosts from discovery could save the job — fail fast after
            # a short grace instead of burning the full start timeout.
            # The clamp is recomputed per iteration so a transiently
            # empty discovery view (flaky script) cannot permanently
            # shorten the real deadline.
            view = self._poller.current()
            potential = sum(s for h, s in view.items()
                            if not self.blacklist.blacklisted(h))
            if potential < count:
                if hopeless_deadline is None:
                    hopeless_deadline = time.monotonic() + min(
                        timeout, self._hopeless_grace)
                effective = min(deadline, hopeless_deadline)
            else:
                hopeless_deadline = None
                effective = deadline
            if time.monotonic() >= effective:
                raise TimeoutError(
                    f"elastic: needed {count} slots but only "
                    f"{sum(hosts.values())} available after {timeout:.0f}s "
                    f"(hosts={sorted(hosts)}, "
                    f"blacklisted={sorted(self.blacklist.hosts)})")
            # re-poll at the configured discovery cadence (the wait must
            # not hammer an external discovery script at 4 Hz)
            time.sleep(min(self._poll_interval,
                           max(0.05, effective - time.monotonic())))
            self._poller.poll_once()

    def _read_doomed(self):
        """Fresh doomed-host announcements (``elastic/doomed/<host>``,
        published by evicted workers — elastic/preempt.py), keyed by
        host. Stale entries (older than ``doomed_ttl``) are dropped and
        deleted: a reclaimed spot host that came back must not stay
        excluded on a leftover key."""
        if self._kv is None:
            return {}
        hosts = set(self._poller.current()) | {
            s.hostname for s in self._current_slots}
        doomed = {}
        for host in sorted(hosts):
            raw = self._kv.get(DOOMED_KEY_PREFIX + host)
            if raw is None:
                continue
            try:
                info = json.loads(raw)
            except (ValueError, UnicodeDecodeError):
                info = {}
            ts = float(info.get("time") or 0)
            if ts and abs(time.time() - ts) > self._doomed_ttl:
                self._kv.delete(DOOMED_KEY_PREFIX + host)
                continue
            doomed[host] = info
        return doomed

    def _consume_doomed(self, hosts):
        """Drain announced-doomed hosts from the rendezvous about to
        open — the point of the announcement: the host leaves the world
        BEFORE its death breaks a collective. One-shot (the keys are
        consumed here). When excluding every doomed host would drop
        below ``min_np`` the hosts are knowingly reused — a doomed host
        that has not died yet beats failing the job, and the
        announcement still bought no-blame drain accounting."""
        doomed = self._read_doomed()
        if not doomed:
            return {}
        kept = {h: s for h, s in hosts.items() if h not in doomed}
        if sum(kept.values()) >= self.min_np:
            for host in doomed:
                hosts.pop(host, None)
            logger.info("elastic: draining doomed host(s) %s from the "
                        "next rendezvous", sorted(doomed))
        else:
            logger.warning(
                "elastic: doomed host(s) %s announced but the remaining "
                "capacity is below min_np=%d; knowingly reusing them",
                sorted(doomed), self.min_np)
        now = time.time()
        for host, info in doomed.items():
            self._kv.delete(DOOMED_KEY_PREFIX + host)
            ts = float(info.get("time") or now)
            self._m_preempt.labels(info.get("kind") or "sigterm").inc()
            self._m_drain.observe(max(0.0, now - ts))
        self._kv.delete(DOOMED_MARKER_KEY)
        self._membership_event(
            "DRAIN", {"epoch": self.epoch, "hosts": sorted(doomed),
                      "reused": sum(kept.values()) < self.min_np})
        return doomed

    def _on_hosts_updated(self, added, removed, current, res):
        logger.info("elastic: host set changed (added=%s removed=%s)",
                    added, removed)
        self._membership_event("UPDATED",
                               {"added": added, "removed": removed,
                                "hosts": sorted(current)})
        reason = "removed" if removed and not added else (
            "added" if added and not removed else "updated")
        self._membership_dirty = True
        if not self.notify_workers(reason):
            # workers may still be booting (endpoint not yet on the KV):
            # keep trying in the background until one acks or the epoch
            # turns over — a membership change must never be lost to a
            # startup race
            self._notify_until_acked(reason, self.epoch)

    def _notify_until_acked(self, res, epoch, attempts=120, interval=0.25):
        def _retry():
            for _ in range(attempts):
                time.sleep(interval)
                if self.epoch != epoch:
                    return
                if self.notify_workers(res):
                    return
            logger.warning("elastic: no worker acked the %s membership "
                           "update in epoch %d", res, epoch)

        threading.Thread(target=_retry, daemon=True,
                         name="hvd_tpu_elastic_notify").start()

    def _membership_event(self, event, details):
        if self._timeline is not None:
            self._timeline.membership(event, details)

    # -- worker notification / liveness --------------------------------------
    def _worker_endpoints(self):
        """Notification endpoints the current epoch's workers published
        on the KV (rank -> (addr, port))."""
        if self._kv is None:
            return {}
        endpoints = {}
        for slot in self._current_slots:
            raw = self._kv.get(f"elastic/notif/{self.epoch}/{slot.rank}")
            if raw is None:
                continue
            info = json.loads(raw)
            endpoints[slot.rank] = (info["addr"], int(info["port"]))
        return endpoints

    def notify_workers(self, res="updated"):
        """Post a hosts-updated interrupt to every reachable worker;
        unreachable ones are already dead or will learn at relaunch."""
        notified = []
        for rank, (addr, port) in self._worker_endpoints().items():
            try:
                acked = WorkerNotificationClient(
                    addr, port, key=self._auth_key).notify_hosts_updated(res)
            except OSError:
                continue
            if acked:  # a dropped frame / empty reply is NOT delivery
                notified.append(rank)
        return notified

    def worker_progress(self):
        """The driver's liveness view: last heartbeat each current worker
        published through its stall-inspector progress hook
        (``elastic/heartbeat/<epoch>/<rank>`` -> {step, time})."""
        if self._kv is None:
            return {}
        progress = {}
        for slot in self._current_slots:
            raw = self._kv.get(f"elastic/heartbeat/{self.epoch}/{slot.rank}")
            if raw is not None:
                progress[slot.rank] = json.loads(raw)
        return progress

    def cluster_view(self):
        """Aggregate the metric snapshots riding the KV heartbeats into
        the coordinator's view of the epoch: per-rank step progress and
        step-time medians, the slowest/median step-time ratio, and the
        flagged straggler ranks (ratio > ``STRAGGLER_THRESHOLD``).
        Updates the ``hvd_straggler_step_time_ratio`` gauge and logs
        flagged ranks (rate-limited to once per epoch per rank)."""
        progress = self.worker_progress()
        view = {"epoch": self.epoch, "ranks": {}, "stragglers": [],
                "straggler_ratio": None, "goodput": None}
        # sustained-health evidence for blacklist decay: a fresh
        # heartbeat marks the rank's host healthy this observation
        now_wall = time.time()
        healthy_hosts = set()
        for slot in self._current_slots:
            hb = progress.get(slot.rank)
            if hb and now_wall - float(hb.get("time") or 0) \
                    <= HEALTHY_HEARTBEAT_S:
                healthy_hosts.add(slot.hostname)
        if healthy_hosts:
            self.blacklist.observe_health(healthy_hosts)
        view["healthy_hosts"] = sorted(healthy_hosts)
        step_times = {}
        fleet_phases = {}
        for rank, hb in progress.items():
            m = hb.get("metrics") or {}
            view["ranks"][rank] = {
                "step": hb.get("step"), "last_heartbeat": hb.get("time"),
                **m}
            t = m.get("step_seconds_p50")
            if t:
                step_times[rank] = float(t)
            for phase, secs in (m.get("goodput") or {}).items():
                fleet_phases[phase] = fleet_phases.get(phase, 0.0) \
                    + float(secs)
        if fleet_phases:
            # the live fleet-wide goodput gauge: per-rank ledger phase
            # totals ride the heartbeats (instruments.kv_snapshot), the
            # driver just sums rank-seconds
            attributed = sum(fleet_phases.values())
            ratio = (fleet_phases.get("compute", 0.0) / attributed
                     if attributed > 0 else 1.0)
            view["goodput"] = {"phases": fleet_phases, "ratio": ratio}
            self._m_goodput.set(ratio)
        if len(step_times) >= 2:
            ordered = sorted(step_times.values())
            # LOWER median: with the upper-middle element, a 2-worker
            # cluster's "median" would be its own slowest rank and a 10x
            # straggler could never be flagged
            median = ordered[(len(ordered) - 1) // 2]
            slowest = ordered[-1]
            if median > 0:
                ratio = slowest / median
                view["straggler_ratio"] = ratio
                self._m_straggler.set(ratio)
                view["stragglers"] = sorted(
                    r for r, t in step_times.items()
                    if t / median > STRAGGLER_THRESHOLD)
        fresh = [r for r in view["stragglers"]
                 if r not in self._flagged_stragglers]
        if fresh:
            self._flagged_stragglers.update(fresh)
            logger.warning(
                "elastic: epoch %d straggler(s) %s — median step time "
                ">%.1fx the cluster median (%s)", self.epoch, fresh,
                STRAGGLER_THRESHOLD,
                {r: round(step_times[r], 4) for r in fresh})
        view["flightrec"] = self._cross_check_digests(progress)
        return view

    def _cross_check_digests(self, progress):
        """Desync detection while the job hangs: compare the flight-
        recorder digests riding the heartbeats (seq + collective-schedule
        hash, ``horovod_tpu.diag.desync``) and NAME the rank whose
        schedule diverged or whose seq stopped advancing — the live
        mirror of the reference controller's shape/dtype mismatch checks
        (``controller.cc:55-346``), working post-negotiation and for the
        compiled plane's trace-time schedules."""
        digests = {r: hb.get("flightrec") for r, hb in progress.items()
                   if hb.get("flightrec")}
        check = desync_lib.cross_check(digests, prev=self._last_digests)
        self._last_digests = digests or self._last_digests
        fresh = [r for r in check["desynced"]
                 if r not in self._flagged_desync]
        if fresh:
            self._flagged_desync.update(fresh)
            logger.error(
                "elastic: epoch %d DESYNC — rank(s) %s diverged from the "
                "majority collective schedule (%s); their compiled/eager "
                "collective order no longer matches the cluster",
                self.epoch, fresh, check.get("detail"))
            self._membership_event(
                "DESYNC", {"epoch": self.epoch, "ranks": fresh,
                           "detail": check.get("detail")})
        if check["stuck"]:
            logger.warning(
                "elastic: epoch %d rank(s) %s stopped advancing their "
                "collective seq while peers progressed (%s) — dead data "
                "feed or wedged collective; flight-recorder dumps will "
                "name the op (hvdrun --doctor)", self.epoch,
                check["stuck"], check["seqs"])
        return check

    # -- rendezvous ----------------------------------------------------------
    def rendezvous(self):
        """Open a new epoch: wait for min-np capacity, assign ranks to
        the current host set (capped at max-np), publish the assignment.
        Returns the slot list."""
        hosts = self.wait_for_available_slots(self.min_np)
        self._consume_doomed(hosts)
        host_list = [allocation.HostSlots(h, s)
                     for h, s in sorted(hosts.items())]
        total = sum(h.slots for h in host_list)
        np_now = min(total, self.max_np) if self.max_np else total
        self.epoch += 1
        slots = allocation.allocate(host_list, np_now)
        self._current_slots = slots
        self._flagged_stragglers = set()
        self._flagged_desync = set()
        self._last_digests = None  # fresh processes restart their seqs
        self._m_epochs.inc()
        self._m_blacklist.set(sum(
            1 for h in self._poller.current()
            if self.blacklist.excluded(h)))
        if self._kv is not None:
            # stale cross-epoch coordination keys must not leak into the
            # new world (a late rank would adopt epoch N-1's controller)
            self._kv.delete("controller/port")
            self._kv.put(f"elastic/slots/{self.epoch}", json.dumps(
                [{"rank": s.rank, "host": s.hostname,
                  "local_rank": s.local_rank} for s in slots]).encode())
            self._kv.put("elastic/epoch", str(self.epoch).encode())
        self._membership_event("RENDEZVOUS",
                               {"epoch": self.epoch, "np": np_now,
                                "hosts": sorted(hosts)})
        logger.info("elastic: epoch %d rendezvous: %d ranks on %s",
                    self.epoch, np_now, sorted(hosts))
        return slots

    def worker_env(self):
        """Extra env vars every elastic worker gets (the elastic side of
        the launcher env contract)."""
        env = {"HOROVOD_ELASTIC": "1",
               "HOROVOD_ELASTIC_EPOCH": str(self.epoch),
               "HOROVOD_ELASTIC_MIN_NP": str(self.min_np)}
        if self.max_np is not None:
            env["HOROVOD_ELASTIC_MAX_NP"] = str(self.max_np)
        return env

    # -- the retry loop ------------------------------------------------------
    def run_job(self, launch_fn, max_epochs=None):
        """Drive the job to completion: launch an epoch, inspect exit
        codes, blame/blacklist, re-rendezvous, repeat.

        ``launch_fn(slots, epoch, extra_env)`` must start one worker per
        slot and return a :class:`horovod_tpu.run.launcher.Job` (or
        anything with ``join() -> {rank: exit_code}`` and
        ``first_failure``). Returns the number of epochs used."""
        self._poller.start()
        spurious_drains = 0
        failure_time = None
        monitor_stop = threading.Event()
        monitor = threading.Thread(
            target=self._monitor_cluster, args=(monitor_stop,),
            name="hvd_tpu_elastic_cluster", daemon=True)
        monitor.start()
        try:
            while True:
                if max_epochs is not None and self.epoch >= max_epochs:
                    raise RuntimeError(
                        f"elastic: giving up after {self.epoch} epochs")
                slots = self.rendezvous()
                if failure_time is not None:
                    # failure -> blame -> wait-for-slots -> new epoch
                    # published: the recovery wall-time the north-star
                    # cares about
                    self._m_recovery.observe(
                        time.monotonic() - failure_time)
                    failure_time = None
                job = launch_fn(slots, self.epoch, self.worker_env())
                job.join()
                first = job.first_failure
                if first is None:
                    logger.info("elastic: job completed in epoch %d",
                                self.epoch)
                    return self.epoch
                rank, rc = first
                doomed = self._read_doomed()
                if rc == EXIT_RENDEZVOUS:
                    # graceful: workers drained at a commit boundary in
                    # response to a membership interrupt — no blame. A
                    # drain with NO membership change behind it means the
                    # command exits 75 on its own: cap it, or hvdrun
                    # would relaunch in a tight infinite loop.
                    if doomed:
                        # planned churn: an evicted worker announced its
                        # host before exiting (elastic/preempt.py) —
                        # blame nobody; the next rendezvous consumes the
                        # announcement and drains the host
                        spurious_drains = 0
                        for h in sorted(doomed):
                            self.blacklist.record_drain(h)
                        logger.info(
                            "elastic: epoch %d graceful eviction of %s "
                            "(kind=%s)", self.epoch, sorted(doomed),
                            sorted({(d.get("kind") or "sigterm")
                                    for d in doomed.values()}))
                    elif self._membership_dirty:
                        self._membership_dirty = False
                        spurious_drains = 0
                    else:
                        spurious_drains += 1
                        if spurious_drains >= 3:
                            raise RuntimeError(
                                "elastic: workers exited with "
                                f"EXIT_RENDEZVOUS ({EXIT_RENDEZVOUS}) "
                                f"{spurious_drains} times with no "
                                "membership change; treating as a "
                                "persistent failure")
                        time.sleep(1.0)
                    logger.info("elastic: epoch %d drained for "
                                "re-rendezvous", self.epoch)
                    continue
                spurious_drains = 0
                failure_time = time.monotonic()
                host = slots[rank].hostname
                logger.warning(
                    "elastic: epoch %d rank %d on %s exited with %s "
                    "(last heartbeat: %s)", self.epoch, rank, host, rc,
                    self.worker_progress().get(rank))
                if host in doomed:
                    # the doomed host died before finishing its clean
                    # exit (SIGKILL beat the grace window) — still
                    # PLANNED churn: drain accounting, no backoff that
                    # would penalize the next rendezvous
                    self.blacklist.record_drain(host)
                    self._membership_event(
                        "DRAIN", {"epoch": self.epoch, "rank": rank,
                                  "host": host, "exit_code": rc,
                                  "crashed_in_grace": True})
                else:
                    self.blacklist.record_failure(host)
                    self._membership_event(
                        "FAILURE", {"epoch": self.epoch, "rank": rank,
                                    "host": host, "exit_code": rc})
        finally:
            monitor_stop.set()
            self._poller.stop()

    def _monitor_cluster(self, stop_event, interval=None):
        """Background cluster-view refresh while a job runs: keeps the
        straggler gauge current and the flag log timely (run_job itself
        is blocked in ``job.join()``)."""
        interval = interval if interval is not None else max(
            2.0, 5 * self._poll_interval)
        while not stop_event.wait(interval):
            try:
                self.cluster_view()
            # hvd-lint: disable=HVD-EXCEPT -- monitor loop: the view refresh retries next tick
            except Exception:
                logger.debug("cluster view refresh failed", exc_info=True)

    def stop(self):
        self._poller.stop()


def default_launch_fn(command, controller_port=0, rendezvous_addr=None,
                      rendezvous_port=None, extra_env=None, ssh_port=None,
                      output_dir=None, jax_coordinator=False):
    """Build a ``launch_fn`` for :meth:`ElasticDriver.run_job` that runs
    ``command`` on real hosts through ``run/launcher.py`` (the hvdrun
    elastic path). Per-rank logs go to ``output_dir/epoch-<n>/`` so a
    relaunch never truncates the previous epoch's logs — the evidence of
    the failure being recovered from. With ``jax_coordinator`` each
    epoch gets a fresh ``HOROVOD_COORDINATOR_ADDR`` on its first host
    (the world size changes between epochs, so the coordinator must be
    re-formed anyway)."""
    import os
    import random

    from horovod_tpu.run import launcher

    def launch(slots, epoch, elastic_env):
        env = dict(extra_env or {})
        env.update(elastic_env)
        controller_addr = slots[0].hostname
        if controller_addr in launcher.LOCAL_HOSTS:
            controller_addr = "127.0.0.1"
        if jax_coordinator:
            from horovod_tpu.run.run import free_port
            jport = (free_port() if controller_addr == "127.0.0.1"
                     else random.randint(23000, 43000))
            env["HOROVOD_COORDINATOR_ADDR"] = f"{controller_addr}:{jport}"
        out_dir = (os.path.join(output_dir, f"epoch-{epoch}")
                   if output_dir else None)
        sys.stderr.write(
            f"hvdrun: elastic epoch {epoch}: launching "
            f"{len(slots)} workers\n")
        return launcher.launch(
            slots, command, controller_addr, controller_port,
            rendezvous_addr=rendezvous_addr,
            rendezvous_port=rendezvous_port, extra_env=env,
            ssh_port=ssh_port, output_dir=out_dir)

    return launch
