"""Worker notification plane: how the driver interrupts workers.

Reference: ``horovod/run/common/service/{worker_notification_service,
compute_service}.py`` — each worker runs a tiny TCP service; when host
membership changes, the driver posts ``HostsUpdatedRequest`` to every
worker, and the worker raises :class:`HostsUpdatedInterrupt` at the next
batch boundary (``State.commit()``/``check_host_updates()``), never
mid-collective.

Transport is the HMAC-framed JSON protocol from ``run/discovery.py``
(``digest || u32 len || json``) — one wire format for the whole control
plane, never pickle.
"""

import logging
import socket
import socketserver
import threading

from horovod_tpu.elastic.exceptions import HostsUpdatedInterrupt
from horovod_tpu.run.discovery import recv_frame, send_frame

logger = logging.getLogger("horovod_tpu")

# Unauthenticated single-host runs still need SOME key for the frame MAC;
# a fixed local key keeps the framing uniform (loopback-only binding is
# the actual isolation there, as with the launcher KV).
LOCAL_KEY = b"horovod-tpu-elastic-local"


class WorkerNotificationManager:
    """Worker-side mailbox between the notification service thread and
    the training loop: the service records interrupts, the loop polls at
    commit boundaries (reference ``WorkerNotificationManager``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = None

    def handle_hosts_updated(self, res="updated"):
        with self._lock:
            self._pending = res

    def poll(self, clear=True):
        """The pending update reason, or None; clears it by default."""
        with self._lock:
            res = self._pending
            if clear:
                self._pending = None
            return res

    def check(self):
        """Raise :class:`HostsUpdatedInterrupt` if an update is pending
        (called by ``State.commit()`` — i.e. between batches)."""
        res = self.poll()
        if res is not None:
            raise HostsUpdatedInterrupt(res)

    def reset(self):
        self.poll()


# The default mailbox ``State`` objects check; a worker process has one
# training loop, so one process-global manager (reference
# ``horovod.common.elastic.notification_manager``).
notification_manager = WorkerNotificationManager()


class WorkerNotificationService:
    """Per-worker TCP endpoint the driver posts interrupts to.

    Ops: ``hosts_updated`` (records the interrupt), ``ping`` (liveness
    probe; answers with the service name, like discovery's PingServer).
    Bad digests and unknown ops are dropped silently."""

    def __init__(self, key=None, manager=None, host="0.0.0.0", port=0):
        self._key = key or LOCAL_KEY
        self.manager = manager if manager is not None else \
            notification_manager
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                req = recv_frame(self.request, outer._key)
                if req is None:
                    return  # bad digest or garbage
                op = req.get("op")
                if op == "hosts_updated":
                    outer.manager.handle_hosts_updated(
                        req.get("res", "updated"))
                    send_frame(self.request, outer._key, {"ok": True})
                elif op == "ping":
                    send_frame(self.request, outer._key,
                               {"service": "worker-notification"})

        self._server = socketserver.ThreadingTCPServer((host, port),
                                                       _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="hvd_tpu_worker_notif",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._server.socket.getsockname()[1]

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join()


class WorkerNotificationClient:
    """Driver-side handle to one worker's notification service."""

    def __init__(self, addr, port, key=None, timeout=3.0):
        self._target = (addr, port)
        self._key = key or LOCAL_KEY
        self._timeout = timeout

    def _call(self, obj):
        with socket.create_connection(self._target,
                                      timeout=self._timeout) as sock:
            send_frame(sock, self._key, obj)
            return recv_frame(sock, self._key)

    def notify_hosts_updated(self, res="updated"):
        resp = self._call({"op": "hosts_updated", "res": res})
        return bool(resp and resp.get("ok"))

    def ping(self):
        resp = self._call({"op": "ping"})
        return bool(resp and resp.get("service") == "worker-notification")
