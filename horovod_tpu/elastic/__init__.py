"""horovod_tpu.elastic — fault-tolerant, membership-elastic training.

The post-0.20 ``horovod.elastic`` capability rebuilt on this framework's
primitives (see docs/ELASTIC.md):

* **discovery** — ``HostDiscovery`` / ``FixedHosts`` / ``ScriptDiscovery``
  + a polling thread diffing host sets,
* **state** — ``State`` / ``ObjectState`` / ``JaxState`` with
  ``commit()`` / ``restore()`` / ``sync()`` (collective broadcast from
  the lowest committed rank; optional disk-backed commits via
  ``checkpoint.py``),
* **driver** — ``ElasticDriver`` + ``Blacklist``: per-epoch rendezvous
  over ``run/allocation.py``, failure blame with exponential backoff,
* **notification** — the driver-to-worker interrupt plane (HMAC-framed
  TCP, same wire format as ``run/discovery.py``),
* **runner** — the ``@hvd.elastic.run`` retry loop,
* **preempt** — graceful eviction on spot capacity: SIGTERM / cloud
  spot-notice → bounded force-commit → doomed-host announcement → clean
  exit (``GracefulEvictionHandler``; docs/ELASTIC.md "Running on spot
  capacity").

Typical worker::

    import horovod_tpu as hvd

    state = hvd.elastic.JaxState(directory=ckpt_dir,
                                 train_state=ts)

    @hvd.elastic.run
    def train(state):
        while int(state.train_state.step) < num_steps:
            state.train_state, loss = step(state.train_state, *batch())
            state.commit()

    train(state)
"""

from horovod_tpu.elastic import preempt
from horovod_tpu.elastic.discovery import (FixedHosts, HostDiscovery,
                                           HostDiscoveryPoller,
                                           HostUpdateResult,
                                           ScriptDiscovery, diff_hosts)
from horovod_tpu.elastic.driver import (EXIT_RENDEZVOUS, Blacklist,
                                        ElasticDriver)
from horovod_tpu.elastic.exceptions import (HorovodInternalError,
                                            HostsUpdatedInterrupt,
                                            WorkerFailureError)
from horovod_tpu.elastic.notification import (WorkerNotificationClient,
                                              WorkerNotificationManager,
                                              WorkerNotificationService,
                                              notification_manager)
from horovod_tpu.elastic.preempt import GracefulEvictionHandler
from horovod_tpu.elastic.runner import run
from horovod_tpu.elastic.state import JaxState, ObjectState, State
from horovod_tpu.elastic.worker import (WorkerContext,
                                        attach_progress_reporter,
                                        get_worker_context,
                                        init_worker_context,
                                        is_elastic_worker,
                                        shutdown_worker_context)

__all__ = [
    "HostDiscovery", "FixedHosts", "ScriptDiscovery",
    "HostDiscoveryPoller", "HostUpdateResult", "diff_hosts",
    "State", "ObjectState", "JaxState",
    "HostsUpdatedInterrupt", "WorkerFailureError", "HorovodInternalError",
    "ElasticDriver", "Blacklist", "EXIT_RENDEZVOUS",
    "WorkerNotificationManager", "WorkerNotificationService",
    "WorkerNotificationClient", "notification_manager",
    "WorkerContext", "init_worker_context", "get_worker_context",
    "shutdown_worker_context", "attach_progress_reporter",
    "is_elastic_worker",
    "run",
    "preempt", "GracefulEvictionHandler",
]
