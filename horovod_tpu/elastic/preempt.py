"""Graceful eviction: preemption notices → bounded commit → drain.

Spot/preemptible capacity announces its death (SIGTERM on most
schedulers, a metadata file or HTTP probe on the clouds) seconds to
minutes before pulling the plug. This module turns that notice into a
*planned drain* instead of a crash:

1. **Catch the notice.** SIGTERM rides the flight recorder's wakeup-fd
   watcher (``diag/recorder.py``): the C-level handler writes the signal
   number to a pipe no matter what the main thread is doing, so a rank
   parked inside a native collective still runs its eviction on the
   watcher thread. File-/HTTP-based notices (``HOROVOD_PREEMPT_NOTICE_
   FILE`` / ``_URL``, matching cloud spot-notice shapes) are polled by a
   daemon thread. Without a recorder the handler degrades to its own
   ``signal.signal`` + self-pipe path (flag-set only in the handler —
   HVD-SIGSAFE).
2. **Bounded force-commit.** The attached elastic ``State``'s
   ``flush(timeout=...)`` pushes any in-flight ``AsyncCheckpointer``
   save to durability within the grace budget
   (``HOROVOD_GRACE_SECONDS``, default 30 s) — the step already
   committed is what survives; an uncommitted half-step never does.
3. **Announce the doomed host** on the launcher KV
   (``elastic/doomed/<host>``) so the :class:`~horovod_tpu.elastic.
   driver.ElasticDriver` removes the host from the *next* rendezvous
   before its death breaks a collective, and blames nobody
   (``Blacklist.record_drain``).
4. **Exit clean** — ``EXIT_RENDEZVOUS`` under a driver-managed epoch
   (one re-rendezvous, not a hang+doctor cycle), 0 otherwise.

The whole window is charged to the goodput ledger's ``preemption``
phase, counted in ``hvd_preemptions_total{kind}`` and
``hvd_grace_commit_seconds``, and recorded as structured ``preempt``
flight-recorder events so ``hvd-doctor hang`` can report "graceful
eviction" instead of a dead rank. Runbook: docs/ELASTIC.md,
"Running on spot capacity".
"""

import contextlib
import json
import logging
import os
import signal
import socket
import sys
import threading
import time

logger = logging.getLogger("horovod_tpu")

GRACE_ENV = "HOROVOD_GRACE_SECONDS"
DEFAULT_GRACE_SECONDS = 30.0
NOTICE_FILE_ENV = "HOROVOD_PREEMPT_NOTICE_FILE"
NOTICE_URL_ENV = "HOROVOD_PREEMPT_NOTICE_URL"
POLL_ENV = "HOROVOD_PREEMPT_POLL_SECONDS"

# KV keys of the doomed-host plane (the driver consumes + deletes both
# at its next rendezvous; elastic/driver.py)
DOOMED_KEY_PREFIX = "elastic/doomed/"
DOOMED_MARKER_KEY = "elastic/doomed-latest"

# A bare SIGTERM arriving this soon after ANOTHER host's doomed
# announcement is the launcher's teardown fan-out (the evicted rank
# exited, the job monitor is recycling the epoch), not a second
# preemption — announcing *our* host doomed too would drain healthy
# capacity. A genuine second preemption inside this window degrades
# gracefully: the rank still grace-commits and exits clean, it just is
# not pre-drained from the next rendezvous.
TEARDOWN_WINDOW_S = 60.0


def grace_seconds(env=None):
    """The grace budget: seconds between the preemption notice and the
    host's death that the eviction may spend committing. Size it above
    the p99 ``hvd_ckpt_save_seconds`` tail (docs/ELASTIC.md)."""
    raw = (env if env is not None else os.environ).get(GRACE_ENV)
    if not raw:
        return DEFAULT_GRACE_SECONDS
    try:
        return max(0.0, float(raw))
    except ValueError:
        logger.warning("preempt: bad %s=%r; using %.0fs", GRACE_ENV, raw,
                       DEFAULT_GRACE_SECONDS)
        return DEFAULT_GRACE_SECONDS


def local_host(env=None):
    env = env if env is not None else os.environ
    return env.get("HOROVOD_HOSTNAME") or socket.gethostname()


def configured(env=None):
    """True when this process should install an eviction handler even
    outside a driver-managed elastic epoch (an explicit grace budget or
    notice source in the env is an opt-in)."""
    env = env if env is not None else os.environ
    return bool(env.get(GRACE_ENV) or env.get(NOTICE_FILE_ENV)
                or env.get(NOTICE_URL_ENV))


class GracefulEvictionHandler:
    """One rank's eviction path (module docstring). ``clock`` and
    ``exit_fn`` are injectable so tests can drive the whole eviction
    without dying; ``finished`` is set right before ``exit_fn`` runs."""

    def __init__(self, state=None, grace=None, notice_file=None,
                 notice_url=None, poll_interval=None,
                 clock=time.monotonic, exit_fn=None, env=None):
        e = env if env is not None else os.environ
        self._env = e
        self._grace = grace_seconds(e) if grace is None else float(grace)
        self._notice_file = notice_file if notice_file is not None \
            else e.get(NOTICE_FILE_ENV)
        self._notice_url = notice_url if notice_url is not None \
            else e.get(NOTICE_URL_ENV)
        try:
            self._poll = float(poll_interval if poll_interval is not None
                               else e.get(POLL_ENV) or 1.0)
        except ValueError:
            self._poll = 1.0
        self._clock = clock
        self._exit = exit_fn if exit_fn is not None else os._exit
        self._state = state
        self._host = local_host(e)
        self._rank = int(e.get("HOROVOD_RANK", "0") or 0)
        self._evicting = threading.Event()
        self._stop = threading.Event()
        self.finished = threading.Event()
        self.last = None      # {"kind", "outcome", ...} of the eviction
        self.installed = False
        self._via_recorder = False
        self._fallback = None
        self._poller = None

    def attach_state(self, state):
        """Point the bounded force-commit at the run's elastic state
        (its ``flush(timeout=...)``). The ``@hvd.elastic.run`` wrapper
        does this automatically."""
        self._state = state

    # -- install / uninstall -------------------------------------------------
    def install(self):
        """Arm the notice sources. Prefers the flight recorder's
        wakeup-fd watcher (a rank parked in a native collective still
        evicts); falls back to a self-pipe ``signal.signal`` path.
        Idempotent."""
        if self.installed:
            return self
        self.installed = True
        try:
            from horovod_tpu.diag import recorder as _flightrec
            watcher = _flightrec.signal_watcher_active()
        except ImportError:
            watcher = False
        if watcher:
            _flightrec.add_signal_listener(signal.SIGTERM, self._on_signal)
            self._via_recorder = True
        else:
            self._install_fallback()
        if self._notice_file or self._notice_url:
            self._poller = threading.Thread(
                target=self._poll_notices, daemon=True,
                name="hvd_tpu_preempt_poll")
            self._poller.start()
        return self

    def uninstall(self):
        if not self.installed:
            return
        self.installed = False
        self._stop.set()
        if self._via_recorder:
            self._via_recorder = False
            try:
                from horovod_tpu.diag import recorder as _flightrec
                _flightrec.remove_signal_listener(signal.SIGTERM,
                                                  self._on_signal)
            except ImportError:
                pass
        fb = self._fallback
        self._fallback = None
        if fb is not None:
            try:
                if signal.getsignal(signal.SIGTERM) is fb["handler"]:
                    prev = fb["prev"]
                    signal.signal(signal.SIGTERM,
                                  prev if prev is not None
                                  else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            for fd in fb["pipe"][::-1]:  # write end first: EOF wakes read
                try:
                    os.close(fd)
                except OSError:
                    pass

    # -- notice sources ------------------------------------------------------
    def _on_signal(self, signum):
        # recorder watcher thread — free to block; the recorder already
        # dumped for this signal before dispatching listeners
        self.trigger("sigterm", signum=int(signum))

    def _install_fallback(self):
        """Degraded mode (no recorder watcher): own self-pipe. The
        handler body only ``os.write``s (HVD-SIGSAFE); a waiter thread
        runs the eviction. A rank parked in native code will not reach
        the Python handler here — the recorder path exists for that."""
        if threading.current_thread() is not threading.main_thread():
            logger.debug("preempt: not the main thread and no recorder "
                         "watcher; SIGTERM eviction unavailable")
            return
        try:
            r_fd, w_fd = os.pipe()
            os.set_blocking(w_fd, False)
        except OSError:
            return

        def _handler(signum, frame):
            try:
                os.write(w_fd, b"\x01")
            except OSError:
                pass

        try:
            prev = signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            for fd in (w_fd, r_fd):
                try:
                    os.close(fd)
                except OSError:
                    pass
            return

        def _wait():
            try:
                data = os.read(r_fd, 1)
            except OSError:
                return
            if data and not self._stop.is_set():
                self.trigger("sigterm", signum=int(signal.SIGTERM))

        waiter = threading.Thread(target=_wait, daemon=True,
                                  name="hvd_tpu_preempt")
        waiter.start()
        self._fallback = {"pipe": (r_fd, w_fd), "prev": prev,
                          "handler": _handler, "waiter": waiter}

    def _poll_notices(self):
        while not self._stop.is_set() and not self._evicting.is_set():
            kind = self._check_notice()
            if kind:
                self.trigger(kind)
                return
            self._stop.wait(self._poll)

    def _check_notice(self):
        if self._notice_file and os.path.exists(self._notice_file):
            return "notice:file"
        if self._notice_url:
            import urllib.error
            import urllib.request
            try:
                with urllib.request.urlopen(self._notice_url,
                                            timeout=2.0) as r:
                    body = r.read(64).decode("utf-8",
                                             errors="replace").strip()
                # GCE's /instance/preempted probe answers 200 with
                # TRUE/FALSE; a bare 200 (custom notifiers) also counts
                if body.upper() not in ("FALSE", "0", "NO"):
                    return "notice:http"
            except (OSError, urllib.error.URLError):
                pass
        return None

    # -- the eviction --------------------------------------------------------
    def trigger(self, kind, signum=None):
        """Begin the eviction once (idempotent; safe from any thread).
        Returns the thread driving it, or None when one already ran."""
        if self._evicting.is_set():
            return None
        self._evicting.set()
        t = threading.Thread(target=self._evict, args=(kind, signum),
                             name="hvd_tpu_evict")
        t.start()
        return t

    def _evict(self, kind, signum):
        if kind == "sigterm" and self._peer_recently_doomed():
            # the launcher's post-eviction fan-out, not a preemption of
            # THIS host (see TEARDOWN_WINDOW_S)
            kind = "teardown"
        deadline = self._clock() + self._grace
        logger.warning("graceful eviction (%s): grace %.1fs, host %s",
                       kind, self._grace, self._host)
        _record("preempt", kind=kind, signum=signum, host=self._host,
                grace=round(self._grace, 3))
        self._count(kind)
        ledger = _get_ledger()
        bracket = ledger.phase("preemption") if ledger is not None \
            else contextlib.nullcontext()
        announced = False
        with bracket:
            if kind != "teardown":
                announced = self._announce(kind)
            outcome, commit_s = self._force_commit(deadline)
        self._observe_commit(commit_s)
        _record("preempt", kind=kind, outcome=outcome, announced=announced,
                commit_seconds=round(commit_s, 6))
        self.last = {"kind": kind, "outcome": outcome,
                     "announced": announced, "commit_seconds": commit_s}
        self._write_dumps(kind)
        code = self._exit_code()
        logger.warning("graceful eviction (%s): commit %s in %.2fs; "
                       "exiting %d", kind, outcome, commit_s, code)
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except (OSError, ValueError):
            pass
        self.finished.set()
        self._exit(code)

    def _kv_endpoint(self):
        addr = self._env.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
        try:
            port = int(self._env.get("HOROVOD_GLOO_RENDEZVOUS_PORT") or 0)
        except ValueError:
            port = 0
        return (addr, port) if addr and port > 0 else (None, 0)

    def _peer_recently_doomed(self):
        addr, port = self._kv_endpoint()
        if not addr:
            return False
        try:
            from horovod_tpu.run import secret as _secret
            from horovod_tpu.run.rendezvous import kv_get
            raw = kv_get(addr, port, DOOMED_MARKER_KEY,
                         auth_key=_secret.key_from_env(self._env))
        except OSError:
            return False
        if not raw:
            return False
        try:
            marker = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return False
        return (marker.get("host") not in (None, self._host)
                and time.time() - float(marker.get("time") or 0)
                < TEARDOWN_WINDOW_S)

    def _announce(self, kind):
        """Publish ``elastic/doomed/<host>`` (+ the latest-marker) so
        the driver drains this host from the next rendezvous."""
        addr, port = self._kv_endpoint()
        if not addr:
            return False
        payload = json.dumps({
            "host": self._host, "rank": self._rank, "kind": kind,
            "time": time.time(), "grace": self._grace,
        }).encode("utf-8")
        try:
            from horovod_tpu.run import secret as _secret
            from horovod_tpu.run.rendezvous import kv_put
            key = _secret.key_from_env(self._env)
            kv_put(addr, port, DOOMED_KEY_PREFIX + self._host, payload,
                   auth_key=key)
            kv_put(addr, port, DOOMED_MARKER_KEY, payload, auth_key=key)
        except OSError:
            logger.warning("preempt: doomed-host announcement failed "
                           "(driver will see a crash instead of a drain)",
                           exc_info=True)
            return False
        return True

    def _force_commit(self, deadline):
        state = self._state
        t0 = self._clock()
        if state is None:
            return "no-state", 0.0
        timeout = max(0.5, deadline - t0)
        try:
            flush = getattr(state, "flush", None)
            if callable(flush):
                flush(timeout=timeout)
                outcome = "committed"
            else:
                outcome = "no-op"
        except TimeoutError:
            outcome = "timeout"
        # hvd-lint: disable=HVD-EXCEPT -- the eviction must reach exit whatever the ckpt does
        except Exception:
            logger.warning("preempt: grace commit failed", exc_info=True)
            outcome = "error"
        return outcome, max(0.0, self._clock() - t0)

    def _exit_code(self):
        if "HOROVOD_ELASTIC_EPOCH" in self._env:
            try:
                from horovod_tpu.elastic.driver import EXIT_RENDEZVOUS
                return EXIT_RENDEZVOUS
            except ImportError:
                return 75
        return 0

    def _write_dumps(self, kind):
        try:
            from horovod_tpu.diag import recorder as _flightrec
        except ImportError:
            return
        rec = _flightrec.get_recorder()
        dump_dir = rec.dump_dir if rec is not None \
            else self._env.get("HOROVOD_FLIGHTREC_DIR")
        if dump_dir:
            try:
                ledger = _get_ledger()
                if ledger is not None and ledger.enabled and ledger.started:
                    ledger.write_dump(dump_dir, self._rank,
                                      extra={"preempted": kind})
            # hvd-lint: disable=HVD-EXCEPT -- accounting must not block the exit path
            except Exception:
                logger.debug("preempt: goodput dump failed", exc_info=True)
        _flightrec.dump_now(reason="preempt")

    # -- metrics -------------------------------------------------------------
    def _count(self, kind):
        try:
            from horovod_tpu.telemetry import instruments as _tele
            from horovod_tpu.telemetry.registry import get_registry
            get_registry().counter(
                _tele.PREEMPTIONS_TOTAL,
                "Preemption notices acted on, by source kind "
                "(docs/OBSERVABILITY.md)",
                label_names=("kind",)).labels(kind).inc()
        # hvd-lint: disable=HVD-EXCEPT -- telemetry must not block the exit path
        except Exception:
            pass

    def _observe_commit(self, seconds):
        try:
            from horovod_tpu.telemetry import instruments as _tele
            from horovod_tpu.telemetry.registry import get_registry
            get_registry().histogram(
                _tele.GRACE_COMMIT_SECONDS,
                "Bounded force-commit duration inside the eviction "
                "grace window").observe(seconds)
        # hvd-lint: disable=HVD-EXCEPT -- telemetry must not block the exit path
        except Exception:
            pass


def _get_ledger():
    try:
        from horovod_tpu.telemetry import ledger as _ledger_lib
        return _ledger_lib.get_ledger()
    # hvd-lint: disable=HVD-EXCEPT -- accounting must not block the eviction
    except Exception:
        return None


def _record(etype, **fields):
    try:
        from horovod_tpu.diag import recorder as _flightrec
        _flightrec.record_event(etype, **fields)
    # hvd-lint: disable=HVD-EXCEPT -- forensics must not block the eviction
    except Exception:
        pass


# -- the process handler -----------------------------------------------------

_handler = None


def install(state=None, **kwargs):
    """Create (once) and arm this process's eviction handler. A second
    call just re-attaches ``state``."""
    global _handler
    if _handler is None:
        _handler = GracefulEvictionHandler(state=state, **kwargs)
        _handler.install()
    elif state is not None:
        _handler.attach_state(state)
    return _handler


def get_handler():
    return _handler


def attach_state(state):
    """Best-effort: point an installed handler at the run's elastic
    state (no-op without one)."""
    if _handler is not None:
        _handler.attach_state(state)


def uninstall():
    global _handler
    if _handler is not None:
        _handler.uninstall()
        _handler = None
