"""Elastic worker state: commit / restore / sync.

Reference: ``horovod/common/elastic.py`` (0.20+) — a ``State`` object the
training loop commits at batch boundaries. On a peer failure the elastic
loop calls ``restore()`` (roll back to the last commit, discarding the
half-applied batch); on a membership change it keeps the state and only
``sync()``-s so new workers start from the survivors' progress.

``sync()`` broadcasts from the **lowest-rank committed** worker through
the existing collective plane (``ops.collective.broadcast`` via
``hvd.broadcast_variables``) so a freshly (re)spawned worker adopts the
survivors' state; disk-backed commits ride ``checkpoint.py`` so state
also survives full process loss (the launcher-restart recovery mode,
docs/ELASTIC.md).
"""

import copy
import logging
import os

import numpy as np

logger = logging.getLogger("horovod_tpu")

# "No committed state" sentinel for the lowest-committed-rank election;
# must beat any real rank in a Min reduction.
_UNCOMMITTED = 1 << 30


def _env_rank():
    # one authority for the initialized-hvd-or-env resolution, shared
    # with the checkpointer it keys (ckpt.snapshot)
    from horovod_tpu.ckpt.snapshot import _env_rank_world
    return _env_rank_world()[0]


def _env_world():
    from horovod_tpu.ckpt.snapshot import _env_rank_world
    return _env_rank_world()[1]


class State:
    """Base elastic state (reference ``State``): subclasses define what
    ``save``/``restore``/``sync`` mean for their payload.

    ``commit()`` is the batch-boundary hook: it saves a restore point and
    then surfaces any pending membership interrupt — so an interrupt can
    never land mid-batch and the committed snapshot always reflects a
    completed batch."""

    def __init__(self, notification_manager=None):
        if notification_manager is None:
            from horovod_tpu.elastic.notification import notification_manager \
                as default_manager
            notification_manager = default_manager
        self._notification_manager = notification_manager
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks):
        """Callbacks run by the elastic loop after a reset (re-rendezvous)
        — e.g. rebuild a jitted step for a new world size."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        # a reset means the world is about to change shape: any async
        # checkpoint still in flight must reach durability first, or the
        # new world could restore a step the old world never committed.
        # The wait is BOUNDED: when the reset is happening because a
        # peer died, that peer's shard never lands and the two-phase
        # commit barrier can never complete — an unbounded flush would
        # park the whole recovery path on a dead rank. On timeout the
        # in-flight save is abandoned (its manifest-less dir is
        # invisible to restore and GC'd later).
        try:
            self.flush(timeout=float(
                os.environ.get("HOROVOD_CKPT_RESET_TIMEOUT", "10")))
        # hvd-lint: disable=HVD-EXCEPT -- a failed flush must not block the recovery path
        except Exception as e:  # noqa: BLE001 — a failed flush must not
            logger.warning("elastic: checkpoint flush before reset "
                           "failed: %s — abandoning the in-flight save "
                           "(restore falls back to the last committed "
                           "manifest)", e)  # block the recovery path
            self._abandon_pending_saves()
        # the re-rendezvous that triggered this reset may have changed
        # the device set; a stale cached proc mesh (built from the old
        # jax.devices()) would corrupt the next eager collective
        from horovod_tpu.ops import collective
        collective.invalidate_proc_mesh()
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def commit(self):
        self.save()
        self._heartbeat()
        self.check_host_updates()

    def _heartbeat(self):
        """Every commit doubles as a liveness signal. When a stall
        inspector is live (``hvd.init()`` under HOROVOD_ELASTIC=1), the
        commit feeds ``record_progress`` — resetting the stall watchdog
        AND firing its listeners, which include the KV heartbeat
        publisher (worker.attach_progress_reporter). Without one, the
        heartbeat is published directly."""
        step = self._progress_step()
        inspector = None
        try:
            from horovod_tpu import basics
            inspector = basics._state.stall_inspector
        except (ImportError, AttributeError):
            pass  # services not installed yet; heartbeat goes direct
        if inspector is not None:
            inspector.record_progress(step)
        from horovod_tpu.elastic import worker
        ctx = worker.get_worker_context()
        if ctx is not None and not (inspector is not None
                                    and ctx.attached_to_inspector):
            ctx.report_progress(step)

    def _progress_step(self):
        """Best-effort step counter for the heartbeat: a ``step``
        attribute on the state itself, or on any held value (e.g. a
        whole TrainState under ``train_state``)."""
        candidates = [getattr(self, "step", None)]
        candidates += [getattr(getattr(self, k, None), "step", None)
                       for k in getattr(self, "_state_keys", ())]
        for cand in candidates:
            if cand is None:
                continue
            try:
                return int(np.asarray(cand))
            except (TypeError, ValueError):
                continue
        return None

    def check_host_updates(self):
        """Raise ``HostsUpdatedInterrupt`` if the driver flagged a
        membership change since the last check."""
        self._notification_manager.check()

    def flush(self, timeout=None):
        """Force any asynchronous persistence to durability. Called by
        the elastic loop before every re-rendezvous (and by subclasses
        with disk-backed commits); base states have nothing pending."""

    def _abandon_pending_saves(self):
        """Drop asynchronous persistence that cannot complete (e.g. a
        commit barrier broken by a dead peer); base states have none."""

    # -- subclass payload hooks ---------------------------------------------
    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """Plain-Python attribute state (reference ``ObjectState``): every
    keyword becomes an attribute; commit deep-copies them, restore puts
    the copies back, sync adopts the lowest committed rank's values via
    the collective plane (pickle-free: values must be tree-mappable)."""

    def __init__(self, notification_manager=None, **kwargs):
        super().__init__(notification_manager=notification_manager)
        self._saved_state = None
        self._state_keys = sorted(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def _capture(self):
        return {k: copy.deepcopy(getattr(self, k))
                for k in self._state_keys}

    def _adopt(self, values):
        for k in self._state_keys:
            setattr(self, k, copy.deepcopy(values[k]))

    def save(self):
        self._saved_state = self._capture()

    def restore(self):
        if self._saved_state is not None:
            self._adopt(self._saved_state)

    def has_commit(self):
        return self._saved_state is not None

    def sync(self, root_rank=None):
        """Adopt the committed state of ``root_rank`` (default: the
        lowest rank that has committed; the election and the broadcast
        both ride the collective plane, so this is a collective call).
        Returns the rank the state was adopted from."""
        root = _elect_root(root_rank, self.has_commit())
        if root is None:
            # nobody has progress: baseline is the fresh init — but the
            # init must still be BROADCAST from rank 0 (reference sync
            # semantics) or rank-dependent initialization would train
            # silently divergent models. After a driver relaunch this is
            # almost certainly LOST progress (e.g. a checkpoint
            # directory not on shared storage) — say so loudly instead
            # of silently retraining from step 0.
            epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0") or 0)
            if epoch > 1:
                logger.warning(
                    "elastic: no committed state found on any rank after "
                    "a relaunch (epoch %d) — training restarts from the "
                    "fresh initialization. Put JaxState(directory=...) "
                    "on storage every replacement worker can read for "
                    "cross-relaunch continuity.", epoch)
            self._adopt(_broadcast_tree(self._capture(), 0))
            self.save()
            return 0
        payload = (self._saved_state if self.has_commit()
                   else self._capture())
        synced = _broadcast_tree(payload, root)
        self._adopt(synced)
        self._saved_state = self._capture()
        return root


class JaxState(ObjectState):
    """JAX-native elastic state: keyword pytrees (``params``,
    ``opt_state``, a whole ``TrainState``, scalars...) with

    * **commit** — pulls every leaf to host memory (``device_get``) and,
      when ``directory`` is given, persists through the async sharded
      checkpoint subsystem (``horovod_tpu/ckpt``): EVERY rank writes its
      own shard (this rank's ZeRO rows included, never re-gathered), the
      serialize/fsync overlaps training on a background thread, and rank
      0 commits the two-phase manifest. ``checkpoint_every=K`` thins the
      disk cadence to every K-th commit; ``async_save=False`` restores
      the old stall-until-durable behavior.
    * **restore** — re-adopts the last in-memory commit, falling back to
      the newest MANIFEST-complete on-disk checkpoint for freshly
      (re)spawned workers (resharding ZeRO state when the world size
      changed N→M); legacy rank-0 ``ckpt-<n>.msgpack`` files from the
      pre-subsystem format still restore.
    * **sync** — broadcasts the trees from the lowest committed rank via
      ``ops.collective`` so surviving workers hand their progress to new
      ones without touching disk.
    * **flush** — forces in-flight async saves to durability; the
      elastic loop calls it before every re-rendezvous.

    A ``horovod_tpu.data.PrefetchLoader`` attached via ``loader=`` (or
    :meth:`attach_loader`) makes the INPUT position part of the state:
    its cursor is captured at every commit, persisted in the checkpoint
    MANIFEST (``meta["data_cursor"]``), rolled back by ``restore()``
    (a retried batch replays the same examples), adopted from the
    elected root by ``sync()``, and re-sharded over the new membership
    on ``reset()`` — docs/DATA.md.
    """

    def __init__(self, directory=None, keep=3, notification_manager=None,
                 async_save=True, checkpoint_every=1, loader=None,
                 **kwargs):
        super().__init__(notification_manager=notification_manager,
                         **kwargs)
        self._directory = directory
        self._keep = keep
        self._async_save = async_save
        self.checkpoint_every = max(1, int(checkpoint_every))
        self._commit_count = 0
        self._ckpt = None
        self._loader = loader
        self._saved_cursor = None

    def attach_loader(self, loader):
        """Adopt ``loader``'s cursor into the commit/restore/sync cycle
        (idempotent; ``training.elastic_train_loop`` calls this when
        handed a loader). If a cursor was already restored from disk —
        the loader arrived after ``restore()`` — it is applied now."""
        self._loader = loader
        if loader is not None and self._saved_cursor is not None:
            loader.set_cursor(self._saved_cursor)

    def _capture(self):
        # a REAL host copy, ZeroState included (it is a registered
        # pytree, so tree_map reaches its inner arrays): the training
        # step donates its input buffers (make_train_step donate=True),
        # so holding device references here would hand restore()/sync()
        # deleted arrays after the very next step. np.array, not
        # asarray — device_get is identity on numpy-backed state (and
        # can be zero-copy on the CPU backend), and the commit must not
        # alias arrays the loop mutates in place
        import jax
        return {k: jax.tree_util.tree_map(
                    lambda x: np.array(jax.device_get(x)),
                    getattr(self, k))
                for k in self._state_keys}

    def _adopt(self, values):
        for k in self._state_keys:
            setattr(self, k, values[k])

    def _checkpointer(self):
        from horovod_tpu import ckpt as ckpt_lib
        rank, world = _env_rank(), _env_world()
        if self._ckpt is not None and (self._ckpt.rank != rank
                                       or self._ckpt.world != world):
            # the world changed shape under us (elastic re-rendezvous):
            # drain the old writer (bounded — its commit barrier may be
            # waiting on ranks that no longer exist), shard for the new
            # membership
            self._ckpt.close(timeout=5.0)
            self._ckpt = None
        if self._ckpt is None:
            self._ckpt = ckpt_lib.AsyncCheckpointer(
                self._directory, keep=self._keep, rank=rank, world=world)
        return self._ckpt

    def save(self):
        self._saved_state = self._capture()
        if self._loader is not None:
            self._saved_cursor = self._loader.cursor()
        self._commit_count += 1
        if self._directory and \
                self._commit_count % self.checkpoint_every == 0:
            meta = {"commit": self._commit_count}
            if self._saved_cursor is not None:
                # the input position rides the manifest so a restore
                # resumes the batch stream exactly where this commit
                # left it (docs/DATA.md)
                meta["data_cursor"] = self._saved_cursor
            # hand the writer the capture itself: it is already host
            # numpy (ZeroState structure preserved by tree_map), so the
            # snapshot half's device_get degrades to a no-op instead of
            # pulling the live device tree a second time per commit
            self._checkpointer().save(
                self._commit_count, self._saved_state, meta=meta,
                block=not self._async_save)

    def flush(self, timeout=None):
        if self._ckpt is not None:
            self._ckpt.flush(timeout=timeout)

    def _abandon_pending_saves(self):
        if self._ckpt is not None:
            self._ckpt.abandon()
            self._ckpt = None

    def restore(self):
        if self._saved_state is None:
            self._restore_from_disk()
        super().restore()
        if self._loader is not None and self._saved_cursor is not None:
            # roll the input position back WITH the model state: the
            # retried steps replay the exact batches of the discarded
            # ones
            self._loader.set_cursor(self._saved_cursor)

    def reset(self):
        super().reset()
        if self._loader is not None:
            try:
                # membership changed: re-shard the REMAINING sample
                # space across the new world (docs/DATA.md)
                self._loader.on_reset()
            # hvd-lint: disable=HVD-EXCEPT -- never block recovery; the reshard failure is logged
            except Exception:  # noqa: BLE001 — never block recovery
                logger.warning("elastic: loader reshard on reset failed",
                               exc_info=True)

    def _restore_from_disk(self):
        if not self._directory:
            return False
        # a rank rebuilding itself from a checkpoint is not serving:
        # the bracket books the time as rendezvous_recovery and flips
        # /healthz to 503 with phase="ckpt_restore" while it runs
        from horovod_tpu.telemetry import ledger as ledger_lib
        with ledger_lib.get_ledger().phase("ckpt_restore",
                                           charge="rendezvous_recovery"):
            return self._restore_from_disk_inner()

    def _restore_from_disk_inner(self):
        from horovod_tpu import checkpoint
        from horovod_tpu import ckpt as ckpt_lib
        if self._ckpt is not None:
            self._ckpt.flush()  # never restore past an in-flight save
        if ckpt_lib.latest_complete_step(self._directory) is not None:
            target = {k: getattr(self, k) for k in self._state_keys}
            step, restored, meta = ckpt_lib.restore_sharded(
                self._directory, target)
            self._saved_state = restored
            self._commit_count = int(meta.get("commit", step))
            self._saved_cursor = meta.get("data_cursor") \
                or self._saved_cursor
            logger.info("elastic: restored commit %d from sharded "
                        "checkpoint %s", self._commit_count,
                        self._directory)
            return True
        # legacy single-file format (pre-ckpt-subsystem checkpoints)
        steps = checkpoint.list_steps(self._directory)
        if not steps:
            return False
        target = {k: _leaf_dict(v)  # flax restores by target structure
                  for k, v in self._capture().items()}
        restored, _opt, meta = checkpoint.restore_checkpoint(
            self._directory, steps[-1], target)
        self._saved_state = {k: _unflatten_like(getattr(self, k),
                                                restored[k])
                             for k in self._state_keys}
        self._commit_count = int(meta.get("commit", steps[-1]))
        self._saved_cursor = meta.get("data_cursor") or self._saved_cursor
        logger.info("elastic: restored commit %d from %s",
                    self._commit_count, self._directory)
        return True

    def sync(self, root_rank=None):
        # A respawned worker first picks up any on-disk commit so the
        # committed-rank election sees its real progress.
        if self._saved_state is None:
            self._restore_from_disk()
            super().restore()
        root = super().sync(root_rank=root_rank)
        # the trees just adopted are ``root``'s commit — adopt its commit
        # COUNTER too: a disk-restored newcomer sits at the on-disk count
        # while survivors are in-memory ahead, and ranks that disagree on
        # the count would write their next shards under DIFFERENT step
        # numbers, a two-phase commit barrier that can never complete
        self._commit_count = int(np.asarray(_broadcast_tree(
            np.asarray(self._commit_count, dtype=np.int64), root)))
        self._sync_cursor(root)
        return root

    def _sync_cursor(self, root):
        """Adopt ``root``'s committed data cursor (JSON over the
        collective plane: a length broadcast sizes the byte buffer, so
        ranks never need matching local payloads). A newcomer that
        joined without disk access still resumes the batch stream at
        the survivors' position."""
        import json as _json
        if self._loader is None:
            # no data plane on this state: skip the exchange. The
            # branch must be UNIFORM across ranks or the length
            # broadcast wedges — loader attachment is part of the
            # training program (same on every rank), unlike
            # _saved_cursor, which a disk restore can set on some
            # ranks only (e.g. loaderless jobs reading loader-era
            # manifests).
            return
        payload = b""
        if self._saved_cursor is not None:
            payload = _json.dumps(self._saved_cursor,
                                  sort_keys=True).encode()
        length = int(np.asarray(_broadcast_tree(
            np.asarray(len(payload), dtype=np.int64), root)))
        if length <= 0:
            return
        buf = (np.frombuffer(payload, dtype=np.uint8).copy()
               if len(payload) == length
               else np.zeros(length, dtype=np.uint8))
        buf = np.asarray(_broadcast_tree(buf, root))
        try:
            cur = _json.loads(bytes(bytearray(buf)).decode())
        except (ValueError, UnicodeDecodeError):
            logger.warning("elastic: undecodable data cursor from "
                           "rank %s; keeping the local one", root)
            return
        self._saved_cursor = cur
        if self._loader is not None:
            self._loader.set_cursor(cur)


def _leaf_dict(tree):
    """Flatten a pytree into ``{"0": leaf, "1": leaf, ...}`` (host
    numpy). Checkpoints store this form so custom pytree nodes survive
    the msgpack roundtrip; structure comes from the live state."""
    import jax
    return {str(i): np.asarray(jax.device_get(leaf))
            for i, leaf in enumerate(jax.tree_util.tree_leaves(tree))}


def _unflatten_like(tree, leaf_dict):
    """Rebuild ``tree``'s structure from a :func:`_leaf_dict` payload."""
    import jax
    treedef = jax.tree_util.tree_structure(tree)
    leaves = [leaf_dict[str(i)] for i in range(len(leaf_dict))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _elect_root(root_rank, has_commit):
    """The broadcast root: the explicit ``root_rank`` or the lowest rank
    that has a commit (Min-allreduce election); None when no rank has
    committed anything (single-process: this process's own view)."""
    if root_rank is not None:
        return root_rank
    import horovod_tpu as hvd
    if not (hvd.is_initialized() and hvd.size() > 1):
        return 0 if has_commit else None
    from horovod_tpu.ops import collective
    me = _env_rank() if has_commit else _UNCOMMITTED
    root = int(np.asarray(collective.allreduce(
        np.asarray(me, dtype=np.int32), op=collective.Min)))
    return None if root >= _UNCOMMITTED else root


def _broadcast_tree(tree, root):
    """Broadcast every leaf of ``tree`` from ``root`` over the collective
    plane (identity when not running multi-process)."""
    import horovod_tpu as hvd
    if not (hvd.is_initialized() and hvd.size() > 1):
        return tree
    return hvd.broadcast_variables(tree, root_rank=root)
