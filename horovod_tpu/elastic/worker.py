"""Worker-side elastic context: notification endpoint + heartbeats.

The worker half of the driver contract (elastic/driver.py):

* starts a :class:`~horovod_tpu.elastic.notification.
  WorkerNotificationService` and publishes its endpoint on the launcher
  KV (``elastic/notif/<epoch>/<rank>``) so the driver can interrupt this
  worker between batches,
* publishes step heartbeats (``elastic/heartbeat/<epoch>/<rank>``) — fed
  by ``runtime/stall.py``'s progress hooks, they are the driver's
  liveness view of this worker.

Workers launched by the elastic driver get ``HOROVOD_ELASTIC=1`` and
``HOROVOD_ELASTIC_EPOCH`` in their env; ``init_worker_context()`` reads
the rest of the standard launcher contract (rank, rendezvous KV address,
secret key).
"""

import json
import logging
import os
import socket
import time

from horovod_tpu.elastic import notification
from horovod_tpu.run import secret as _secret
from horovod_tpu.run.rendezvous import kv_put

logger = logging.getLogger("horovod_tpu")

_context = None


def is_elastic_worker(env=None):
    return (env or os.environ).get("HOROVOD_ELASTIC") == "1"


class WorkerContext:
    """One elastic worker's control-plane attachments."""

    def __init__(self, rank=None, epoch=None, kv_addr=None, kv_port=None,
                 auth_key=None):
        env = os.environ
        self.rank = rank if rank is not None else int(
            env.get("HOROVOD_RANK", "0"))
        self.epoch = epoch if epoch is not None else int(
            env.get("HOROVOD_ELASTIC_EPOCH", "0"))
        self._kv_addr = kv_addr or env.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
        self._kv_port = int(kv_port or
                            env.get("HOROVOD_GLOO_RENDEZVOUS_PORT", "0"))
        self._key = auth_key if auth_key is not None else \
            _secret.key_from_env()
        self.attached_to_inspector = False
        self.manager = notification.notification_manager
        # no per-run key (all-local job) -> the fixed LOCAL_KEY provides
        # no secrecy, so loopback binding must be the isolation; only
        # authenticated multi-host runs listen on the network
        self.service = notification.WorkerNotificationService(
            key=self._key, manager=self.manager,
            host="0.0.0.0" if self._key else "127.0.0.1")
        self._publish_endpoint()
        try:
            from horovod_tpu.diag import recorder as _flightrec
            _flightrec.record_event("epoch", epoch=self.epoch)
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the worker epoch setup
        except Exception:
            pass

    def _advertised_addr(self):
        """An address the DRIVER can dial: this host's primary IP, or
        loopback when resolution fails / the job is launcher-local."""
        if not self._key:
            return "127.0.0.1"  # matches the loopback-only bind above
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    def _kv_ready(self):
        return bool(self._kv_addr) and self._kv_port > 0

    def _publish_endpoint(self):
        if not self._kv_ready():
            logger.debug("elastic: no rendezvous KV; notification "
                         "endpoint not published")
            return
        payload = {"addr": self._advertised_addr(),
                   "port": self.service.port}
        kv_put(self._kv_addr, self._kv_port,
               f"elastic/notif/{self.epoch}/{self.rank}",
               json.dumps(payload).encode(), auth_key=self._key)

    def report_progress(self, step=None):
        """Publish a heartbeat; wired into ``StallInspector.
        record_progress`` via :func:`attach_progress_reporter` so every
        completed step refreshes the driver's liveness view. The
        heartbeat carries a compact metrics snapshot
        (``telemetry.instruments.kv_snapshot``) so the driver can render
        a cluster view and flag stragglers without any new channel."""
        if not self._kv_ready():
            return
        payload = {"step": step, "time": time.time()}
        try:
            from horovod_tpu.telemetry import instruments as _tele
            metrics = _tele.kv_snapshot()
            if metrics:
                payload["metrics"] = metrics
        # hvd-lint: disable=HVD-EXCEPT -- telemetry must never break the liveness channel
        except Exception:
            pass  # telemetry must never break the liveness channel
        try:
            from horovod_tpu.diag import recorder as _flightrec
            _flightrec.record_event("heartbeat", step=step)
            digest = _flightrec.current_digest()
            if digest:
                # the desync plane rides the channel that already
                # exists: seq + schedule hash (+ a short history) so the
                # driver can name a diverged/stuck rank WHILE it hangs
                payload["flightrec"] = digest
        # hvd-lint: disable=HVD-EXCEPT -- forensics must never break the liveness channel
        except Exception:
            pass  # forensics must never break the liveness channel
        try:
            kv_put(self._kv_addr, self._kv_port,
                   f"elastic/heartbeat/{self.epoch}/{self.rank}",
                   json.dumps(payload).encode(), auth_key=self._key)
        except OSError:
            pass  # the launcher KV going away must never kill a step

    def shutdown(self):
        self.service.shutdown()


def init_worker_context(**kwargs):
    """Create (once) and return this process's :class:`WorkerContext`."""
    global _context
    if _context is None:
        _context = WorkerContext(**kwargs)
    return _context


def get_worker_context():
    return _context


def shutdown_worker_context():
    global _context
    if _context is not None:
        _context.shutdown()
        _context = None


def attach_progress_reporter(inspector, context=None):
    """Register the heartbeat publisher as a progress listener on a
    ``runtime.stall.StallInspector`` — the bridge named in the elastic
    design: stall-inspector progress hooks feed the driver's liveness
    view."""
    ctx = context or init_worker_context()
    inspector.add_progress_listener(ctx.report_progress)
    ctx.attached_to_inspector = True
    return ctx
