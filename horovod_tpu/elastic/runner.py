"""The elastic retry loop: ``@hvd.elastic.run``.

Reference: ``horovod/common/elastic.py`` ``run_fn`` — wraps a training
function taking a :class:`~horovod_tpu.elastic.state.State` first, and
implements the recovery policy:

* ``HostsUpdatedInterrupt`` (membership changed, raised at a commit
  boundary): committed progress is KEPT. Under a driver-managed worker
  (``HOROVOD_ELASTIC_EPOCH`` set) the process exits with
  ``EXIT_RENDEZVOUS`` so the driver relaunches it into the new world;
  in-process (tests, single-process elasticity) the loop re-syncs and
  retries directly.
* worker-failure exceptions (``WorkerFailureError`` plus anything passed
  via ``retryable=``): the last committed state is restored — the
  half-applied batch is discarded — then the loop re-syncs and retries,
  up to ``HOROVOD_ELASTIC_RESET_LIMIT`` resets (0 = unlimited).
"""

import functools
import logging
import os
import sys

from horovod_tpu.elastic.exceptions import (HostsUpdatedInterrupt,
                                            WorkerFailureError)

logger = logging.getLogger("horovod_tpu")


def _driver_managed():
    """True when this process was launched by an ElasticDriver epoch (so
    re-rendezvous means exit-and-be-relaunched, not retry-in-place)."""
    return "HOROVOD_ELASTIC_EPOCH" in os.environ


def run(func=None, *, retryable=()):
    """Decorate ``func(state, *args, **kwargs)`` with the elastic retry
    loop. ``retryable`` extends the worker-failure exception set (e.g.
    the RuntimeError a dead peer surfaces as from a collective)."""
    if func is None:
        return functools.partial(run, retryable=retryable)
    failure_excs = (WorkerFailureError,) + tuple(retryable)

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        from horovod_tpu.elastic import preempt as _preempt
        from horovod_tpu.elastic.driver import EXIT_RENDEZVOUS
        from horovod_tpu.telemetry import ledger as ledger_lib
        # an armed eviction handler (runtime/services.py) force-commits
        # THIS state's in-flight save inside the grace window
        _preempt.attach_state(state)
        reset_limit = int(os.environ.get("HOROVOD_ELASTIC_RESET_LIMIT",
                                         "0") or 0)
        resets = 0
        first = True

        def _recovery_bracket(in_recovery):
            # recovery time (reset/restore/resync after the FIRST
            # iteration) is a first-class goodput phase, and the open
            # bracket flips /healthz to 503 with phase="re-rendezvous"
            # while the rank is parked here (docs/OBSERVABILITY.md)
            if not in_recovery:
                import contextlib
                return contextlib.nullcontext()
            return ledger_lib.get_ledger().phase(
                "re-rendezvous", charge="rendezvous_recovery")

        while True:
            if not first:
                with _recovery_bracket(True):
                    state.on_reset()
            try:
                with _recovery_bracket(not first):
                    state.sync()
                return func(state, *args, **kwargs)
            except HostsUpdatedInterrupt as e:
                # progress is committed; only the world needs rebuilding
                if _driver_managed():
                    logger.info("elastic: hosts %s — draining for "
                                "re-rendezvous", e.res)
                    sys.exit(EXIT_RENDEZVOUS)
                logger.info("elastic: hosts %s — re-syncing in process",
                            e.res)
                first = False
            except failure_excs as e:
                resets += 1
                if reset_limit and resets > reset_limit:
                    raise WorkerFailureError(
                        f"elastic: giving up after {resets - 1} resets "
                        f"(HOROVOD_ELASTIC_RESET_LIMIT="
                        f"{reset_limit})") from e
                logger.warning("elastic: worker failure (%s); restoring "
                               "last commit (reset %d)", e, resets)
                with _recovery_bracket(True):
                    state.restore()
                first = False

    return wrapper
