"""Host discovery for elastic jobs.

Reference: ``horovod/run/elastic/discovery.py`` — a ``HostDiscovery``
interface the driver polls for the current ``{hostname: slots}`` view,
with a fixed implementation for static clusters and a script-backed one
(``--host-discovery-script``) for schedulers that can report membership
(spot/preemptible pools, TPU pod autoscalers).

The poller thread diffs consecutive views and reports additions and
removals to the driver, which turns them into worker interrupts and a
re-rendezvous (driver.py).
"""

import logging
import subprocess
import threading

logger = logging.getLogger("horovod_tpu")


class HostUpdateResult:
    """Bitmask describing a membership diff (reference
    ``HostUpdateResult``): what the poller saw between two views."""
    NO_UPDATE = 0
    ADDED = 1
    REMOVED = 2
    MIXED = ADDED | REMOVED


class HostDiscovery:
    """Interface: report the CURRENT available hosts and their slots."""

    def find_available_hosts_and_slots(self):
        """Return ``{hostname: slots}`` for every host usable right now."""
        raise NotImplementedError


class FixedHosts(HostDiscovery):
    """A static host set (reference ``FixedHosts``): elasticity then means
    "survive losing members of this set", not growing it.

    Accepts ``{host: slots}``, a ``"h1:4,h2:2"`` spec string, or a list of
    ``run.allocation.HostSlots``. The set can be swapped at runtime with
    :meth:`set` — tests and schedulers use that to simulate membership
    changes.
    """

    def __init__(self, hosts):
        self._lock = threading.Lock()
        self._hosts = _normalize_hosts(hosts)

    def find_available_hosts_and_slots(self):
        with self._lock:
            return dict(self._hosts)

    def set(self, hosts):
        with self._lock:
            self._hosts = _normalize_hosts(hosts)


class ScriptDiscovery(HostDiscovery):
    """Poll an external executable (reference ``HostDiscoveryScript``,
    ``--host-discovery-script``): it must print one host per line,
    ``hostname:slots`` or bare ``hostname`` (= ``default_slots``).

    A failing script (non-zero exit) reports an EMPTY host set — the
    driver's min-np wait then decides whether that is fatal; a flaky
    script must not crash the polling thread."""

    def __init__(self, script, default_slots=1, timeout=10.0):
        self._script = script
        self._default_slots = default_slots
        self._timeout = timeout

    def find_available_hosts_and_slots(self):
        try:
            out = subprocess.run(
                [self._script], capture_output=True, text=True,
                timeout=self._timeout)
        except (OSError, subprocess.TimeoutExpired) as e:
            logger.warning("host discovery script %s failed: %s",
                           self._script, e)
            return {}
        if out.returncode != 0:
            logger.warning("host discovery script %s exited %d: %s",
                           self._script, out.returncode,
                           out.stderr.strip()[:500])
            return {}
        hosts = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                if ":" in line:
                    name, slots = line.rsplit(":", 1)
                    hosts[name.strip()] = int(slots)
                else:
                    hosts[line] = self._default_slots
            except ValueError:
                # malformed output is a flaky poll, never a driver crash
                # (same contract as a non-zero exit)
                logger.warning("host discovery script %s printed a "
                               "malformed line %r; ignoring this poll",
                               self._script, line)
                return {}
        return hosts


def _normalize_hosts(hosts):
    if isinstance(hosts, str):
        from horovod_tpu.run.allocation import parse_hosts
        hosts = parse_hosts(hosts)
    if isinstance(hosts, dict):
        return dict(hosts)
    # list of HostSlots (or anything with .hostname/.slots)
    return {h.hostname: h.slots for h in hosts}


def diff_hosts(old, new):
    """Diff two ``{host: slots}`` views; returns ``(added, removed, res)``
    where a slot-count change on a surviving host counts as both (its
    workers must be renumbered either way)."""
    added = sorted(h for h in new
                   if h not in old or new[h] > old[h])
    removed = sorted(h for h in old
                     if h not in new or new[h] < old[h])
    res = HostUpdateResult.NO_UPDATE
    if added:
        res |= HostUpdateResult.ADDED
    if removed:
        res |= HostUpdateResult.REMOVED
    return added, removed, res


class HostDiscoveryPoller:
    """Background thread diffing consecutive discovery views (reference
    ``ElasticDriver._discover_hosts``): on any change, invokes
    ``on_update(added, removed, current, res)`` from the polling thread.

    The current view is always available via :meth:`current` (first read
    polls synchronously so callers never see an empty bootstrap view)."""

    def __init__(self, discovery, poll_interval=1.0, on_update=None):
        self._discovery = discovery
        self._interval = poll_interval
        self._on_update = on_update
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()
        self._current = None
        self._stop = threading.Event()
        self._thread = None

    def current(self):
        with self._lock:
            if self._current is not None:
                return dict(self._current)
        return self.poll_once()

    def poll_once(self):
        """One synchronous discovery round: update the view, fire the
        callback on change, return the new view.

        Serialized end-to-end: concurrent callers (the poll thread and
        the driver's min-np wait) must not interleave, or a slow caller
        could overwrite a newer view with its stale read and fire a
        phantom diff."""
        with self._poll_lock:
            new = self._discovery.find_available_hosts_and_slots()
            with self._lock:
                old, self._current = self._current, dict(new)
            if old is not None:
                added, removed, res = diff_hosts(old, new)
                if res != HostUpdateResult.NO_UPDATE and self._on_update:
                    try:
                        self._on_update(added, removed, dict(new), res)
                    # hvd-lint: disable=HVD-EXCEPT -- a bad update listener must not kill host discovery
                    except Exception:
                        logger.exception("host-update callback failed")
            return dict(new)

    def start(self):
        if self._thread is not None:
            return
        self.poll_once()  # establish the baseline before going async
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd_tpu_host_discovery",
                                        daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            # hvd-lint: disable=HVD-EXCEPT -- poll loop: transient discovery failures retry next tick
            except Exception:
                logger.exception("host discovery poll failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
