"""Checkpoint layout + the two-phase manifest commit.

Layout (one directory per step under the checkpoint root)::

    <root>/ckpt-<step>/shard-<r>-of-<w>.msgpack   per-rank payload
    <root>/ckpt-<step>/shard-<r>-of-<w>.ok        durability marker + CRC
    <root>/ckpt-<step>/MANIFEST.json              written LAST, by rank 0
    <root>/latest                                 pointer (human/tooling aid)

**Two-phase commit.** Phase 1: every rank writes its shard (tmp + fsync
+ rename + directory fsync) and then its ``.ok`` marker carrying the
shard's CRC32 and byte count — the marker is the durable "my shard is
on disk" ack. Phase 2: rank 0 waits for all ``w`` markers, aggregates
their CRCs into ``MANIFEST.json`` (tmp + fsync + rename + dir fsync),
updates ``latest``, and runs retention GC. **A checkpoint without a
manifest never happened**: the loader only ever considers
manifest-complete steps, so a crash at any point mid-save leaves either
the previous complete checkpoint (torn dir ignored, later GC'd) or the
new complete one — never a half-read.

**The barrier.** The phase-1→2 barrier is the ``.ok`` markers on the
shared checkpoint filesystem itself — sharded restore already requires
every rank to read every shard, so a shared FS is a subsystem invariant
and the markers double as the ack channel. It deliberately does NOT
ride the collective plane: commits run on a background thread
(``snapshot.AsyncCheckpointer``), and a background collective would
race the training step's collectives into a desync. When the elastic
rendezvous KV (``run/rendezvous.py``, the ``run/allocation`` plane) is
configured, each rank additionally publishes a best-effort
``ckpt/ack/<step>/<rank>`` key so the driver side can observe
checkpoint progress — but durability decisions never depend on it.

Retention GC (rank 0, after each commit): keeps the newest ``keep``
manifest-COMPLETE checkpoints; manifest-less dirs older than the newest
complete step — by step number AND by dir mtime against that step's
recorded commit time — are dead torn writes and are removed too. A
manifest-less dir newer by either measure is (or may be) an in-flight
save and is never touched: step numbering can run backwards after a
fallback restore past a damaged newest step.
"""

import json
import logging
import os
import re
import shutil
import time

logger = logging.getLogger("horovod_tpu")

MANIFEST_NAME = "MANIFEST.json"
LATEST_NAME = "latest"
# 1: one unkeyed ZeroState row per rank shard (the pre-GSPMD layout).
# 2: ZeroState rows keyed by ROW index, each shard carrying the block
#    of schedule rows its process owns (sharded.py _owned_rows) — a
#    single GSPMD process saves every row. Readers accept <= their own
#    version (v2 restores v1 shards); a payload from a NEWER writer
#    fails loudly by version, not by a misleading shape error.
FORMAT_VERSION = 2

_DIR_RE = re.compile(r"^ckpt-(\d+)$")
_POLL_S = 0.02


def step_dir(root, step):
    return os.path.join(root, f"ckpt-{int(step)}")


def shard_name(rank, world):
    return f"shard-{int(rank)}-of-{int(world)}.msgpack"


def ok_name(rank, world):
    return shard_name(rank, world) + ".ok"


def fsync_dir(path):
    """fsync a DIRECTORY so a rename into it is durable across power
    loss (rename alone only orders metadata in the page cache)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; best effort
    finally:
        os.close(fd)


def atomic_write(path, data, fsync_parent=True):
    """tmp + fsync + rename (+ parent dir fsync): the write either fully
    exists under its final name or not at all, and survives a crash."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    if fsync_parent:
        fsync_dir(os.path.dirname(path))


# -- discovery (the torn-write-recovery read side) --------------------------

def _step_dirs(root):
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            out.append(int(m.group(1)))
    return sorted(out)


def is_complete(root, step):
    return os.path.isfile(os.path.join(step_dir(root, step), MANIFEST_NAME))


def list_complete_steps(root):
    """Steps with a committed MANIFEST under ``root`` — the ONLY steps a
    loader may consider (manifest-less dirs are torn writes)."""
    return [s for s in _step_dirs(root) if is_complete(root, s)]


def latest_complete_step(root, default=None):
    """Newest committed step by SCANNING for manifests — the ``latest``
    pointer file is advisory (for humans and external tooling); the
    manifest set is the truth a crashed pointer update cannot skew."""
    steps = list_complete_steps(root)
    return steps[-1] if steps else default


def read_manifest(root, step):
    with open(os.path.join(step_dir(root, step), MANIFEST_NAME)) as f:
        return json.load(f)


def manifest_path(root, step):
    return os.path.join(step_dir(root, step), MANIFEST_NAME)


def manifest_mtime(root, step):
    """mtime of a step's committed MANIFEST, or ``None`` when the step
    dir is manifest-less (torn/in-flight — it never happened). A pure
    ``stat``: cheap enough to poll."""
    try:
        return os.path.getmtime(manifest_path(root, step))
    except OSError:
        return None


def complete_manifests(root):
    """Stat-only probe primitive: ``[(step, manifest_mtime), ...]`` for
    every manifest-complete step under ``root``, ascending by step — no
    shard is opened, parsed or CRC-checked, so watchers can poll it at
    high frequency. Torn (manifest-less) dirs are invisible, exactly as
    for the loaders. The mtime matters twice: it distinguishes a
    RE-commit of the same step number (fallback-restore step numbering
    can run backwards — see ``clear_stale_ack``) from nothing-new, and
    it is the recency key a rolling-reload watcher must rank by when
    the highest-NUMBERED step is unloadable (``serve/loader.py``)."""
    out = []
    for s in _step_dirs(root):
        mt = manifest_mtime(root, s)
        if mt is not None:
            out.append((s, mt))
    return out


def latest_manifest(root):
    """Cheap newest-complete probe: ``(step, manifest_mtime)`` of the
    newest (by step number) manifest-complete step, or ``None`` when no
    complete checkpoint exists — ``complete_manifests`` reduced the way
    the restore side ranks steps."""
    probes = complete_manifests(root)
    return probes[-1] if probes else None


# -- the commit -------------------------------------------------------------

def write_ok(root, step, rank, world, crc32, nbytes):
    """Phase-1 ack: ``shard-<r>-of-<w>.ok`` with the shard's CRC32 +
    size. Written AFTER the shard file is durable; atomic itself."""
    sdir = step_dir(root, step)
    payload = {"rank": int(rank), "world": int(world),
               "file": shard_name(rank, world),
               "crc32": int(crc32), "bytes": int(nbytes)}
    atomic_write(os.path.join(sdir, ok_name(rank, world)),
                 json.dumps(payload).encode())
    _kv_announce(f"ckpt/ack/{int(step)}/{int(rank)}", payload)


def clear_stale_ack(root, step, rank, world):
    """A dir left by a previous incarnation of this job may still hold
    this rank's OLD phase-1 ack (crash mid-save, then restore + re-save
    of the same step number). A new save into that dir must clear it
    BEFORE any fresh bytes land, or a peer's commit barrier could pair
    a fresh manifest with this rank's stale shard CRC. A
    manifest-COMPLETE dir can be re-entered too: restore falling back
    past a CRC-damaged newest step resumes training BELOW it, and the
    resumed counter re-reaches the damaged step number — the old
    MANIFEST must go first (the dir becomes torn again, invisible to
    restore), or every rank's commit barrier would be satisfied
    instantly by the stale acks it pairs with. Safe ordering: rank 0's
    NEW manifest needs every rank's fresh ack, and each rank's fresh
    ack postdates that rank's clear — so no clear can remove a new
    manifest."""
    sdir = step_dir(root, step)
    man = os.path.join(sdir, MANIFEST_NAME)
    ok = os.path.join(sdir, ok_name(rank, world))
    for stale in (man, ok):
        if os.path.isfile(stale):
            try:
                os.remove(stale)
                fsync_dir(sdir)
            except OSError:
                pass


def _await(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while True:
        got = predicate()
        if got is not None:
            return got
        if time.monotonic() >= deadline:
            raise TimeoutError(f"checkpoint commit: timed out after "
                               f"{timeout:.0f}s waiting for {what}")
        time.sleep(_POLL_S)


def _read_oks(root, step, world):
    sdir = step_dir(root, step)
    infos = {}
    for r in range(world):
        p = os.path.join(sdir, ok_name(r, world))
        if not os.path.isfile(p):
            return None
        try:
            with open(p) as f:
                infos[str(r)] = json.load(f)
        except (OSError, ValueError):
            return None  # racing the rename; retry
    return infos


def commit(root, step, rank, world, meta=None, zero_info=None, keep=None,
           timeout=120.0):
    """Run this rank's half of phase 2. Rank 0 barriers on every
    ``.ok`` marker, writes MANIFEST + ``latest`` and GCs; other ranks
    wait for the manifest to appear. Returns the manifest dict."""
    sdir = step_dir(root, step)
    if rank == 0:
        infos = _await(lambda: _read_oks(root, step, world), timeout,
                       f"{world} shard .ok markers in {sdir}")
        manifest = {
            "format": FORMAT_VERSION,
            "step": int(step),
            "world": int(world),
            "time": time.time(),
            "meta": meta or {},
            "shards": infos,
            "zero": zero_info or [],
        }
        atomic_write(os.path.join(sdir, MANIFEST_NAME),
                     json.dumps(manifest, indent=1).encode())
        atomic_write(os.path.join(root, LATEST_NAME),
                     (str(int(step)) + "\n").encode())
        _kv_announce(f"ckpt/manifest/{int(step)}", {"world": int(world)})
        if keep:
            retention_gc(root, keep)
        return manifest
    _await(lambda: (True if is_complete(root, step) else None), timeout,
           f"rank 0's {MANIFEST_NAME} in {sdir}")
    return read_manifest(root, step)


def retention_gc(root, keep):
    """Prune to the newest ``keep`` COMPLETE checkpoints. Manifest-less
    dirs older than the newest complete step are dead torn writes and
    go too; newer ones are in-flight saves and are left alone. "Older"
    is judged by the dir's mtime against the newest manifest's recorded
    commit time, not by step NUMBER alone: after a fallback restore past
    a damaged newest step, resumed training re-uses lower step numbers,
    and a peer may be mid-write into such a dir right now."""
    complete = list_complete_steps(root)
    if not complete:
        return []
    doomed = set(complete[:-keep]) if keep else set()
    newest = complete[-1]
    try:
        newest_time = float(read_manifest(root, newest).get("time", 0.0))
    except (OSError, ValueError):
        newest_time = 0.0
    for s in _step_dirs(root):
        if is_complete(root, s) or s >= newest:
            continue
        try:
            mtime = os.path.getmtime(step_dir(root, s))
        except OSError:
            continue  # vanished under us (a peer's GC)
        if mtime < newest_time:
            doomed.add(s)  # torn write, predates the newest commit — dead
    removed = []
    for s in sorted(doomed):
        shutil.rmtree(step_dir(root, s), ignore_errors=True)
        removed.append(s)
    if removed:
        logger.info("ckpt: retention GC removed step(s) %s from %s",
                    removed, root)
    return removed


def _kv_announce(key, payload):
    """Best-effort progress ack on the elastic rendezvous KV (the
    ``run/allocation`` plane) so the driver can observe checkpoint
    progress. Never load-bearing; never raises."""
    addr = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
    port = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT")
    if not addr or not port:
        return
    try:
        from horovod_tpu.run import secret as _secret
        from horovod_tpu.run.rendezvous import kv_put
        kv_put(addr, int(port), key, json.dumps(payload).encode(),
               auth_key=_secret.key_from_env())
    # hvd-lint: disable=HVD-EXCEPT -- best-effort KV announcement off the commit path
    except Exception:
        pass
