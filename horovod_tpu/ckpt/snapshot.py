"""Snapshot-offload: the training thread pays for the copy, not the write.

:class:`AsyncCheckpointer` is the CheckFreq/Gemini-style split of a save
into a fast SYNCHRONOUS device→host snapshot (``sharded.
snapshot_payload`` — decoupled from the live, possibly donated, device
buffers) and a background serialize → CRC → fsync → two-phase commit
(``sharded.write_shard`` + ``manifest.commit``). Training stalls for
``hvd_ckpt_blocking_seconds`` (the snapshot, plus any wait for the
bounded in-flight budget); the full ``hvd_ckpt_save_seconds`` overlaps
the next training steps.

The background thread NEVER touches the collective plane — the commit
barrier is the shared-filesystem ``.ok`` protocol (manifest.py) — so an
in-flight save can overlap training collectives without desync risk.

``flush()`` forces every queued save to durability and re-raises the
first background failure; the elastic plane calls it before every
re-rendezvous (``elastic/state.py``) so a membership change can never
orphan a half-written step, and ``close()`` is registered via
``atexit`` as a last resort for clean interpreter exits.
"""

import atexit
import logging
import os
import queue
import threading
import time

from horovod_tpu.ckpt import manifest as manifest_lib
from horovod_tpu.ckpt import sharded

logger = logging.getLogger("horovod_tpu")

snapshot_tree = sharded.snapshot_payload  # the synchronous half, exported

DEFAULT_KEEP = 5


def _env_rank_world():
    import horovod_tpu as hvd
    if hvd.is_initialized():
        return hvd.rank(), hvd.size()
    return (int(os.environ.get("HOROVOD_RANK", "0")),
            int(os.environ.get("HOROVOD_SIZE", "1")))


class AsyncCheckpointer:
    """Bounded-budget async sharded checkpoint writer for one rank.

    ``max_inflight`` caps queued-but-uncommitted saves: when the budget
    is exhausted, ``save()`` blocks until the oldest save commits (the
    wait is part of the blocking metric — a budget stall is a real
    training stall and must be visible, not hidden). ``keep`` is the
    retention GC depth (complete checkpoints, enforced by rank 0 at
    each commit)."""

    def __init__(self, directory, keep=DEFAULT_KEEP, max_inflight=1,
                 rank=None, world=None, barrier_timeout=None,
                 registry=None):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{max_inflight}")
        if barrier_timeout is None:
            barrier_timeout = float(
                os.environ.get("HOROVOD_CKPT_TIMEOUT", "120"))
        env_rank, env_world = _env_rank_world()
        self.directory = directory
        self.keep = keep
        self.rank = env_rank if rank is None else int(rank)
        self.world = env_world if world is None else int(world)
        self.barrier_timeout = barrier_timeout
        self.max_inflight = max_inflight
        from horovod_tpu.telemetry import instruments as _tele
        self._metrics = _tele.ckpt_instruments(registry)
        self._queue = queue.Queue()
        self._inflight = 0
        self._lock = threading.Condition()
        self._error = None
        self._thread = None
        self._closed = False
        self._abandoned = False
        self.last_manifest = None
        atexit.register(self.close)

    # -- the training-thread half ------------------------------------------
    def save(self, step, tree, meta=None, block=False):
        """Snapshot ``tree`` now; persist + commit in the background.
        Returns the seconds training was blocked. ``block=True`` turns
        this save synchronous (snapshot + wait for its commit)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._reraise()
        t0 = time.perf_counter()
        with self._lock:
            while self._inflight >= self.max_inflight and not self._error:
                self._lock.wait(0.005)
            self._reraise()
            self._inflight += 1
            self._metrics.inflight.set(self._inflight)
        from horovod_tpu.diag import recorder as _flightrec
        _flightrec.record_event("ckpt", ph="B", step=int(step),
                                rank=self.rank, world=self.world)
        try:
            # re-saving a step whose previous attempt was torn — or
            # whose damaged manifest a fallback restore skipped: clear
            # the old manifest and our stale phase-1 ack on the
            # TRAINING thread (the commit-cadence sync point), so no
            # peer barrier can pair a manifest with stale shards
            manifest_lib.clear_stale_ack(self.directory, step, self.rank,
                                         self.world)
            payload, zero_info = sharded.snapshot_payload(tree, self.rank,
                                                          self.world)
        except BaseException:
            # no job was queued: give the budget slot back, or every
            # later save()/flush() parks on it forever
            with self._lock:
                self._inflight -= 1
                self._metrics.inflight.set(self._inflight)
                self._lock.notify_all()
            _flightrec.record_event("ckpt", ph="E", step=int(step),
                                    rank=self.rank, ok=False,
                                    error="snapshot failed")
            raise
        blocking = time.perf_counter() - t0
        self._metrics.blocking_seconds.observe(blocking)
        self._charge_goodput(blocking)
        self._ensure_thread()
        self._queue.put((int(step), payload, zero_info, meta, t0))
        if block:
            self.flush()
        return blocking

    def flush(self, timeout=None):
        """Block until every queued save has committed; re-raise the
        first background failure. Call before a rendezvous, a restore,
        or process exit. The wait blocks the calling (training) thread,
        so it is charged to the goodput ledger's ``ckpt_stall`` phase
        alongside ``hvd_ckpt_blocking_seconds``."""
        t0 = time.perf_counter()
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        try:
            with self._lock:
                while self._inflight > 0 and self._error is None:
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"ckpt flush: {self._inflight} save(s) still "
                            f"in flight after {timeout:.0f}s")
                    self._lock.wait(0.01)
        finally:
            self._charge_goodput(time.perf_counter() - t0)
        self._reraise()
        return self.last_manifest

    @staticmethod
    def _charge_goodput(seconds):
        try:
            from horovod_tpu.telemetry import ledger as _ledger_lib
            _ledger_lib.get_ledger().charge("ckpt_stall", seconds)
        # hvd-lint: disable=HVD-EXCEPT -- accounting must never break a save path
        except Exception:  # accounting must never break a save path
            pass

    def close(self, timeout=None):
        """Flush (best effort) and stop the background thread."""
        if self._closed:
            return
        try:
            self.flush(timeout=timeout)
        # hvd-lint: disable=HVD-EXCEPT -- exit path must not throw; the failed save is logged
        except Exception as e:  # noqa: BLE001 — exit path must not throw
            logger.warning("ckpt: close() dropping failed save: %s", e)
        self._closed = True
        atexit.unregister(self.close)  # elastic churn replaces writers;
        if self._thread is not None:   # don't pin dead ones for life
            self._queue.put(None)
            self._thread.join(timeout=5.0)
            self._thread = None

    def abandon(self):
        """Stop WITHOUT waiting for in-flight saves: once membership
        broke, the commit barrier may never complete, and the elastic
        recovery path must not park on it. Queued-but-unwritten saves
        are DROPPED (a shard this writer lands minutes from now could
        pair with a manifest the post-reset world commits for the same
        step); only a save already mid-write drains, bounded by its own
        barrier timeout. The torn step dir stays invisible to restore
        (no manifest) and is GC'd later."""
        self._abandoned = True
        self._closed = True
        atexit.unregister(self.close)
        self._queue.put(None)

    # -- the background half -----------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name="hvd-ckpt-writer", daemon=True)
            self._thread.start()

    def _worker(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            step, payload, zero_info, meta, t0 = job
            if self._abandoned:
                from horovod_tpu.diag import recorder as _flightrec
                _flightrec.record_event("ckpt", ph="E", step=int(step),
                                        rank=self.rank, ok=False,
                                        error="abandoned before write")
                with self._lock:
                    self._inflight -= 1
                    self._metrics.inflight.set(self._inflight)
                    self._lock.notify_all()
                continue
            try:
                info = sharded.write_shard(self.directory, step, payload)
                man = manifest_lib.commit(
                    self.directory, step, self.rank, self.world, meta=meta,
                    zero_info=zero_info, keep=self.keep,
                    timeout=self.barrier_timeout)
                self.last_manifest = man
                dt = time.perf_counter() - t0
                self._metrics.bytes_written.inc(info["bytes"])
                self._metrics.save_seconds.observe(dt)
                from horovod_tpu.diag import recorder as _flightrec
                _flightrec.record_event("ckpt", ph="E", step=int(step),
                                        rank=self.rank, ok=True,
                                        bytes=info["bytes"],
                                        save_s=round(dt, 4))
                logger.debug("ckpt: committed step %d (%d bytes, %.1f ms "
                             "end-to-end)", step, info["bytes"], dt * 1e3)
            # hvd-lint: disable=HVD-EXCEPT -- background writer: failure is surfaced via flush()
            except Exception as e:  # noqa: BLE001 — surfaced via flush()
                logger.error("ckpt: background save of step %s failed: %s",
                             step, e)
                from horovod_tpu.diag import recorder as _flightrec
                _flightrec.record_event("ckpt", ph="E", step=int(step),
                                        rank=self.rank, ok=False,
                                        error=str(e)[:160])
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._metrics.inflight.set(self._inflight)
                    self._lock.notify_all()

    def _reraise(self):
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError(
                f"ckpt: a background checkpoint save failed: {e}") from e
