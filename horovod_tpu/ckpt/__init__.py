"""Async sharded checkpointing: snapshot-offload writes, two-phase
manifest commit, elastic N→M resharded restore.

The successor to the rank-0 synchronous ``checkpoint.py`` path (which
stays as a thin compatibility shim): once optimizer state is
ZeRO-1-sharded (``parallel/zero.py``) and membership is elastic
(``elastic/``), a checkpoint can no longer be "gather everything onto
rank 0 and stall all ranks for the serialize+fsync". The design follows
CheckFreq (FAST '21) and Gemini (SOSP '23):

* **snapshot-offload** (``snapshot.py``) — the training thread pays only
  for a fast device→host copy of params + THIS rank's ZeRO shard; the
  serialize / CRC / write / commit runs on a background thread under a
  bounded in-flight budget, so checkpoint cost is the copy, not the
  write (``hvd_ckpt_blocking_seconds`` vs ``hvd_ckpt_save_seconds``).
* **per-rank shards** (``sharded.py``) — every rank writes its own
  ``ckpt-<step>/shard-<r>-of-<w>.msgpack`` (CRC32-protected), so write
  bandwidth scales with the world and no shard is ever re-gathered.
  Restore re-slices the flat ``[world, shard]`` ZeRO bucket layout
  deterministically for ANY new world size M (the bucket partition is
  world-independent; only the per-world padding changes).
* **two-phase manifest commit** (``manifest.py``) — shards land + fsync
  (phase 1), a barrier confirms every rank's shard is durable, then rank
  0 writes ``MANIFEST.json`` + dir-fsync (phase 2). A checkpoint without
  a manifest never existed: the loader ignores manifest-less dirs (torn
  writes from a crash mid-save) and retention GC only counts complete
  checkpoints.

Integration: ``elastic.JaxState`` commits route through
:class:`AsyncCheckpointer` (flushed before every re-rendezvous),
``training.elastic_train_loop`` grows ``checkpoint_every``, telemetry
exports ``hvd_ckpt_{save_seconds,blocking_seconds,bytes_written,
inflight}``, and the flight recorder logs ckpt begin/commit events the
doctor surfaces as "interrupted save" after a crash. docs/CHECKPOINT.md
is the user-facing contract.
"""

from horovod_tpu.ckpt.manifest import (  # noqa: F401
    MANIFEST_NAME,
    is_complete,
    latest_complete_step,
    list_complete_steps,
    read_manifest,
    retention_gc,
)
from horovod_tpu.ckpt.sharded import (  # noqa: F401
    ShardValidationError,
    restore_sharded,
    save_sharded,
    shard_path,
    step_dir,
)
from horovod_tpu.ckpt.snapshot import (  # noqa: F401
    AsyncCheckpointer,
    snapshot_tree,
)

__all__ = [
    "AsyncCheckpointer", "snapshot_tree",
    "save_sharded", "restore_sharded", "ShardValidationError",
    "shard_path", "step_dir",
    "MANIFEST_NAME", "read_manifest", "is_complete",
    "list_complete_steps", "latest_complete_step", "retention_gc",
]
