"""Per-rank shard files + deterministic N→M reshard-on-load.

**What a shard holds.** The state tree is flattened with ``ZeroState``
(``parallel/zero.py``) as a leaf boundary:

* plain (replicated) leaves are round-robin-assigned by flat index —
  leaf ``i`` lives in shard ``i % world`` — so write bandwidth scales
  with the world and no byte is written twice;
* inside each ``ZeroState``, the ``[world, shard]`` bucket-row leaves
  are split by OWNERSHIP: each rank writes its contiguous block of the
  schedule's rows (``_owned_rows`` — the bytes it already holds under
  ZeRO-1, never re-gathered). One process per mesh slot means exactly
  row ``r`` for rank ``r`` — the original layout; a single process
  driving a multi-device mesh (the GSPMD hot path,
  ``parallel/gspmd.py``, where the rows live ``P('data')``-sharded as
  one ``NamedSharding`` array) owns and writes ALL of them. Shards
  store rows keyed by row index; the restore side also accepts the
  pre-GSPMD single-row layout, so old checkpoints keep loading. The
  small replicated inner leaves (step counts etc.) ride in rank 0's
  shard.

**Why N→M reshard is deterministic.** The flat bucket partition
(``ops/fusion.plan_buckets``) depends only on the parameter leaves and
the fusion threshold — NOT on the world size; only the per-bucket
padding (round up to a multiple of world) does. So the concatenation of
the N saved rows of a bucket is ``used`` real elements plus N-padding
zeros; restore truncates to ``used``, re-pads for M, and reshapes to
``[M, shard_M]``. The used prefix — the actual optimizer state — is
carried over BITWISE for any M; the manifest records the per-bucket
used sizes so a threshold/model mismatch fails loudly instead of
re-slicing garbage.

Every shard file carries a CRC32, recorded in its ``.ok`` marker and
aggregated into the manifest; restore verifies each shard against the
manifest before deserializing.
"""

import logging
import re
import zlib

import numpy as np

from horovod_tpu.ckpt import manifest as manifest_lib

logger = logging.getLogger("horovod_tpu")

_BUCKET_KEY_RE = re.compile(r"^b(\d+)$")


class ShardValidationError(ValueError):
    """A shard file of an otherwise manifest-complete step is unusable:
    it fails its manifest CRC32 (disk rot, or a manifest paired with a
    stale phase-1 ack by the crash-adjacent re-save race). Distinct from
    plain ``ValueError`` so ``restore_sharded`` can fall back to an
    older complete step for per-step damage while a bucket-layout or
    state-tree mismatch (wrong model/threshold — hits every step the
    same) stays loud."""

# re-exported layout helpers (one naming authority: manifest.py)
step_dir = manifest_lib.step_dir


def shard_path(root, step, rank, world):
    import os
    return os.path.join(manifest_lib.step_dir(root, step),
                        manifest_lib.shard_name(rank, world))


def _is_zero_state(x):
    from horovod_tpu.parallel import zero as zero_lib
    return isinstance(x, zero_lib.ZeroState)


def _host(x):
    import jax
    # np.array (copy=True) rather than asarray: device_get is identity
    # on host numpy, and even device arrays can come back as zero-copy
    # views on the CPU backend — the copy is what actually decouples
    # the payload from live, in-place-mutable / donated state
    return np.array(jax.device_get(x))


def _row(leaf, r):
    """Row ``r`` of a ``[world, shard]`` bucket-row leaf, reading only
    the local shard when the array is genuinely device-sharded."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is not None:
        for s in shards:
            idx = s.index
            sl = idx[0] if idx else slice(None)
            start = sl.start or 0
            stop = sl.stop if sl.stop is not None else leaf.shape[0]
            if start <= r < stop:
                # np.array: the shard view can be zero-copy on the CPU
                # backend, and the row must not alias live state
                return np.array(np.asarray(s.data)[r - start])
    return np.ascontiguousarray(_host(leaf)[r])


def _bucket_index(path, leaf, sched):
    """Bucket index when ``leaf`` is a ``[world, shard]`` bucket-row
    living under a ``b<i>`` dict key of ``path``, else None — the
    classification ``zero.state_specs`` shards by, sharpened with the
    bucket-key check so a replicated leaf that happens to have a
    world-sized dim 0 cannot be mistaken for a row. The ONE authority
    for both save (ownership-row split) and restore (re-slice): the two
    sides must classify identically or a leaf saved as a row would be
    looked up as replicated."""
    for part in reversed(path):
        name = getattr(part, "key", None)
        m = _BUCKET_KEY_RE.match(name) if isinstance(name, str) else None
        if m:
            bi = int(m.group(1))
            shape = np.shape(leaf)
            if (bi < len(sched.buckets) and len(shape) == 2
                    and shape[0] == sched.world
                    and shape[1] == sched.shard_sizes[bi]):
                return bi
            return None
    return None


def _owned_rows(sched_world, rank, world):
    """The contiguous block of a schedule's ``[world, shard]`` rows that
    process ``rank`` of ``world`` saves: the block partition of
    ``sched_world`` rows across ``world`` processes. One process per
    mesh slot (``world == sched_world``) degenerates to ``[rank]`` —
    the original one-row-per-rank layout; one process driving the whole
    mesh (``world == 1``) owns every row. Matches physical placement
    for process-contiguous meshes, so each row read stays local."""
    lo = rank * sched_world // world
    hi = (rank + 1) * sched_world // world
    return range(lo, hi)


def _inner_entries(zstate):
    """``(key, bucket_index_or_None, leaf)`` per inner leaf of a
    ZeroState: the key is the stable tree-path string."""
    import jax

    sched = zstate.plan.schedule
    flat, _ = jax.tree_util.tree_flatten_with_path(zstate.inner)
    return [(jax.tree_util.keystr(path), _bucket_index(path, leaf, sched),
             leaf) for path, leaf in flat]


def _zero_infos(leaves):
    """Manifest-side description of every ZeroState in the outer leaf
    list: the reshard validator (used/padded sizes are the invariants a
    different fusion threshold or model would break)."""
    infos = []
    for i, leaf in enumerate(leaves):
        if not _is_zero_state(leaf):
            continue
        sched = leaf.plan.schedule
        infos.append({
            "leaf": i,
            "world": int(sched.world),
            "used_sizes": [int(sum(b.sizes)) for b in sched.buckets],
            "padded_sizes": [int(p) for p in sched.padded_sizes],
        })
    return infos


def snapshot_payload(tree, rank, world):
    """The SYNCHRONOUS half of a save: device→host copy of this rank's
    share of ``tree``. Returns ``(payload, zero_info)`` — the payload is
    plain nested dicts of host numpy arrays (msgpack-ready, fully
    decoupled from the live/donated device buffers), so everything
    after this call can run on a background thread."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten(tree, is_leaf=_is_zero_state)
    repl = {}
    zeros = {}
    z = 0
    for i, leaf in enumerate(leaves):
        if _is_zero_state(leaf):
            sched_world = int(leaf.plan.schedule.world)
            own = _owned_rows(sched_world, rank, world)
            rows, zrepl = {}, {}
            for key, bucket, inner_leaf in _inner_entries(leaf):
                if bucket is not None:
                    # rows keyed by ROW index (not process rank): the
                    # schedule's world and the process world need not
                    # match — a single GSPMD process owns every row
                    rows[key] = {str(r): _row(inner_leaf, r)
                                 for r in own}
                elif rank == 0:
                    zrepl[key] = _host(inner_leaf)
            zeros[str(z)] = {"rows": rows, "repl": zrepl}
            z += 1
        elif i % world == rank:
            repl[str(i)] = _host(leaf)
    payload = {"format": manifest_lib.FORMAT_VERSION, "rank": int(rank),
               "world": int(world), "repl": repl, "zero": zeros}
    return payload, _zero_infos(leaves)


def write_shard(root, step, payload):
    """Serialize + CRC + durably write one rank's shard, then its
    ``.ok`` marker (the phase-1 ack). Returns ``{file, crc32, bytes}``."""
    import os

    from flax import serialization

    rank, world = payload["rank"], payload["world"]
    sdir = manifest_lib.step_dir(root, step)
    os.makedirs(sdir, exist_ok=True)
    data = serialization.msgpack_serialize(payload)
    crc = zlib.crc32(data) & 0xFFFFFFFF
    manifest_lib.atomic_write(
        os.path.join(sdir, manifest_lib.shard_name(rank, world)), data)
    manifest_lib.write_ok(root, step, rank, world, crc, len(data))
    return {"file": manifest_lib.shard_name(rank, world),
            "crc32": crc, "bytes": len(data)}


def save_sharded(root, step, tree, rank=0, world=1, meta=None, keep=None,
                 timeout=120.0):
    """Synchronous single-call save: snapshot + write + commit (this
    rank's part of the two-phase protocol). The async path
    (``snapshot.AsyncCheckpointer``) runs the same three calls with the
    last two on a background thread. Returns the manifest dict."""
    manifest_lib.clear_stale_ack(root, step, rank, world)
    payload, zero_info = snapshot_payload(tree, rank, world)
    write_shard(root, step, payload)
    return manifest_lib.commit(root, step, rank, world, meta=meta,
                               zero_info=zero_info, keep=keep,
                               timeout=timeout)


# -- restore ----------------------------------------------------------------

def _read_shard(root, step, rank, world, expect):
    import os

    from flax import serialization

    path = os.path.join(manifest_lib.step_dir(root, step),
                        manifest_lib.shard_name(rank, world))
    with open(path, "rb") as f:
        data = f.read()
    crc = zlib.crc32(data) & 0xFFFFFFFF
    if expect is not None and crc != int(expect.get("crc32", crc)):
        raise ShardValidationError(
            f"checkpoint shard {path} failed its CRC32 check "
            f"(manifest {expect['crc32']:#010x}, file {crc:#010x}) — "
            "the shard is corrupt or torn; restore a different step")
    payload = serialization.msgpack_restore(data)
    fmt = int(payload.get("format", 1))
    if fmt > manifest_lib.FORMAT_VERSION:
        raise ValueError(
            f"checkpoint shard {path} was written with format {fmt}, "
            f"this reader understands <= {manifest_lib.FORMAT_VERSION}: "
            "the checkpoint comes from a NEWER horovod_tpu — upgrade "
            "this process (or restore an older checkpoint) instead of "
            "letting a layout mismatch surface as a shape error")
    return payload


def _assemble_zero(target_z, z, payloads, info):
    """Re-slice one ZeroState's rows for the target world size."""
    import jax

    sched = target_z.plan.schedule
    used = [int(sum(b.sizes)) for b in sched.buckets]
    if info is None or info.get("used_sizes") != used:
        raise ValueError(
            "checkpoint ZeRO bucket layout does not match the restore "
            f"target (saved used_sizes={info and info.get('used_sizes')}, "
            f"target={used}): the bucket partition is a function of the "
            "parameter tree and the fusion threshold — restore with the "
            "same model and HOROVOD_FUSION_THRESHOLD it was saved under")
    src_world = int(info["world"])
    zkey = str(z)

    def one(path, leaf):
        key = jax.tree_util.keystr(path)
        bucket = _bucket_index(path, leaf, sched)
        if bucket is None:
            try:
                saved = payloads[0]["zero"][zkey]["repl"][key]
            except KeyError:
                raise ValueError(
                    f"checkpoint is missing replicated optimizer leaf "
                    f"{key!r} of ZeroState #{z}") from None
            if np.shape(saved) != np.shape(leaf):
                if np.size(saved) == np.size(leaf):
                    saved = np.asarray(saved).reshape(np.shape(leaf))
                else:
                    raise ValueError(
                        f"replicated optimizer leaf {key!r} of ZeroState "
                        f"#{z} has shape {np.shape(saved)} in the "
                        f"checkpoint, the restore target expects "
                        f"{np.shape(leaf)}")
            return saved
        rows_by_idx = {}
        for p in payloads:
            entry = p.get("zero", {}).get(zkey, {}).get("rows", {})
            saved_rows = entry.get(key)
            if saved_rows is None:
                continue
            if isinstance(saved_rows, dict):
                # current layout: rows keyed by row index (each payload
                # holds the block its process owned at save time)
                for rk, arr in saved_rows.items():
                    rows_by_idx[int(rk)] = arr
            else:
                # pre-GSPMD layout: one unkeyed row per shard — its row
                # index IS the saving process's rank
                rows_by_idx[int(p["rank"])] = saved_rows
        missing = [r for r in range(src_world) if r not in rows_by_idx]
        if missing:
            raise ValueError(
                f"checkpoint is missing bucket row(s) {missing} of "
                f"{key!r} in ZeroState #{z} (saved schedule world "
                f"{src_world})")
        flat = np.concatenate([np.asarray(rows_by_idx[r]).reshape(-1)
                               for r in range(src_world)])
        n_used = used[bucket]
        if flat.shape[0] < n_used:
            raise ValueError(
                f"checkpoint rows for bucket {bucket} of ZeroState #{z} "
                f"hold {flat.shape[0]} elements < used {n_used}")
        out = np.zeros((sched.padded_sizes[bucket],), dtype=flat.dtype)
        out[:n_used] = flat[:n_used]
        return out.reshape(sched.world, sched.shard_sizes[bucket])

    from horovod_tpu.parallel import zero as zero_lib
    new_inner = jax.tree_util.tree_map_with_path(one, target_z.inner)
    return zero_lib.ZeroState(new_inner, target_z.plan)


def restore_sharded(root, target, step=None):
    """Load a sharded checkpoint into the structure of ``target``
    (rank-local read — broadcast discipline is the caller's, exactly as
    with ``checkpoint.restore_checkpoint``). ``step=None`` picks the
    newest manifest-COMPLETE step (torn dirs are invisible) and FALLS
    BACK to older complete steps when the newest one fails validation —
    a shard missing or failing its manifest CRC (disk rot, or the rare
    crash-adjacent race where a manifest paired a re-saved shard with a
    stale phase-1 ack): torn-write philosophy, applied to reads. An
    EXPLICIT ``step`` still fails loudly. The target may be built for a
    different world size than the checkpoint: ZeRO bucket rows are
    re-sliced (see module docstring) and replicated leaves are
    reassembled from their round-robin homes. Returns
    ``(step, tree, meta)``."""
    if step is not None:
        if not manifest_lib.is_complete(root, step):
            raise FileNotFoundError(
                f"step {step} under {root} has no "
                f"{manifest_lib.MANIFEST_NAME} (incomplete/torn "
                "checkpoint)")
        return _restore_step(root, target, step)
    steps = manifest_lib.list_complete_steps(root)
    if not steps:
        raise FileNotFoundError(
            f"no manifest-complete checkpoint under {root}")
    last_err = None
    for s in reversed(steps):
        try:
            return _restore_step(root, target, s)
        except (OSError, ShardValidationError) as e:
            # ONLY shard-validation failures fall back; a bucket-layout
            # or state-tree mismatch (wrong model/threshold) hits every
            # step the same and must stay loud
            logger.warning(
                "ckpt: step %d under %s is unrestorable (%s) — falling "
                "back to the previous complete step", s, root, e)
            last_err = e
    raise ValueError(
        f"no restorable checkpoint under {root}: all {len(steps)} "
        f"manifest-complete step(s) failed validation") from last_err


def _restore_step(root, target, step):
    import jax

    man = manifest_lib.read_manifest(root, step)
    src_world = int(man["world"])
    shards = man.get("shards") or {}
    payloads = [_read_shard(root, step, r, src_world, shards.get(str(r)))
                for r in range(src_world)]

    zero_by_index = {int(i["leaf"]): i for i in (man.get("zero") or [])}
    leaves, treedef = jax.tree_util.tree_flatten(
        target, is_leaf=_is_zero_state)
    # the z-th ZeroState in leaf order pairs with payload key str(z);
    # manifest zero infos are keyed by SAVED outer-leaf index, which must
    # line up with the target's (same state tree shape)
    out, z = [], 0
    for i, leaf in enumerate(leaves):
        if _is_zero_state(leaf):
            out.append(_assemble_zero(leaf, z, payloads,
                                      zero_by_index.get(i)))
            z += 1
            continue
        try:
            saved = payloads[i % src_world]["repl"][str(i)]
        except KeyError:
            raise ValueError(
                f"checkpoint step {step} has no leaf {i} — it was saved "
                f"from a different state tree ({len(leaves)} target "
                "leaves)") from None
        if np.shape(saved) != np.shape(leaf):
            # msgpack round-trips 0-d arrays as shape (1,); any
            # same-size difference is a benign layout artifact
            if np.size(saved) == np.size(leaf):
                saved = np.asarray(saved).reshape(np.shape(leaf))
            else:
                raise ValueError(
                    f"checkpoint leaf {i} has shape {np.shape(saved)}, "
                    f"the restore target expects {np.shape(leaf)}")
        out.append(saved)
    return step, jax.tree_util.tree_unflatten(treedef, out), \
        man.get("meta") or {}
