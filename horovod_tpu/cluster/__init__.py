"""Process-mesh subsystem: real multi-process ``jax.distributed`` jobs
forming ONE logical mesh with DCN-aware tiers (docs/SCALING.md).

``ensure_distributed`` is the single sanctioned
``jax.distributed.initialize`` call site in the tree (HVD-DISTINIT
lint pass); everything else here derives the global/(per-process
addressable) split the rest of the framework was built against.
"""

from horovod_tpu.cluster.procmesh import (  # noqa: F401
    assert_process_contiguous,
    build_process_mesh,
    coordinator_spec,
    ensure_distributed,
    global_batch,
    is_multiprocess,
    local_row_block,
    mesh_tiers,
    place,
    process_grid,
    shard_from_global,
)

__all__ = [
    "assert_process_contiguous",
    "build_process_mesh",
    "coordinator_spec",
    "ensure_distributed",
    "global_batch",
    "is_multiprocess",
    "local_row_block",
    "mesh_tiers",
    "place",
    "process_grid",
    "shard_from_global",
]
