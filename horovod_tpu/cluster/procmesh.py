"""The process mesh: one logical device mesh spanning N real
``jax.distributed`` processes.

Everything below ``horovod_tpu.init()`` — GspmdPlan, the ZeRO row
keying, the ckpt v2 row-dict layout, the serve loader's N-host→
M-device reshard — was built against a *global* device set with
*per-process addressable shards*. This module is where that global
view actually comes from in a multi-process job:

* :func:`ensure_distributed` is the ONE sanctioned call site of
  ``jax.distributed.initialize`` in the tree (ratcheted by the
  HVD-DISTINIT lint pass): every process launched by
  ``hvdrun --spmd-procs N`` joins the coordinator named by
  ``HOROVOD_COORDINATOR_ADDR``, after which ``jax.devices()`` spans
  the whole job and ``jax.local_devices()`` is this process's slice.
* :func:`build_process_mesh` arranges that global device set as a 2-D
  ``(dcn, data)`` grid, **ICI-first**: the minor (fastest-varying)
  axis is the intra-process/intra-host device tier whose collectives
  ride ICI, the outer axis is the process tier whose collectives ride
  the data-center network. Row ``p`` of the grid is exactly process
  ``p``'s local devices — so a batch sharded over ``(dcn, data)`` puts
  a *contiguous* block of global rows on each process, which is the
  same contract ``ckpt.sharded._owned_rows`` and the data loader's
  ``rank/world`` sharding already assume.

On CPU the multi-process data plane needs two things set **before the
first backend touch**, both handled here: the gloo cross-process
collectives implementation (without it XLA:CPU refuses multiprocess
computations outright) and ``--xla_force_host_platform_device_count``
so each process contributes ``HOROVOD_SPMD_LOCAL_DEVICES`` virtual
chips — the test/bench stand-in for a real TPU host's 4–8 chips.
"""

import logging
import os
import threading

import numpy as np

from horovod_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS

logger = logging.getLogger("horovod_tpu")

_lock = threading.Lock()
_dist = {"joined": False, "spec": None}


def _env_int(env, name, default=0):
    v = env.get(name)
    if v in (None, ""):
        return default
    return int(v)


def coordinator_spec(cfg=None, env=None):
    """The ``(address, num_processes, process_id)`` this process should
    join, or ``None`` for single-process runs. Read from the hvdrun env
    contract: ``HOROVOD_COORDINATOR_ADDR`` names the coordinator,
    ``HOROVOD_SPMD_PROCS`` (default ``HOROVOD_SIZE``) the world, and
    the process id is the launcher rank."""
    env = os.environ if env is None else env
    coord = env.get("HOROVOD_COORDINATOR_ADDR")
    if not coord:
        return None
    if cfg is not None:
        rank, size = cfg.rank, cfg.size
        procs = getattr(cfg, "spmd_procs", 0) or size
    else:
        rank = _env_int(env, "HOROVOD_RANK", 0)
        size = _env_int(env, "HOROVOD_SIZE", 1)
        procs = _env_int(env, "HOROVOD_SPMD_PROCS", 0) or size
    if procs <= 1:
        return None
    return (coord, procs, rank)


def _backend_live():
    """True once any jax backend is initialized in this process — the
    point after which distributed init / device-count forcing is too
    late."""
    try:
        from jax._src import xla_bridge
        return xla_bridge.backends_are_initialized()
    # hvd-lint: disable=HVD-EXCEPT -- internal-API probe across jax versions; False is safe
    except Exception:  # pragma: no cover - internal API drift
        return False


def _foreign_distributed():
    """True when something else already ran jax.distributed.initialize
    in this process (a notebook, a framework wrapper)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    # hvd-lint: disable=HVD-EXCEPT -- internal-API probe across jax versions; False is safe
    except Exception:  # pragma: no cover - internal API drift
        return False


def _force_local_device_count(n, env):
    """Merge ``--xla_force_host_platform_device_count=n`` into
    XLA_FLAGS (CPU-only flag; the TPU backend ignores it). User-set
    values win, matching config.apply_xla_flags."""
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()


def ensure_distributed(cfg=None, env=None):
    """Join the multi-process JAX runtime (idempotent).

    The ONE sanctioned ``jax.distributed.initialize`` call site
    (HVD-DISTINIT). Returns True when this process is part of a
    multi-process job (whether joined now or on a prior call), False
    for single-process runs.

    Must run before the first backend touch: ``basics.init()`` calls
    it right after ``apply_xla_flags`` for exactly that reason.
    """
    env = os.environ if env is None else env
    spec = coordinator_spec(cfg, env)
    with _lock:
        if _dist["joined"]:
            if spec is not None and spec != _dist["spec"]:
                raise RuntimeError(
                    f"jax.distributed already joined as {_dist['spec']} "
                    f"but the environment now names {spec}; one process "
                    "cannot re-join a different coordinator")
            return _dist["spec"] is not None
        if spec is None:
            if _foreign_distributed():
                # someone initialized jax.distributed before us (library
                # embedding); adopt their world rather than fight it
                _dist["joined"] = True
                _dist["spec"] = ("<external>", None, None)
                return True
            return False
        if _foreign_distributed():
            _dist["joined"] = True
            _dist["spec"] = ("<external>", None, None)
            return True
        if _backend_live():
            raise RuntimeError(
                "HOROVOD_COORDINATOR_ADDR is set but a jax backend was "
                "already initialized in this process — "
                "jax.distributed.initialize must run before any jax "
                "computation. Call horovod_tpu.init() (or "
                "cluster.ensure_distributed()) before touching jax.")
        coord, procs, pid = spec

        import jax

        local = 0
        if cfg is not None:
            local = getattr(cfg, "spmd_local_devices", 0)
        local = local or _env_int(env, "HOROVOD_SPMD_LOCAL_DEVICES", 0)
        platforms = (env.get("JAX_PLATFORMS")
                     or jax.config.jax_platforms or "")
        cpu_only = platforms.replace("cpu", "").strip(", ") == "" and \
            "cpu" in platforms
        if local > 1:
            if cpu_only:
                _force_local_device_count(local, env)
            else:  # pragma: no cover - TPU path
                logger.warning(
                    "HOROVOD_SPMD_LOCAL_DEVICES=%d ignored: only the CPU "
                    "backend supports forced device counts", local)
        if cpu_only:
            # XLA:CPU refuses cross-process computations without a real
            # collectives implementation; gloo is the in-tree one.
            impl = None
            if cfg is not None:
                impl = getattr(cfg, "cpu_collectives", None)
            impl = impl or env.get("HOROVOD_CPU_COLLECTIVES") or "gloo"
            jax.config.update("jax_cpu_collectives_implementation", impl)
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=procs,
            process_id=pid,
        )
        _dist["joined"] = True
        _dist["spec"] = spec
        logger.info("joined jax.distributed: coordinator=%s process %d/%d",
                    coord, pid, procs)
        return True


def is_multiprocess():
    """True when this process joined (or adopted) a multi-process
    jax.distributed runtime via :func:`ensure_distributed`."""
    with _lock:
        return _dist["joined"] and _dist["spec"] is not None


def process_grid(devices=None):
    """The global device set as a ``(process, local_device)`` ndarray —
    row ``p`` is process ``p``'s local devices in id order (ICI-first
    minor axis). Raises when processes contribute unequal device
    counts (a ragged grid cannot form a rectangular mesh)."""
    import jax
    if devices is None:
        devices = jax.devices()
    by_proc = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    counts = {p: len(ds) for p, ds in by_proc.items()}
    if len(set(counts.values())) != 1:
        raise ValueError(
            f"ragged process mesh: per-process device counts {counts}")
    rows = [sorted(by_proc[p], key=lambda d: d.id)
            for p in sorted(by_proc)]
    return np.asarray(rows, dtype=object)


def build_process_mesh(devices=None, axis_names=(DCN_AXIS, DATA_AXIS)):
    """ONE logical mesh spanning every process of the job.

    Axis order is ICI-first: ``axis_names[-1]`` (``data``) is the
    intra-process tier — the minor, fastest-varying grid axis, so
    collectives over it stay inside a host's ICI domain — and
    ``axis_names[0]`` (``dcn``) is the process tier riding DCN.
    Single-process device sets degrade to a 1-D ``(data,)`` mesh,
    matching ``parallel.mesh.build_mesh``.
    """
    from jax.sharding import Mesh
    grid = process_grid(devices)
    if grid.shape[0] == 1:
        return Mesh(grid.reshape(-1), (axis_names[-1],))
    return Mesh(grid, axis_names)


def mesh_tiers(mesh):
    """The interconnect tier of every mesh axis, outer→inner:
    ``[{"axis", "size", "tier", "scope"}]``. The ``dcn`` axis is the
    inter-process/inter-host tier; everything else is an ICI tier
    (intra-host on real TPU, virtual devices on the CPU stand-in)."""
    procs = len({d.process_index for d in mesh.devices.flat})
    out = []
    for axis, size in zip(mesh.axis_names, mesh.devices.shape):
        if axis == DCN_AXIS:
            out.append({"axis": axis, "size": int(size), "tier": "dcn",
                        "scope": f"inter-process ({procs} processes)"})
        else:
            out.append({"axis": axis, "size": int(size), "tier": "ici",
                        "scope": "intra-process"})
    return out


def assert_process_contiguous(mesh):
    """Checkpoint/loader row keying assumes each process owns a
    contiguous block of global batch rows — true iff every outer-axis
    row of the mesh grid lives on exactly one process and rows appear
    in process order. Raise otherwise (a scrambled grid would silently
    save rows under wrong global indices)."""
    grid = mesh.devices
    if grid.ndim == 1:
        grid = grid.reshape(1, -1)
    else:
        grid = grid.reshape(grid.shape[0], -1)
    procs = len({d.process_index for d in mesh.devices.flat})
    if procs == 1:
        return
    last = -1
    for r in range(grid.shape[0]):
        owners = {d.process_index for d in grid[r]}
        if len(owners) != 1:
            raise ValueError(
                f"process mesh row {r} spans processes {sorted(owners)}; "
                "ckpt row ownership requires one process per dcn row")
        owner = owners.pop()
        if owner < last:
            raise ValueError(
                "process mesh rows out of process order; ckpt global row "
                "indices would not be contiguous per process")
        last = owner


def local_row_block(global_rows, mesh=None):
    """``(start, stop)`` of the contiguous global batch rows this
    process feeds, for a batch sharded over all data axes of a
    process-contiguous mesh. Mirrors ``ckpt.sharded._owned_rows``:
    block ``p`` of ``process_count`` equal blocks."""
    import jax
    procs = jax.process_count()
    pid = jax.process_index()
    if mesh is not None:
        assert_process_contiguous(mesh)
    if global_rows % procs != 0:
        raise ValueError(
            f"global batch {global_rows} not divisible by process count "
            f"{procs}")
    per = global_rows // procs
    return pid * per, (pid + 1) * per


def global_batch(x, sharding, global_rows=None):
    """Assemble a globally-sharded batch from this process's local
    rows. Single-process: a plain ``device_put``. Multi-process: the
    caller passes ONLY its own row block (``local_row_block``'s slice)
    and the runtime stitches the global array from per-process
    addressable shards — no process ever materializes the whole batch.
    """
    import jax
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    shape = (global_rows or x.shape[0] * jax.process_count(),) + \
        tuple(x.shape[1:])
    return jax.make_array_from_process_local_data(sharding, x, shape)


def shard_from_global(x, sharding):
    """The global array for ``sharding`` built from a full host copy of
    the global value — with NO collectives. Under SPMD every process
    computes the same host-side values (batches, init params, zero
    buffers), so each process can slice out exactly the shards its own
    devices address and stitch them together locally.

    This matters because ``jax.device_put`` onto a sharding that spans
    processes instead *broadcasts the entire value through the
    collective fabric* to assert cross-process equality — per call. On
    the gloo CPU transport those broadcasts interleave with the
    compiled step's own async collectives and can mis-pair (message
    size mismatch aborts), and on any transport they put the full batch
    on the wire every step. Slicing locally costs a memcpy and cannot
    race. The equality *check* device_put performed is forfeited: the
    caller vouches that ``x`` is process-identical, which is the same
    SPMD contract the rest of the program already rests on.
    """
    import jax
    x = np.asarray(x)
    indices = sharding.addressable_devices_indices_map(x.shape)
    shards = [jax.device_put(x[idx], d) for d, idx in indices.items()]
    return jax.make_array_from_single_device_arrays(
        x.shape, sharding, shards)


def place(x, sharding):
    """Multi-process-safe ``device_put``: the one placement primitive
    the framework's hot paths use (``training._placer``,
    ``gspmd.place_state``).

    * fully-addressable target (single process): plain device_put.
    * cross-process target, ``x`` host-side or process-local (a numpy
      batch, init params committed to one local device): hop via host
      and ``shard_from_global`` — zero collectives. device_put would
      instead broadcast the whole value through the fabric per leaf to
      assert cross-process equality, which both costs the wire and can
      mis-pair with the compiled step's own async collectives on gloo.
    * ``x`` already a global array: device_put, which is a no-op when
      the shardings match (every step after the first) and a true
      fabric reshard when they don't.
    """
    import jax
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array):
        if not x.is_fully_addressable or x.sharding == sharding:
            return jax.device_put(x, sharding)
        x = np.asarray(x)
    return shard_from_global(x, sharding)


def _reset_for_tests():
    """Forget the joined-coordinator record (unit tests monkeypatch the
    underlying initialize; a real joined runtime cannot be re-joined)."""
    with _lock:
        _dist["joined"] = False
        _dist["spec"] = None
