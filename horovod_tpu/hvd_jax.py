"""JAX user-facing API: DistributedOptimizer, broadcast, Join.

Rebuilds the L5 user contract of the reference for JAX/optax:

* ``DistributedOptimizer`` — wraps an ``optax.GradientTransformation`` so
  gradients are fusion-bucketed and allreduced across the mesh before the
  inner update (reference: ``horovod/torch/__init__.py:57-212``
  ``_DistributedOptimizer``; ``horovod/tensorflow/__init__.py:266-311``).
* ``distributed_grad`` / ``distributed_value_and_grad`` — the
  ``DistributedGradientTape`` analogue
  (``horovod/tensorflow/__init__.py:475-531``).
* ``broadcast_variables`` / ``broadcast_parameters`` /
  ``broadcast_optimizer_state`` — rank-0 state sync at startup
  (``horovod/torch/__init__.py:440-560``,
  ``hvd.broadcast_global_variables``).
* ``join`` — uneven-data fault tolerance
  (``EnqueueJoin``, ``operations.cc:909``; zero-fill semantics
  ``controller.cc:209-220``).

All of these are meant to be used inside a ``jax.shard_map``-style SPMD step
(each shard computes local gradients on its local batch — the Horovod
programming model) OR at top level eagerly across processes.
"""

import jax
import jax.numpy as jnp

from horovod_tpu.ops import collective
from horovod_tpu.ops.collective import Adasum, Average, Sum
from horovod_tpu.ops.fusion import fused_allreduce


def DistributedGradientTransform(op=Average, axes=None, compression=None,
                                 threshold_bytes=None, hierarchical=None):
    """An ``optax.GradientTransformation`` that allreduces gradients across
    the mesh (fused, optionally compressed/hierarchical/Adasum). Chain it
    before any optimizer: ``optax.chain(DistributedGradientTransform(), tx)``.
    """
    import optax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        reduced = fused_allreduce(
            updates, op=op, axes=axes, compression=compression,
            threshold_bytes=threshold_bytes, hierarchical=hierarchical)
        return reduced, state

    return optax.GradientTransformation(init_fn, update_fn)


class HorovodOptimizer:
    """The object ``DistributedOptimizer`` returns: duck-typed as an
    ``optax.GradientTransformation`` (``init``/``update``) and carrying the
    reduction configuration as attributes so the training pipeline
    (``training.make_train_step(accum_steps=..., overlap_grads=True)``) can
    introspect it — which collective op, which axes, whether the optimizer
    state is ZeRO-sharded, and the unwrapped inner transform for updates
    on gradients the pipeline has already reduced."""

    def __init__(self, inner, op, axes, compression, threshold_bytes,
                 hierarchical, sharded_update, backward_passes_per_step):
        self.inner = inner
        self.op = op
        self.axes = axes
        self.threshold_bytes = threshold_bytes
        self.hierarchical = hierarchical
        self.sharded_update = sharded_update
        self.backward_passes_per_step = backward_passes_per_step

        from horovod_tpu.ops import compression as compression_lib

        # ``None`` defers to config.wire_dtype AT USE TIME (the config
        # does not exist before hvd.init(), and the autotuner's wire
        # axis may install its winner after this optimizer is built —
        # same late binding as _hierarchical_resolved); an explicit
        # "none"/Compression.none pins uncompressed regardless of config.
        self._wire_forced_off = False
        if isinstance(compression, str):
            name = compression
            compression = compression_lib.by_name(compression)
            if compression is None and name is not None:
                self._wire_forced_off = True
        elif isinstance(compression, compression_lib.NoneCompressor):
            self._wire_forced_off = True
            compression = None
        if compression is not None:
            self._check_wire(compression)
        self._compression = compression

        if sharded_update:
            if op not in (Sum, Average):
                raise ValueError(
                    f"sharded_update supports Sum or Average, got {op!r}")
            if backward_passes_per_step > 1:
                raise ValueError(
                    "sharded_update accumulates via make_train_step("
                    "accum_steps=...) — backward_passes_per_step>1 would "
                    "stack a second accumulator on top")
        self._transform = None
        self._transform_wire = self._WIRE_UNSET
        self._config_wire_warned = False

    def _check_wire(self, compression):
        if (getattr(compression, "chunked", False)
                and self.op not in (Sum, Average)):
            raise ValueError(
                f"chunked wire format {compression.name!r} only composes "
                f"with Sum/Average reductions (got {self.op!r}): e.g. "
                "int8 wire + Adasum is unsupported — per-chunk scales "
                "cannot ride Adasum's dot-product composition. Use "
                "bf16/fp16 (cast) compression or drop the quantizer.")

    @property
    def compression(self):
        """The resolved wire format: the explicit argument if one was
        given, else ``config.wire_dtype`` read at access time (so an
        optimizer built before ``hvd.init()`` / before the autotuner
        installed its wire-axis winner still picks the config value up),
        else ``None``. A config-derived DEFAULT that is incompatible
        with this optimizer's op (e.g. int8 installed globally while
        this one runs Adasum) is ignored with a warning — only an
        EXPLICIT argument hard-errors on an unsupported combo."""
        if self._compression is not None or self._wire_forced_off:
            return self._compression
        from horovod_tpu import basics
        from horovod_tpu.ops import compression as compression_lib
        cfg = basics._state.config
        if cfg is None or not cfg.wire_dtype:
            return None
        wire = compression_lib.by_name(cfg.wire_dtype)
        if isinstance(wire, compression_lib.NoneCompressor):
            return None
        if wire is not None:
            try:
                self._check_wire(wire)
            except ValueError as e:
                if not self._config_wire_warned:
                    self._config_wire_warned = True
                    import warnings
                    warnings.warn(
                        f"ignoring config.wire_dtype={cfg.wire_dtype!r} "
                        f"for this optimizer (op={self.op!r}): {e}")
                return None
        return wire

    _WIRE_UNSET = object()

    def _ensure_transform(self):
        """Build the chained (non-sharded) transform against the wire
        format resolved NOW, rebuilding if the resolution has changed
        since (init() before the autotuner installs config.wire_dtype
        must not freeze the stale value while ``tx.compression`` reports
        the new one). Rebuilding is safe: the chain's state structure
        does not depend on the wire format — only the traced update
        math changes, which is the point."""
        wire = self.compression
        if self._transform is None or wire is not self._transform_wire:
            import optax

            chained = optax.chain(
                DistributedGradientTransform(
                    op=self.op, axes=self.axes, compression=wire,
                    threshold_bytes=self.threshold_bytes,
                    hierarchical=self.hierarchical),
                self.inner,
            )
            if self.backward_passes_per_step > 1:
                chained = optax.MultiSteps(
                    chained,
                    every_k_schedule=self.backward_passes_per_step)
            self._transform = chained
            self._transform_wire = wire
        return self._transform

    def init(self, params):
        if self.sharded_update:
            from horovod_tpu.parallel import zero
            plan = zero.make_plan(
                params, op=self.op, axes=self.axes,
                threshold_bytes=self.threshold_bytes,
                hierarchical=bool(self._hierarchical_resolved()))
            return zero.init(self.inner, params, plan)
        return self._ensure_transform().init(params)

    def update(self, updates, state, params=None):
        if self.sharded_update:
            from horovod_tpu.parallel import zero
            if params is None:
                raise ValueError("sharded_update needs params: "
                                 "tx.update(grads, state, params)")
            return zero.sharded_update(self.inner, updates, state, params,
                                       wire=self.compression)
        return self._ensure_transform().update(updates, state, params)

    def update_preaveraged(self, grads, state, params=None):
        """Inner update on gradients that are ALREADY reduced across the
        mesh (the overlap pipeline reduce-scatters during backward and
        all-gathers before calling this) — skips the chained allreduce,
        preserves the chain's state structure."""
        if self.sharded_update or self.backward_passes_per_step > 1:
            raise ValueError("update_preaveraged is the plain-optimizer "
                             "tail of the overlap pipeline")
        inner_updates, inner_state = self.inner.update(grads, state[1],
                                                       params)
        return inner_updates, (state[0], inner_state)

    def update_spmd(self, grads, state, params, plan, wire=None,
                    ag_residuals=None):
        """The GSPMD-path update (``training.make_train_step(spmd=True)``
        routes here): gradients arrive as the logical GLOBAL-batch mean —
        XLA's inserted collectives already own the reduction — so no
        allreduce is chained. ZeRO-1 state goes through the plan's
        sharding-constraint exchange (``parallel/gspmd.apply_shards_spmd``,
        no explicit collective calls); plain state through the inner
        transform with the chain structure preserved, so optimizer state
        and checkpoints stay interchangeable with the explicit path.
        Same public ``DistributedOptimizer`` surface — this method is the
        routing, not a new user contract.

        ``wire``/``ag_residuals`` thread a CAST wire format (and its
        delta error-feedback carry) into the ZeRO-1 constraint exchange
        — see ``apply_shards_spmd``; chunked quantizers never reach
        here (the train step compiles them as a shard_map island)."""
        if self.sharded_update:
            from horovod_tpu.parallel import gspmd
            if params is None:
                raise ValueError("sharded_update needs params: "
                                 "tx.update_spmd(grads, state, params, plan)")
            return gspmd.apply_shards_spmd(self.inner, grads, state,
                                           params, plan, wire=wire,
                                           ag_residuals=ag_residuals)
        if self.backward_passes_per_step > 1:
            raise ValueError(
                "backward_passes_per_step>1 has no GSPMD path — its "
                "accumulator lives in the explicit pipeline; use "
                "make_train_step(accum_steps=...) there")
        if wire is not None or ag_residuals is not None:
            raise ValueError(
                "wire=/ag_residuals= narrow the ZeRO-1 "
                "(sharded_update=True) constraint exchange; the plain "
                "path's cast narrowing lives in the train step itself")
        return self.update_preaveraged(grads, state, params)

    def _hierarchical_resolved(self):
        if self.hierarchical is not None:
            return self.hierarchical
        from horovod_tpu import basics
        cfg = basics._state.config
        return cfg.hierarchical_allreduce if cfg is not None else False


def DistributedOptimizer(tx, op=Average, axes=None, compression=None,
                         threshold_bytes=None, hierarchical=None,
                         backward_passes_per_step=1, sharded_update=False):
    """Wrap optimizer ``tx`` so every update first averages gradients across
    all shards (the core Horovod contract,
    ``horovod/torch/__init__.py:57``). With
    ``backward_passes_per_step > 1`` gradients are accumulated locally and
    the allreduce fires every k-th step
    (``horovod/torch/__init__.py`` backward_passes_per_step).

    ``sharded_update=True`` switches the exchange to ZeRO stage-1
    (``parallel/zero.py``): gradients are reduce-scattered per fusion
    bucket, ``tx`` updates only this rank's 1/N shard of its state, and the
    updated parameter deltas are all-gathered — same wire bytes as the
    bandwidth-optimal allreduce, ~1/N the optimizer compute and state
    memory per device. ``tx`` must be elementwise (see the zero module
    docstring); ``init``/``update`` must then run where the mesh axes are
    bound (inside ``shard_map`` — ``training.make_train_step`` handles
    placement and specs automatically).

    ``compression`` picks the collective wire format: a compressor from
    ``hvd.Compression`` (``bf16``, ``fp8_e4m3``, ``int8``, ...) or its
    name as a string. ``None`` (default) defers to ``config.wire_dtype``
    (``HOROVOD_WIRE_DTYPE`` / the autotuner's wire axis), which itself
    defaults to uncompressed; pass ``Compression.none`` / ``"none"`` to
    force uncompressed regardless of config. Compression composes with
    ``sharded_update`` and the overlapped pipeline (``training.
    make_train_step(overlap_grads=True)`` threads the per-bucket
    error-feedback residual); genuinely unsupported combos — a chunked
    quantizer with Adasum/Min/Max — raise loudly (docs/PERFORMANCE.md,
    "Wire compression"). The config deferral binds LATE — at first use,
    not at construction — so building the optimizer before ``hvd.init()``
    or before the autotuner installs its winner still honors the
    config."""
    return HorovodOptimizer(
        tx, op=op, axes=axes, compression=compression,
        threshold_bytes=threshold_bytes, hierarchical=hierarchical,
        sharded_update=sharded_update,
        backward_passes_per_step=backward_passes_per_step)


def distributed_value_and_grad(fun, op=Average, axes=None, compression=None,
                               **grad_kwargs):
    """``jax.value_and_grad`` whose gradients are allreduced across shards
    (the ``DistributedGradientTape`` analogue,
    ``horovod/tensorflow/__init__.py:475-531``)."""
    vg = jax.value_and_grad(fun, **grad_kwargs)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        grads = fused_allreduce(grads, op=op, axes=axes,
                                compression=compression)
        return value, grads

    return wrapped


def distributed_grad(fun, op=Average, axes=None, compression=None,
                     **grad_kwargs):
    """``jax.grad`` with cross-shard gradient averaging."""
    g = jax.grad(fun, **grad_kwargs)

    def wrapped(*args, **kwargs):
        return fused_allreduce(g(*args, **kwargs), op=op, axes=axes,
                               compression=compression)

    return wrapped


def broadcast_variables(tree, root_rank=0, axes=None):
    """Replace every leaf with shard ``root_rank``'s value — the startup
    parameter sync (``horovod/torch/__init__.py:440``
    ``broadcast_parameters``, ``BroadcastGlobalVariablesHook``
    ``horovod/tensorflow/__init__.py:194-227``)."""
    return jax.tree_util.tree_map(
        lambda x: collective.broadcast(x, root_rank=root_rank, axes=axes),
        tree)


# Horovod names both of these in different frameworks; keep the aliases.
broadcast_parameters = broadcast_variables


def broadcast_optimizer_state(opt_state, root_rank=0, axes=None):
    """Broadcast optimizer state from ``root_rank``
    (``horovod/torch/__init__.py:472-560``). With optax the state is a
    pytree, so unlike the reference no state_dict walking is needed —
    one fused broadcast covers it. Non-float leaves (step counters) are
    broadcast as-is."""
    return broadcast_variables(opt_state, root_rank=root_rank, axes=axes)


def allreduce_metrics(metrics, axes=None, op=Average):
    """Reduce scalar metrics across shards at epoch end (reference:
    ``MetricAverageCallback``, ``horovod/_keras/callbacks.py:46-85``).

    ``op=Average`` (default) matches the reference: every metric becomes
    an fp32 mean — including int-valued ones (a sample COUNT averaged
    across shards is a float). Pass ``op=Sum`` for totals: integer
    leaves then keep their dtype (int counts stay exact ints).

    ``metrics`` may be any pytree (nested dicts of a framework's logs
    included); non-numeric leaves (strings, ``None``) pass through
    unchanged — the reference iterates ``logs`` items and only ever sees
    numeric metric values, so reducing a string has no reference
    semantics to honor and dropping it would lose the user's data.
    An empty dict/pytree comes back as-is."""
    def _numeric(x):
        if isinstance(x, (bool, int, float)) or (
                hasattr(x, "dtype") and hasattr(x, "shape")):
            try:
                return jnp.issubdtype(jnp.result_type(x), jnp.number) or \
                    jnp.issubdtype(jnp.result_type(x), jnp.bool_)
            # hvd-lint: disable=HVD-EXCEPT -- dtype probe: an unresolvable leaf passes through as-is on every rank
            except Exception:
                return False
        return False

    def one(x):
        if not _numeric(x):
            return x
        x = jnp.asarray(x)
        if op == Average or jnp.issubdtype(x.dtype, jnp.floating):
            x = jnp.asarray(x, jnp.float32)
        return collective.allreduce(x, op=op, axes=axes)
    return jax.tree_util.tree_map(one, metrics)


def join(grads_tree, is_active, op=Average, axes=None, **fusion_kwargs):
    """Join-aware gradient allreduce for uneven data: shards whose data is
    exhausted pass ``is_active=False`` and contribute zeros; the mean is
    taken over *active* shards only.

    This is the compiled-data-plane realization of the reference's Join op
    (``message.h:49`` JOIN request type; coordinator counts joined ranks and
    zero-fills them, ``controller.cc:797-820``, ``tensor_queue.h:39-41``).
    Host-level join (process drops out of the loop entirely) is handled by
    the controller — see ``horovod_tpu.runtime``.
    """
    active = jnp.asarray(is_active, jnp.float32)
    n_active = collective.allreduce(active, op=Sum, axes=axes)
    n_active = jnp.maximum(n_active, 1.0)

    def _one(g):
        masked = g * active.astype(g.dtype)
        summed = collective.allreduce(masked, op=Sum, axes=axes)
        if op == Average:
            summed = summed / n_active.astype(summed.dtype)
        return summed

    return jax.tree_util.tree_map(_one, grads_tree), n_active
