"""Checkpoint/resume conventions for distributed training.

Rebuilds the reference's checkpoint discipline (SURVEY §5.4;
``examples/keras_imagenet_resnet50.py:85-103,156-158``):

* **only rank 0 writes** — other workers would corrupt the file,
* the resume step is discovered on rank 0 and **broadcast** so every
  worker starts the same epoch (reference ``hvd.broadcast(resume_from_
  epoch, 0, name='resume_from_epoch')``),
* after a rank-0 restore, parameters and optimizer state are **broadcast
  from root** so all workers start identical (reference
  ``BroadcastGlobalVariablesCallback(0)`` + ``hvd.load_model``).

Pytrees are serialized with flax msgpack (TPU-idiomatic: works on any
params/opt_state tree, jax or numpy arrays); writes are atomic
(tmp + fsync + rename + directory fsync) so a worker killed mid-write
never leaves a truncated checkpoint behind and a committed one survives
power loss.

**This module is the legacy/compatibility surface.** The successor is
``horovod_tpu/ckpt`` (async snapshot-offload saves, per-rank shards,
two-phase manifest commit, elastic N→M resharded restore — see
docs/CHECKPOINT.md): new code and the elastic ``JaxState`` persist
through it. The rank-0 single-file format here stays fully supported
for small states and for restoring pre-subsystem checkpoints. One
signature changed: ``restore_or_init`` now returns ``(step, params,
opt_state, meta)`` — callers unpacking three values must add the
fourth.
"""

import os
import re

import numpy as np

_STEP_RE = re.compile(r"ckpt-(\d+)\.msgpack$")


def _fmt(directory, step):
    return os.path.join(directory, f"ckpt-{step}.msgpack")


def save_checkpoint(directory, step, params, opt_state=None, meta=None,
                    keep=None):
    """Write ``ckpt-<step>.msgpack`` from rank 0 only; no-op elsewhere.

    ``meta`` is a small JSON-able dict (e.g. epoch, rng seed). ``keep``
    (int) prunes all but the newest N checkpoints after a successful
    write."""
    import horovod_tpu as hvd
    if hvd.rank() != 0:
        return None
    return write_checkpoint(directory, step, params, opt_state=opt_state,
                            meta=meta, keep=keep)


def write_checkpoint(directory, step, params, opt_state=None, meta=None,
                     keep=None):
    """Rank-agnostic checkpoint write (atomic tmp+rename). Callers that
    are not under an initialized ``hvd`` — the elastic ``JaxState``, whose
    commits may run before/without ``init()`` — gate on their own notion
    of rank; everyone else should use :func:`save_checkpoint`."""
    import json

    from flax import serialization

    os.makedirs(directory, exist_ok=True)
    # meta rides as one JSON string leaf: flax from_bytes restores by the
    # TARGET's structure, so a dict-of-unknown-keys would come back empty
    payload = {"step": np.asarray(step, dtype=np.int64),
               "params": params,
               "opt_state": opt_state if opt_state is not None else {},
               "meta": json.dumps(meta or {})}
    data = serialization.to_bytes(payload)
    path = _fmt(directory, step)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    # rename alone only orders metadata in the page cache; the entry is
    # durable across power loss only once the DIRECTORY is fsynced
    from horovod_tpu.ckpt.manifest import fsync_dir
    fsync_dir(directory)
    if keep:
        _prune(directory, keep)
    return path


def _prune(directory, keep):
    """Retention: keep the newest ``keep`` COMPLETE checkpoints. Only
    names fully matching ``ckpt-<step>.msgpack`` are candidates — tmp
    files and anything else are never deleted by step order. The one
    exception: ``.msgpack.tmp`` debris OLDER than the newest complete
    step is a dead torn write and is swept; a newer tmp may be another
    rank's in-flight write and is left alone."""
    steps = list_steps(directory)
    for old in steps[:-keep]:
        try:
            os.remove(_fmt(directory, old))
        except OSError:
            pass
    if not steps:
        return
    newest = steps[-1]
    for name in os.listdir(directory):
        m = re.match(r"^ckpt-(\d+)\.msgpack\.tmp$", name)
        if m and int(m.group(1)) < newest:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def list_steps(directory):
    """Steps with a complete checkpoint in ``directory`` (rank-local)."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def resume_step(directory, default=0):
    """The step every worker should resume from: rank 0 scans the
    directory, the result is broadcast so workers agree even when the
    checkpoint dir is rank-0-local (reference resume_from_epoch
    broadcast, keras_imagenet_resnet50.py:85-88)."""
    import horovod_tpu as hvd
    if hvd.rank() == 0:
        steps = list_steps(directory)
        step = steps[-1] if steps else default
    else:
        step = default
    if hvd.size() > 1:
        step = int(np.asarray(
            hvd.broadcast(np.asarray([step], dtype=np.int64),
                          root_rank=0))[0])
    return step


def restore_checkpoint(directory, step, params, opt_state=None):
    """Load ``ckpt-<step>`` into the given target trees (flax msgpack
    needs the structure); returns ``(params, opt_state, meta)``.
    Rank-local read — see :func:`restore_or_init` for the broadcast
    discipline."""
    import json

    from flax import serialization
    target = {"step": np.asarray(0, dtype=np.int64),
              "params": params,
              "opt_state": opt_state if opt_state is not None else {},
              "meta": ""}
    with open(_fmt(directory, step), "rb") as f:
        restored = serialization.from_bytes(target, f.read())
    return (restored["params"], restored["opt_state"],
            json.loads(restored["meta"] or "{}"))


def restore_or_init(directory, params, opt_state=None, axes=None):
    """The full resume convention in one call:

    1. rank 0 discovers the newest checkpoint; the step is broadcast,
    2. if one exists, **rank 0** restores it (other ranks keep their
       fresh init),
    3. params (and opt_state) are broadcast from root so every worker
       starts identical — whether restored or freshly initialized.

    Returns ``(step, params, opt_state, meta)`` with ``step == 0`` and
    ``meta == {}`` when no checkpoint existed. The broadcast discipline
    is unchanged: only the step/params/opt_state travel the collective
    plane; ``meta`` (the small JSON dict ``save_checkpoint`` stored —
    epoch, rng seed, notes) is restored on rank 0 and ``{}`` elsewhere.
    Designed for the eager (pre-jit) phase of a training script; inside
    shard_map use ``hvd.broadcast_variables`` directly."""
    import horovod_tpu as hvd
    meta = {}
    step = resume_step(directory)
    if step > 0 and hvd.rank() == 0:
        params, opt_state, meta = restore_checkpoint(
            directory, step, params, opt_state)
    if hvd.size() > 1:
        params = hvd.broadcast_parameters(params, root_rank=0)
        if opt_state is not None:
            opt_state = hvd.broadcast_optimizer_state(opt_state,
                                                      root_rank=0)
    return step, params, opt_state, meta
