"""horovod_tpu — a TPU-native distributed data-parallel training framework.

A from-scratch rebuild of the capabilities of Horovod v0.18.2
(reference: ``/root/reference``, see ``SURVEY.md``) designed TPU-first:

* The **data plane** is compiled: gradient fusion, allreduce, allgather,
  broadcast, Adasum and hierarchical (ICI x DCN) reductions are expressed as
  XLA collectives over a ``jax.sharding.Mesh`` (reference equivalent:
  ``horovod/common/ops/nccl_operations.cc``, ``mpi_operations.cc``).
* The **control plane** is a host-side core (TCP controller + HTTP-style
  rendezvous, name-negotiated readiness, response cache, stall inspector,
  timeline, autotuner) mirroring ``horovod/common/{controller.cc,
  operations.cc}`` — but it never touches tensor bytes on TPU: negotiation
  decides *what* to run, XLA executes it.
* The **user contract** is Horovod's: ``init()``, ``rank()/size()``,
  ``DistributedOptimizer``, ``broadcast_variables``, Join, and an
  ``hvdrun``-style launcher (reference: ``horovod/run/run.py``).

Top-level namespace re-exports the JAX-first API (reference equivalent:
``horovod/tensorflow/__init__.py`` / ``horovod/torch/__init__.py``).
"""

from horovod_tpu import compat  # noqa: F401  (installs jax.shard_map shim)
from horovod_tpu.basics import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    num_devices,
    mesh,
    data_axes,
    ccl_built,
    ddl_built,
    gloo_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
)
from horovod_tpu.ops.collective import (
    Sum,
    Average,
    Adasum,
    Min,
    Max,
    allreduce,
    allgather,
    broadcast,
    reducescatter,
    alltoall,
    mesh_rank,
    mesh_size,
)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.ops.fusion import (autotune_fusion_threshold,
                                    fused_allreduce)
from horovod_tpu.hvd_jax import (
    DistributedOptimizer,
    DistributedGradientTransform,
    HorovodOptimizer,
    distributed_grad,
    distributed_value_and_grad,
    broadcast_variables,
    broadcast_parameters,
    broadcast_optimizer_state,
    allreduce_metrics,
    join,
)
from horovod_tpu import checkpoint
from horovod_tpu import ckpt
from horovod_tpu import data
from horovod_tpu import elastic
from horovod_tpu import telemetry

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "num_devices", "mesh", "data_axes", "mpi_threads_supported",
    "mpi_built", "mpi_enabled", "gloo_built", "nccl_built",
    "ddl_built", "ccl_built",
    "Sum", "Average", "Adasum", "Min", "Max",
    "allreduce", "allgather", "broadcast", "reducescatter", "alltoall",
    "mesh_rank", "mesh_size",
    "Compression", "fused_allreduce", "autotune_fusion_threshold",
    "DistributedOptimizer", "DistributedGradientTransform",
    "HorovodOptimizer",
    "distributed_grad", "distributed_value_and_grad",
    "broadcast_variables", "broadcast_parameters",
    "broadcast_optimizer_state", "allreduce_metrics", "join",
    "checkpoint", "ckpt", "data", "elastic", "telemetry",
]
