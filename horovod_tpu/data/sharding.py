"""Per-rank input sharding: the data side of the Horovod contract.

The reference's flagship examples feed every rank a disjoint shard of a
real dataset: torch via ``torch.utils.data.distributed.DistributedSampler``
(``examples/pytorch_imagenet_resnet50.py``), Keras/TF by splitting the
input files per rank (``examples/keras_imagenet_resnet50.py:102-158``),
MXNet via ``num_parts/part_index`` (``examples/mxnet_imagenet_resnet50.py``).
This module is that role, framework-neutral:

* ``shard_indices`` — the functional core: deterministic per-epoch
  shuffle, padded strided split (every rank gets the same count, the
  whole dataset is covered every epoch).
* ``DistributedSampler`` — the torch-sampler protocol (``__iter__`` /
  ``__len__`` / ``set_epoch``) over ``shard_indices``; duck-compatible
  with ``torch.utils.data.DataLoader(sampler=...)`` without importing
  torch.
* ``shard_dataset`` — the tf.data / grain variant: delegates to the
  dataset's own ``shard(num_shards, index)`` (both APIs expose it).
* ``local_batches`` — numpy/jax convenience iterator yielding this
  rank's batches of (arrays...) for hand-rolled loops.

Rank/size default to the initialized horovod_tpu world so the call sites
read exactly like the reference (``DistributedSampler(n)`` ==
``DistributedSampler(dataset, num_replicas=hvd.size(), rank=hvd.rank())``).
"""

import numpy as np


def _world(num_shards, shard_id):
    if num_shards is None or shard_id is None:
        from horovod_tpu import basics
        if basics.is_initialized():
            num_shards = basics.size() if num_shards is None else num_shards
            shard_id = basics.rank() if shard_id is None else shard_id
        else:
            num_shards = 1 if num_shards is None else num_shards
            if shard_id is None:
                if num_shards != 1:
                    # silently defaulting to shard 0 would hand EVERY
                    # process the same 1/N of the data with no error
                    raise ValueError(
                        f"num_shards={num_shards} but no shard_id and "
                        "horovod_tpu is not initialized; pass shard_id "
                        "explicitly (or call hvd.init() so rank() "
                        "supplies it)")
                shard_id = 0
    if not 0 <= shard_id < num_shards:
        raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
    return num_shards, shard_id


def shard_indices(n, num_shards=None, shard_id=None, *, epoch=0,
                  shuffle=True, seed=0, drop_last=False):
    """This shard's dataset indices for ``epoch``.

    Semantics of ``torch.utils.data.distributed.DistributedSampler``
    (the reference's input sharder): the order is a deterministic
    function of ``(seed, epoch)`` and identical on every rank; with
    ``drop_last=False`` the order is wrapped to the next multiple of
    ``num_shards`` so all shards get the same count and every example
    appears at least once per epoch; with ``drop_last=True`` the tail is
    trimmed instead. Shards take strided slices — pairwise disjoint by
    construction.
    """
    num_shards, shard_id = _world(num_shards, shard_id)
    if shuffle:
        order = np.random.default_rng((seed, epoch)).permutation(n)
    else:
        order = np.arange(n)
    if drop_last:
        order = order[:n - n % num_shards]
    elif n % num_shards:
        order = np.concatenate([order, order[:num_shards - n % num_shards]])
    return order[shard_id::num_shards]


class DistributedSampler:
    """Torch-sampler-protocol wrapper over ``shard_indices``.

    ``dataset`` may be a length (int) or anything with ``__len__``. Use
    as ``DataLoader(ds, sampler=DistributedSampler(ds))`` and call
    ``set_epoch(e)`` at each epoch start (same contract as torch's:
    forgetting it reuses epoch-0's shuffle order every epoch).
    """

    def __init__(self, dataset, num_replicas=None, rank=None, *,
                 shuffle=True, seed=0, drop_last=False):
        self._n = dataset if isinstance(dataset, int) else len(dataset)
        self.num_replicas, self.rank = _world(num_replicas, rank)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = int(epoch)

    def __iter__(self):
        return iter(shard_indices(
            self._n, self.num_replicas, self.rank, epoch=self.epoch,
            shuffle=self.shuffle, seed=self.seed,
            drop_last=self.drop_last).tolist())

    def __len__(self):
        if self.drop_last:
            return self._n // self.num_replicas
        return -(-self._n // self.num_replicas)


def shard_dataset(dataset, num_shards=None, shard_id=None):
    """Per-rank shard of a ``tf.data.Dataset`` / grain dataset — anything
    exposing ``shard(num_shards, index)`` (the reference pattern for TF
    input pipelines: shard FIRST, then shuffle/augment per rank)."""
    num_shards, shard_id = _world(num_shards, shard_id)
    return dataset.shard(num_shards, shard_id)


def local_batches(arrays, batch_size, num_shards=None, shard_id=None, *,
                  epoch=0, shuffle=True, seed=0, drop_last=True):
    """Yield this rank's batches as tuples of numpy views.

    ``arrays`` is a sequence of equal-length arrays (images, labels, ...).
    Batch boundaries fall inside the rank's shard, so ranks never see
    overlapping examples; ``drop_last=True`` (default) keeps every step's
    batch full — the SPMD-friendly choice (static shapes).

    ``drop_last`` governs BOTH trims, consistently: the cross-shard tail
    (``shard_indices`` would otherwise wrap-pad the shard, handing this
    rank duplicated examples within one epoch) and the ragged final
    batch. With ``drop_last=True`` an example therefore appears AT MOST
    once per rank per epoch; with ``drop_last=False`` the wrap padding
    keeps every example covered at the cost of a few duplicates near the
    epoch tail (DistributedSampler semantics)."""
    arrays = [np.asarray(a) for a in arrays]
    n = len(arrays[0])
    for a in arrays:
        if len(a) != n:
            raise ValueError("all arrays must share their leading dim")
    idx = shard_indices(n, num_shards, shard_id, epoch=epoch,
                        shuffle=shuffle, seed=seed, drop_last=drop_last)
    end = len(idx) - len(idx) % batch_size if drop_last else len(idx)
    for i in range(0, end, batch_size):
        b = idx[i:i + batch_size]
        yield tuple(a[b] for a in arrays)
