"""The prefetch loader: double-buffered host→device input, checkpointable
and elastic-aware.

``PrefetchLoader`` closes the last synchronous gap in the hot path: a
background PRODUCER thread assembles this rank's next batches from a
:mod:`~horovod_tpu.data.sources` source and stages them onto device
(``jax.device_put`` to the train step's mesh placement, when one is
attached) while the current step runs on the accelerator. The training
thread pulls from a bounded queue (``depth`` batches, default 2 — the
double buffer) and only ever blocks when the pipeline genuinely stalls;
that blocked time is exactly ``hvd_data_wait_seconds``.

**Determinism.** Which indices make up batch ``b`` is a pure function of
the cursor ``(seed, epoch, offset, batch_index)`` and the membership
``(rank, world)`` — the same :func:`~horovod_tpu.data.sharding`
``(seed, epoch)``-keyed permutation every rank computes identically,
strided across ranks. Prefetch depth, thread scheduling and restarts
cannot change the stream: the consumer-side cursor names the next batch
the TRAINING thread will receive, and rebuilding a loader from that
cursor replays the identical remaining stream bit for bit.

**Checkpointing.** ``cursor()`` is a small JSON-able dict;
``elastic.JaxState`` commits it alongside the model state and persists
it in the checkpoint MANIFEST (``meta["data_cursor"]``), so
``restore_sharded`` hands it back and a mid-epoch restore resumes the
batch stream exactly where the interrupted run's last commit left it
(docs/DATA.md, docs/CHECKPOINT.md).

**Elastic resharding.** ``on_reset(new_world)`` re-shards the REMAINING
sample space of the current epoch across the new membership: the global
examples this membership already consumed (``offset + batch_index *
batch_size * world`` positions of the epoch order) are retired into
``offset``, and the tail re-strides across the new world — every
remaining example is visited exactly once, none dropped, none repeated
(up to the usual wrap padding at the epoch tail when
``drop_last=False``).

The producer emits flight-recorder ``data`` B/E events and the consumer
brackets a genuine stall in ``data_wait`` B/E — which is what lets the
desync doctor's "data stall" verdict name the starving producer instead
of guessing (docs/DATA.md, diag/doctor.py).
"""

import logging
import queue
import threading
import time

import numpy as np

from horovod_tpu.data import sharding
from horovod_tpu.telemetry import ledger as _ledger

logger = logging.getLogger("horovod_tpu")

CURSOR_VERSION = 1
# a consumer wait longer than this is a real pipeline stall: bracket it
# with flight-recorder data_wait B/E so a post-mortem can see the
# training thread was starved (not hung) and by which producer
STALL_EVENT_S = 0.05
# queue poll granularity; must not exceed STALL_EVENT_S or the stall
# bracket's effective threshold silently becomes the poll interval
_GET_POLL_S = 0.05


def epoch_order(n, *, seed=0, epoch=0, shuffle=True):
    """The epoch's global example order — identical on every rank (the
    ``shard_indices`` permutation, pre-sharding)."""
    if shuffle:
        return np.random.default_rng((seed, epoch)).permutation(n)
    return np.arange(n)


def segment(n, *, seed=0, epoch=0, offset=0, world=1, batch_size=1,
            shuffle=True, drop_last=False):
    """The remaining sample space of ``epoch`` past ``offset``, shaped
    for ``world`` ranks taking ``batch_size`` examples per step: sized
    to a multiple of one GLOBAL batch (``world * batch_size``) — trimmed
    when ``drop_last``, wrap-padded otherwise, so with
    ``drop_last=False`` no example is ever dropped (the tail global
    batch repeats a few head examples instead — DistributedSampler's
    padding trade-off at batch granularity, which is what static SPMD
    shapes require). Rank ``r`` owns ``segment[r::world]`` — the
    strided split keeps consumption lockstep-interleaved, so "the first
    k global batches" is always a prefix of this array."""
    order = epoch_order(n, seed=seed, epoch=epoch, shuffle=shuffle)
    seg = order[int(offset):]
    if len(seg) == 0:
        return seg
    chunk = world * batch_size
    rem = len(seg) % chunk
    if drop_last:
        seg = seg[:len(seg) - rem] if rem else seg
    elif rem:
        seg = np.concatenate([seg, np.resize(seg, chunk - rem)])
    return seg


class PrefetchLoader:
    """Background-prefetching, cursor-addressable batch iterator.

    Parameters
    ----------
    source : a :mod:`~horovod_tpu.data.sources` source (``len`` +
        ``batch(indices)``).
    batch_size : this RANK's per-step batch (for the compiled SPMD step
        that is the per-process share of the global batch).
    depth : bounded prefetch queue size, >= 2 for real double buffering
        (1 still overlaps a single batch).
    rank, world : membership; default to the initialized horovod_tpu
        world exactly like ``shard_indices``.
    seed, shuffle, drop_last : stream identity knobs (``shard_indices``
        semantics; ``drop_last`` applies at the cross-rank tail AND the
        ragged final batch).
    epochs : stop after this many epochs (None = run forever).
    placement : optional callable run on the PRODUCER thread to stage
        the assembled numpy batch onto device —
        ``training.make_train_step(loader=...)`` installs its own
        ``device_put``-to-mesh here so the host→device copy overlaps
        the step too.
    telemetry : override the ``hvd_data_*`` registry instruments (a
        ``telemetry.DataInstruments``); default: the process registry.
    """

    def __init__(self, source, batch_size, *, depth=2, rank=None,
                 world=None, seed=0, shuffle=True, drop_last=True,
                 epochs=None, placement=None, telemetry=None):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = source
        self._batch_size = int(batch_size)
        self._depth = int(depth)
        self._world, self._rank = sharding._world(world, rank)
        self._seed = int(seed)
        self._shuffle = bool(shuffle)
        self._drop_last = bool(drop_last)
        self._epochs = None if epochs is None else int(epochs)
        self._placement = placement
        self.placement_spec = None
        self._epoch = 0
        self._offset = 0
        self._batch_index = 0
        self._lock = threading.Lock()
        # serializes whole halts (detach + join): see _halt_producer
        self._halt_lock = threading.Lock()
        self._queue = None
        self._thread = None
        self._stop = None
        self._gen = 0
        self._closed = False
        self._exhausted = False
        if telemetry is not None:
            self._metrics = telemetry
        else:
            from horovod_tpu.telemetry import data_instruments
            self._metrics = data_instruments()

    # -- stream identity ----------------------------------------------------
    @property
    def batch_size(self):
        return self._batch_size

    @property
    def rank(self):
        return self._rank

    @property
    def world(self):
        return self._world

    def batches_remaining_in_epoch(self):
        """Full batches this rank has left in the current epoch."""
        seg = segment(len(self._source), seed=self._seed,
                      epoch=self._epoch, offset=self._offset,
                      world=self._world, batch_size=self._batch_size,
                      shuffle=self._shuffle, drop_last=self._drop_last)
        nb = (len(seg) // self._world) // self._batch_size
        return max(nb - self._batch_index, 0)

    def _plan(self, epoch, offset, batch_index):
        """Yield ``(indices, cursor_after)`` from the given cursor on.
        Pure function of (cursor, membership) — the determinism anchor
        for prefetch, resume and resharding alike."""
        e, o, b = int(epoch), int(offset), int(batch_index)
        n = len(self._source)
        B, w = self._batch_size, self._world
        while self._epochs is None or e < self._epochs:
            seg = segment(n, seed=self._seed, epoch=e, offset=o,
                          world=w, batch_size=B, shuffle=self._shuffle,
                          drop_last=self._drop_last)
            mine = seg[self._rank::w]
            nb = len(mine) // B
            if nb == 0 and o == 0:
                raise ValueError(
                    f"dataset of {n} examples yields zero full batches "
                    f"for world={w} x batch_size={B}")
            while b < nb:
                idx = mine[b * B:(b + 1) * B]
                b += 1
                after = (e, o, b) if b < nb else (e + 1, 0, 0)
                yield idx, after
            e, o, b = e + 1, 0, 0

    # -- the producer -------------------------------------------------------
    def _produce(self, gen, q, stop, start):
        from horovod_tpu.diag import recorder as flightrec
        src_name = type(self._source).__name__
        place = self._placement
        try:
            for idx, after in self._plan(*start):
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                flightrec.record_event("data", ph="B",
                                       epoch=int(start[0]),
                                       batch=int(start[2]),
                                       source=src_name)
                batch = self._source.batch(idx)
                nbytes = sum(getattr(leaf, "nbytes", 0)
                             for leaf in _leaves(batch))
                if place is not None:
                    batch = place(batch)
                load_s = time.perf_counter() - t0
                flightrec.record_event("data", ph="E", source=src_name,
                                       nbytes=int(nbytes))
                self._metrics.load_seconds.observe(load_s)
                self._metrics.bytes_staged.inc(nbytes)
                if not _put(q, (gen, "batch", batch, after), stop):
                    return
                start = after
            _put(q, (gen, "end", None, None), stop)
        # hvd-lint: disable=HVD-EXCEPT -- producer thread: everything (incl. control flow) is re-raised on the consumer via the queue
        except BaseException as e:  # noqa: BLE001 — surfaced on the consumer
            _put(q, (gen, "error", e, None), stop)

    def _ensure_producer(self):
        # steady path: a live producer needs no halt coordination —
        # the consumer checks under self._lock alone and stays out of
        # any in-flight halt's way
        if self._closed:
            raise RuntimeError("PrefetchLoader is closed")
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
        # (re)start path: serialize with halts — a consumer must not
        # spawn a NEW producer while a halt is still joining the old
        # one (two threads concurrently inside source.batch(), or a
        # producer born after close() detached the stream). Same
        # _halt_lock → _lock order as _halt_producer, so no cycle.
        with self._halt_lock:
            self._ensure_producer_locked()

    def _ensure_producer_locked(self):
        if self._closed:
            raise RuntimeError("PrefetchLoader is closed")
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            if self._thread is not None and self._queue is not None \
                    and not self._queue.empty():
                # the producer ran its plan to completion and exited;
                # its queue still holds staged batches (+ the end
                # marker) — restarting now would throw them away and
                # re-stage them. Drain first; the end/error item halts
                # and clears the thread, and only then may we restart.
                return
            if self._thread is not None or self._queue is None:
                # fresh generation: a dead/halted producer's queue may
                # hold stale batches from a pre-set_cursor stream
                self._gen += 1
                self._queue = queue.Queue(maxsize=self._depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._produce,
                args=(self._gen, self._queue, self._stop,
                      (self._epoch, self._offset, self._batch_index)),
                daemon=True, name=f"hvd_data_prefetch_r{self._rank}")
            self._thread.start()

    def _halt_producer(self):
        # detach under self._lock, JOIN OUTSIDE it (hvd-lint
        # HVD-LOCKORDER): a producer parked in a slow storage read
        # (FileSource delay_s simulates exactly this) used to hold
        # every other loader entry point — including the elastic reset
        # path, whose recovery time is otherwise carefully bounded —
        # hostage for the whole read. The queue is generation-keyed, so
        # __next__ ignores anything the detached producer still emits.
        #
        # _halt_lock serializes WHOLE halts (and producer (re)starts):
        # every _halt_producer caller mutates cursor/source state right
        # after it returns (set_cursor, on_reset, close), so a second
        # halter must park here until the previous halt's producer has
        # really died — not skip ahead on seeing _thread already None
        # and call source.set_state() under a zombie's in-flight
        # batch() read. Consumers on the steady path (live producer)
        # only take self._lock and stay unblocked; a consumer that
        # needs a (re)start parks behind the halt by design.
        with self._halt_lock:
            with self._lock:
                t, q, stop = self._thread, self._queue, self._stop
                self._thread = None
                self._queue = None
                self._gen += 1
                if t is None:
                    return
                stop.set()
            while t.is_alive():
                try:  # unblock a producer parked in q.put
                    q.get_nowait()
                except queue.Empty:
                    pass
                # hvd-lint: disable=HVD-LOCKORDER -- _halt_lock guards only halts (no other acquisition path) and the join MUST finish before the caller mutates source state
                t.join(timeout=0.05)

    # -- the consumer -------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        from horovod_tpu.diag import recorder as flightrec
        if self._closed:
            raise RuntimeError("PrefetchLoader is closed")
        if self._exhausted:
            # don't spin up a producer just to re-emit the end marker;
            # set_cursor / on_reset clear this and re-arm the stream
            raise StopIteration
        self._ensure_producer()
        q, gen = self._queue, self._gen
        t0 = time.perf_counter()
        stalled = False
        while True:
            try:
                item = q.get(timeout=_GET_POLL_S)
            except queue.Empty:
                waited = time.perf_counter() - t0
                if not stalled and waited >= STALL_EVENT_S:
                    stalled = True
                    flightrec.record_event(
                        "data_wait", ph="B",
                        source=type(self._source).__name__,
                        epoch=self._epoch, batch=self._batch_index)
                t = self._thread
                if (t is None or not t.is_alive()) and q.empty():
                    raise RuntimeError(
                        "prefetch producer thread died without a "
                        "result — see the rank log for its traceback")
                continue
            g, kind, payload, after = item
            if g != gen:
                continue  # stale generation raced the restart
            break
        waited = time.perf_counter() - t0
        if stalled:
            flightrec.record_event("data_wait", ph="E",
                                   seconds=round(waited, 6))
        if kind == "error":
            self._halt_producer()
            raise payload
        if kind == "end":
            self._exhausted = True
            self._halt_producer()
            raise StopIteration
        self._metrics.wait_seconds.observe(waited)
        # the wait blocked the TRAINING thread: the goodput ledger books
        # it as data_wait instead of letting it masquerade as compute in
        # the next step settle (docs/OBSERVABILITY.md)
        _ledger.get_ledger().charge("data_wait", waited)
        self._metrics.queue_depth.set(q.qsize())
        self._metrics.batches.inc()
        self._epoch, self._offset, self._batch_index = after
        return payload

    # -- cursor / checkpoint ------------------------------------------------
    def cursor(self):
        """The (JSON-able) position of the NEXT batch the training
        thread will receive — prefetched-but-undelivered batches are
        deliberately not counted, so a restore never skips them."""
        return {
            "version": CURSOR_VERSION,
            "seed": self._seed,
            "shuffle": self._shuffle,
            "drop_last": self._drop_last,
            "batch_size": self._batch_size,
            "world": self._world,
            "epoch": self._epoch,
            "offset": self._offset,
            "batch_index": self._batch_index,
            "source": self._source.state(),
        }

    def set_cursor(self, cur):
        """Reposition the stream to ``cur`` (from :meth:`cursor`, the
        checkpoint manifest, or a peer's elastic sync). Stream-identity
        knobs (batch size, shuffle, drop_last, seed) are adopted from
        the cursor — they define WHICH stream the position is in.

        The cursor records the membership its ``batch_index`` counted
        against: restoring it into a loader with a DIFFERENT world
        (elastic N→M restore) automatically retires the old
        membership's consumption into ``offset`` and re-strides the
        remaining epoch across this loader's world — the same
        arithmetic as :meth:`on_reset`."""
        if cur is None:
            return
        v = cur.get("version", CURSOR_VERSION)
        if v != CURSOR_VERSION:
            raise ValueError(f"unknown data cursor version {v}")
        if int(cur.get("batch_size", self._batch_size)) \
                != self._batch_size:
            raise ValueError(
                f"cursor batch_size {cur['batch_size']} != loader "
                f"batch_size {self._batch_size}: the cursor names a "
                "position in a different batch stream")
        self._halt_producer()
        self._seed = int(cur.get("seed", self._seed))
        self._shuffle = bool(cur.get("shuffle", self._shuffle))
        self._drop_last = bool(cur.get("drop_last", self._drop_last))
        self._epoch = int(cur.get("epoch", 0))
        self._offset = int(cur.get("offset", 0))
        self._batch_index = int(cur.get("batch_index", 0))
        cur_world = int(cur.get("world", self._world))
        if cur_world != self._world:
            consumed = self._batch_index * self._batch_size * cur_world
            self._offset = min(self._offset + consumed,
                               len(self._source))
            self._batch_index = 0
        self._exhausted = False
        try:
            self._source.set_state(cur.get("source") or {})
        # hvd-lint: disable=HVD-EXCEPT -- cursor still applies; source extras are best-effort
        except Exception:
            logger.warning("data: source rejected its cursor state",
                           exc_info=True)

    # -- elastic ------------------------------------------------------------
    def on_reset(self, new_world=None, new_rank=None):
        """Re-shard the REMAINING sample space over a new membership
        (elastic N→M). Everything this membership consumed is retired
        into ``offset``; the epoch tail re-strides across the new world
        so no remaining example is dropped or revisited. Defaults to
        re-reading rank/world from the (re)initialized horovod_tpu
        world, which is what the elastic reset path wants."""
        self._halt_producer()
        consumed = self._batch_index * self._batch_size * self._world
        self._offset = min(self._offset + consumed, len(self._source))
        self._batch_index = 0
        self._world, self._rank = sharding._world(new_world, new_rank)
        self._exhausted = False

    # -- placement ----------------------------------------------------------
    def attach_placement(self, placement, spec=None):
        """Install (or replace) the producer-side staging function.
        ``training.make_train_step(loader=...)`` calls this with its
        own mesh ``device_put`` so batches land pre-sharded — on the
        GSPMD path that is a ``NamedSharding`` put straight onto the
        plan's batch sharding (``parallel/gspmd.py``), so prefetched
        batches arrive already laid out for the compiled step's
        ``in_shardings``. ``spec`` optionally names WHAT the staging
        targets (a ``PartitionSpec``/``NamedSharding``), exposed as
        ``placement_spec`` for diagnostics — the batch layout is
        otherwise opaque inside the callable. Replacing the placement
        restarts the producer from the consumer cursor —
        already-queued batches were staged the old way and are
        discarded, never delivered."""
        if placement is self._placement:
            # no-op re-attach: keep the recorded spec unless the caller
            # supplied a fresh one (a default None must not clobber it)
            if spec is not None:
                self.placement_spec = spec
            return
        self._halt_producer()
        self._placement = placement
        self.placement_spec = spec

    def close(self):
        # closed BEFORE the halt: a consumer parked behind the halt in
        # _ensure_producer must observe the close when it resumes, not
        # spawn a post-close producer (leaked thread doing I/O)
        self._closed = True
        self._halt_producer()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _leaves(tree):
    if isinstance(tree, dict):
        out = []
        for v in tree.values():
            out.extend(_leaves(v))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for v in tree:
            out.extend(_leaves(v))
        return out
    return [tree]


def _put(q, item, stop):
    """Bounded put that stays responsive to a halt: returns False when
    the producer should exit instead of blocking forever on a full
    queue nobody will drain."""
    while not stop.is_set():
        try:
            q.put(item, timeout=_GET_POLL_S)
            return True
        except queue.Full:
            continue
    return False
