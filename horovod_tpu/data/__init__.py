"""The distributed data plane: per-rank sharding + the prefetch loader.

Two layers, one subsystem (docs/DATA.md):

* **Sharding** (``sharding.py``) — the functional core carried over from
  the original ``data.py`` module: deterministic per-epoch permutations
  split disjointly across ranks (``shard_indices``), the torch-sampler
  protocol (``DistributedSampler``), ``shard_dataset`` for tf.data/grain,
  and the ``local_batches`` convenience iterator. Everything importable
  exactly as before — ``horovod_tpu.data`` is the same namespace.
* **Loading** (``sources.py`` + ``loader.py``) — what the reference
  lineage never had: :class:`PrefetchLoader` overlaps host batch
  assembly AND the host→device transfer with the running step
  (background producer, bounded queue), exposes a serializable cursor
  that rides the checkpoint manifest for exact mid-epoch resume, and
  re-shards the remaining sample space on elastic N→M membership
  changes. :class:`ArraySource` / :class:`FileSource` are the two
  shipped batch sources behind one index-addressed protocol.

Integration points: ``training.make_train_step(loader=...)`` installs
the step's mesh placement into the loader (batches land pre-sharded),
``training.elastic_train_loop`` accepts a loader in place of
``batch_fn``, and ``elastic.JaxState(loader=...)`` commits/restores the
cursor with the model state. Telemetry: the ``hvd_data_*`` series
(docs/OBSERVABILITY.md).
"""

from horovod_tpu.data.loader import (  # noqa: F401
    CURSOR_VERSION,
    PrefetchLoader,
    epoch_order,
    segment,
)
from horovod_tpu.data.sharding import (  # noqa: F401
    DistributedSampler,
    local_batches,
    shard_dataset,
    shard_indices,
)
from horovod_tpu.data.sources import (  # noqa: F401
    ArraySource,
    FileSource,
    Source,
)

__all__ = [
    "shard_indices", "DistributedSampler", "shard_dataset",
    "local_batches",
    "Source", "ArraySource", "FileSource",
    "PrefetchLoader", "epoch_order", "segment", "CURSOR_VERSION",
]
