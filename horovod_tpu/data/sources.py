"""Batch sources: the supply side of the prefetch loader's protocol.

A *source* is random-access storage for a dataset, addressed by global
example index — the contract ``loader.PrefetchLoader`` drives:

* ``len(source)`` — total example count (the global sample space the
  epoch permutation runs over).
* ``source.batch(indices)`` — assemble the examples at ``indices`` (a
  1-D numpy int array) into a pytree of stacked numpy arrays. Called
  from the loader's PRODUCER thread; may block on storage.
* ``source.state()`` / ``source.set_state(d)`` — optional
  source-specific cursor extras (a JSON-able dict) that ride the
  loader's cursor into the checkpoint manifest. The built-in sources
  are pure functions of their indices, so theirs is ``{}``.

Index-addressing is what makes the whole data plane deterministic:
the loader owns WHICH indices make up each batch (a pure function of
``(seed, epoch, offset, batch_index, rank, world)``), the source only
materializes them — so mid-epoch resume and elastic N→M resharding are
index arithmetic, never source state surgery.

Two implementations ship:

* :class:`ArraySource` — in-memory arrays (the ``local_batches``
  upgrade): zero-copy row gathers off resident numpy.
* :class:`FileSource` — file-backed ``.npy`` volumes, memory-mapped
  lazily per file, so the working set is what the producer touches, not
  the dataset. Doubles as the synthetic-latency source: ``delay_s``
  injects a per-batch storage stall, which is how the overlap tests and
  ``bench.py --data-plane`` make the input pipeline measurably the
  bottleneck on demand.

Both accept ``delay_s`` (default 0): a simulated per-``batch()`` storage
latency, applied before assembly on the producer thread.
"""

import os
import time

import numpy as np


class Source:
    """Protocol base: subclasses implement ``__len__`` and ``_gather``."""

    def __init__(self, delay_s=0.0):
        self.delay_s = float(delay_s)

    def batch(self, indices):
        """Assemble the examples at ``indices`` (producer-thread call)."""
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        return self._gather(np.asarray(indices))

    def state(self):
        """Source-specific cursor extras (JSON-able). Pure sources: {}."""
        return {}

    def set_state(self, state):
        del state

    def _gather(self, indices):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class ArraySource(Source):
    """In-memory arrays (images, labels, ...) behind the source protocol.

    ``arrays`` is a sequence (or dict) of equal-leading-dim numpy/jax
    arrays; ``batch`` stacks the requested rows into the same structure
    as a tuple (or dict) of numpy arrays.
    """

    def __init__(self, arrays, delay_s=0.0):
        super().__init__(delay_s=delay_s)
        if isinstance(arrays, dict):
            self._keys = tuple(sorted(arrays))
            items = [arrays[k] for k in self._keys]
        else:
            self._keys = None
            items = list(arrays)
        if not items:
            raise ValueError("ArraySource needs at least one array")
        self._arrays = [np.asarray(a) for a in items]
        n = len(self._arrays[0])
        for a in self._arrays:
            if len(a) != n:
                raise ValueError("all arrays must share their leading dim")
        self._n = n

    def __len__(self):
        return self._n

    def _gather(self, indices):
        rows = tuple(a[indices] for a in self._arrays)
        if self._keys is not None:
            return dict(zip(self._keys, rows))
        return rows


class FileSource(Source):
    """File-backed source over ``.npy`` volumes (one stacked array per
    file, possibly uneven lengths), memory-mapped on first touch.

    ``groups`` maps each field name to an ordered list of file paths;
    file ``k`` of every field must hold the same number of examples
    (the fields are parallel). Global example index ``i`` resolves to
    ``(file, row)`` through the cumulative lengths of the first field.

        FileSource({"images": ["a_img.npy", "b_img.npy"],
                    "labels": ["a_lbl.npy", "b_lbl.npy"]})

    A single flat list is shorthand for one anonymous field (batches
    come back as a 1-tuple). ``delay_s`` adds a synthetic per-batch
    storage latency on top of the real I/O.
    """

    def __init__(self, groups, delay_s=0.0):
        super().__init__(delay_s=delay_s)
        if not isinstance(groups, dict):
            groups = {None: list(groups)}
        if not groups or any(not paths for paths in groups.values()):
            raise ValueError("FileSource needs at least one file per field")
        nfiles = {len(paths) for paths in groups.values()}
        if len(nfiles) != 1:
            raise ValueError("every field needs the same number of files "
                             f"(got {sorted(nfiles)})")
        self._fields = sorted(groups, key=lambda k: (k is None, k))
        self._paths = {f: [os.fspath(p) for p in groups[f]]
                       for f in self._fields}
        self._mmaps = {f: [None] * len(groups[f]) for f in self._fields}
        first = self._fields[0]
        lengths = [self._file_len(first, k)
                   for k in range(len(self._paths[first]))]
        for field in self._fields[1:]:
            # file k of EVERY field must hold the same examples — a
            # mismatched split would silently pair rows of one field
            # with the wrong rows of another for the whole run
            other = [self._file_len(field, k)
                     for k in range(len(self._paths[field]))]
            if other != lengths:
                raise ValueError(
                    f"field {field!r} file lengths {other} do not match "
                    f"field {self._fields[0]!r} lengths {lengths}: "
                    "parallel fields must be split identically")
        self._starts = np.concatenate([[0], np.cumsum(lengths)])
        self._n = int(self._starts[-1])

    def _file_len(self, field, k):
        # mmap'ing reads the header only; rows fault in at first gather
        return int(self._mmap(field, k).shape[0])

    def _mmap(self, field, k):
        m = self._mmaps[field][k]
        if m is None:
            m = np.load(self._paths[field][k], mmap_mode="r")
            self._mmaps[field][k] = m
        return m

    def __len__(self):
        return self._n

    def _gather(self, indices):
        files = np.searchsorted(self._starts, indices, side="right") - 1
        rows = indices - self._starts[files]
        out = []
        for field in self._fields:
            # gather per touched file, scattered back into request order
            got = None
            for k in np.unique(files):
                sel = files == k
                chunk = np.asarray(self._mmap(field, int(k))[rows[sel]])
                if got is None:
                    got = np.empty((len(indices),) + chunk.shape[1:],
                                   chunk.dtype)
                got[sel] = chunk
            out.append(got)
        if self._fields == [None]:
            return (out[0],)
        return dict(zip(self._fields, out))
