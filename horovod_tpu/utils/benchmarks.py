"""Shared scaffold for the repo's benchmark scripts (bench.py,
bench_scaling.py): model registry, synthetic batch synthesis, and the
warmup + timed-loop throughput measurement (reference pattern:
``examples/pytorch_synthetic_benchmark.py:95-115``). One copy, so dtype
and donation semantics cannot drift between scripts."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def model_registry():
    from horovod_tpu import models
    return {"resnet18": models.ResNet18, "resnet50": models.ResNet50,
            "resnet101": models.ResNet101, "vgg16": models.VGG16}


def compute_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (emulated bf16 on CPU is
    slow and proves nothing)."""
    return (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
            else jnp.float32)


def make_model(name, dtype=None, num_classes=1000):
    dtype = dtype if dtype is not None else compute_dtype()
    return model_registry()[name](num_classes=num_classes, dtype=dtype)


def synthetic_batch(global_batch, image_size, dtype=None, num_classes=1000,
                    seed=0):
    dtype = dtype if dtype is not None else compute_dtype()
    rng = np.random.default_rng(seed)
    images = jnp.asarray(rng.standard_normal(
        (global_batch, image_size, image_size, 3)), dtype)
    labels = jnp.asarray(rng.integers(0, num_classes,
                                      size=(global_batch,)), jnp.int32)
    return images, labels


def cost_analysis_dict(compiled):
    """``compiled.cost_analysis()`` normalized across jax versions (some
    return the per-device dict, some a 1-list of it) — the ONE copy;
    bench.py and bench_roofline.py both read flops/bytes through it so
    a version that returns the list form cannot zero one script's MFU
    while the other reports correctly."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def sync(x):
    """Force TRUE completion by reading ONE element back to the host.

    ``jax.block_until_ready`` is NOT sufficient through an async
    execution tunnel (measured round 4: it returned in ~20 us while
    8192-cubed matmuls were still in flight, inflating throughput ~6x);
    a host readback cannot complete before the value exists anywhere.
    The element is sliced on-device first so the readback moves 2-4
    bytes — transferring a whole buffer would add a size-dependent,
    cold/warm-varying cost that poisons slope timing.
    """
    import jax.numpy as jnp
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.ravel(leaf)[0])


class WindowTime(float):
    """A ``slope_window`` duration. ``upper_bound`` is True when the
    inverted-window fallback reported the FULL window time (fixed costs
    included) instead of a slope difference — a conservative bound, not
    a measurement. ``asymmetric`` is True when the per-iteration rates
    implied by the two window segments disagreed beyond tolerance — a
    fixed cost attached itself to SOME window lengths but not others, so
    the slope may be deflated/inflated rather than clean. Callers that
    publish medians can count either flag so suspect samples are
    distinguishable in the reported runs."""

    upper_bound = False
    asymmetric = False

    def __new__(cls, value, upper_bound=False, asymmetric=False):
        obj = super().__new__(cls, value)
        obj.upper_bound = upper_bound
        obj.asymmetric = asymmetric
        return obj


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def slope_window(step_once, state, iters, base_iters=2, rounds=3,
                 rate_tolerance=0.5):
    """THE timing primitive (one copy — every bench path uses it).

    Times ``iters`` iterations by the slope method, hardened with
    interleaved windows: each of ``rounds`` rounds runs a *base* window
    (``base_iters`` iterations), a *mid* window (``base_iters + h``,
    ``h = iters // 2``) and a *full* window (``base_iters + iters``),
    each terminated by a forced readback (``sync``). Every
    (shorter, longer) window pair within a round yields a pairwise
    per-iteration slope; the reported duration is the MEDIAN pairwise
    slope times ``iters``. The readback guarantees real completion and
    its ~100 ms tunnel cost — like every other fixed dispatch cost —
    cancels in each difference; the median across interleaved rounds
    keeps any one polluted window (GC pause, CI neighbor, async residue
    draining late) from owning the result the way the old single
    base/full pair let it (the reproducible
    ``test_slope_window_measures_per_iteration_cost`` suite failure —
    VERDICT r5 Weak #1).

    Asymmetric fixed-cost detection: with three window lengths the
    per-iteration rate is implied twice over disjoint segments —
    ``(t_mid - t_base) / h`` and ``(t_full - t_mid) / (iters - h)``. A
    fixed cost that cancels symmetrically leaves the two medians equal;
    one that attaches to some window lengths only (partial constant
    folding, length-dependent re-dispatch) deflates one segment and
    inflates the other. When the medians disagree by more than
    ``rate_tolerance`` x the overall rate (and by a material absolute
    amount — clock granularity on near-zero work does not count), the
    result is flagged
    ``asymmetric`` (and a warning names the two rates) — the sample is
    still the best available estimate, but it is not a clean slope.

    ``step_once(state) -> (state, syncable)`` advances ONE iteration and
    must thread state so no two calls see identical inputs (the tunnel
    memoizes pure calls on repeated inputs — BENCH_NOTES.md).
    Returns ``(dt_for_iters, state)``; the duration is a ``WindowTime``
    whose ``upper_bound``/``asymmetric`` flags mark the fallback and
    suspect cases.

    Before the timed windows, ONE untimed flush iteration runs and is
    synced: any one-time cost left pending by earlier work in the
    process (deferred autotune/warm-up executables draining through the
    async tunnel, a first-touch compile) would land in the first short
    window and DEFLATE its slopes while passing as a clean measurement —
    a 10 ms/iter step measured 0.0127 s for 5 iters with
    ``upper_bound=False`` when run right after the fusion autotuner
    (VERDICT r5 "sharpest finding"). The flush pins that residue outside
    every timed window.
    """
    import warnings

    def window(k, st):
        out = None
        t0 = time.perf_counter()
        for _ in range(k):
            st, out = step_once(st)
        sync(out)
        return time.perf_counter() - t0, st

    h = iters // 2
    lengths = ([base_iters, base_iters + h, base_iters + iters]
               if 0 < h < iters else [base_iters, base_iters + iters])

    def measure(st):
        slopes, seg_lo, seg_hi, fulls = [], [], [], []
        for _ in range(max(1, rounds)):
            times = []
            for k in lengths:
                t, st = window(k, st)
                times.append(t)
            fulls.append(times[-1])
            for i in range(len(lengths)):
                for j in range(i + 1, len(lengths)):
                    slopes.append((times[j] - times[i])
                                  / (lengths[j] - lengths[i]))
            if len(lengths) == 3:
                seg_lo.append((times[1] - times[0]) / h)
                seg_hi.append((times[2] - times[1]) / (iters - h))
        return slopes, seg_lo, seg_hi, fulls, st

    _, state = window(1, state)  # untimed flush: absorb one-time residue
    slopes, seg_lo, seg_hi, fulls, state = measure(state)
    per_iter = _median(slopes)
    if per_iter <= 0:
        # jitter inversion (fixed-cost noise exceeded the work): retry
        # one full interleaved set, then fall back to the median FULL
        # window time — an upper bound including fixed costs, so the
        # published rate can only be conservative. (Clamping the slope
        # would publish an absurd multi-billion-rate sample; raising
        # would turn tiny smoke runs on loaded CI machines into flaky
        # failures.)
        slopes, seg_lo, seg_hi, fulls, state = measure(state)
        per_iter = _median(slopes)
        if per_iter <= 0:
            bound = _median(fulls)
            warnings.warn(
                f"slope window inverted twice (median pairwise slope "
                f"{per_iter:.6f}s/iter over {iters} iters); reporting "
                f"the full-window upper bound — increase iters for a "
                f"real measurement", stacklevel=2)
            return WindowTime(bound, upper_bound=True), state
    asymmetric = False
    if seg_lo and seg_hi:
        lo, hi = _median(seg_lo), _median(seg_hi)
        # relative disagreement AND a material absolute amount (clock
        # granularity on near-zero work is not an asymmetric fixed cost)
        if (abs(hi - lo) > rate_tolerance * max(per_iter, 1e-12)
                and abs(hi - lo) * iters > 1e-4):
            asymmetric = True
            warnings.warn(
                f"slope window segments imply different per-iteration "
                f"rates ({lo:.6f}s vs {hi:.6f}s per iter, median "
                f"{per_iter:.6f}s): a fixed cost is attaching "
                f"asymmetrically to window lengths; treat this sample "
                f"as suspect", stacklevel=2)
    return WindowTime(per_iter * iters, asymmetric=asymmetric), state


def repeat_step_windows(step_once, state, warmup, iters, repeats,
                        base_iters=2):
    """THE warm-then-measure discipline, step-shape-agnostic: ``warmup``
    synced calls (covers compilation; later windows are warm by
    construction), then ``repeats`` slope windows over the continuously
    evolving state (donation-safe — consumed once, threaded through).
    ``step_once(state) -> (state, syncable)``. Returns
    ``(list[WindowTime], state)`` — the ``upper_bound``/``asymmetric``
    flags ride along, so every caller can tell measurements from
    inverted-window bounds. One copy: ``repeat_throughput`` (the
    (images, labels) classification shape), bench.py's LM comparison
    and bench_roofline's LM roofline all delegate here, so the timing
    discipline cannot drift between scripts."""
    for _ in range(warmup):
        state, out = step_once(state)
        sync(out)
    runs = []
    for _ in range(repeats):
        dt, state = slope_window(step_once, state, iters,
                                 base_iters=base_iters)
        runs.append(dt)
    return runs, state


def repeat_throughput(step, state, images, labels, warmup, iters,
                      repeats, base_iters=2):
    """``repeats`` slope-timed windows of a ``step(state, images,
    labels)`` classification step, returning a list of
    ``(img_per_sec, dt)`` where ``dt`` is a ``WindowTime`` — check its
    ``upper_bound`` flag to tell slope measurements from inverted-window
    conservative bounds. The (images, labels) view of
    :func:`repeat_step_windows`."""
    dts, _ = repeat_step_windows(
        lambda st: step(st, images, labels), state, warmup, iters,
        repeats, base_iters=base_iters)
    return [(images.shape[0] * iters / dt, dt) for dt in dts]


def timed_throughput(step, state, images, labels, warmup, iters):
    """img/s of ``step`` over one slope-timed window (readback-
    terminated base + full windows, difference reported — see
    ``slope_window``). The single-window view of ``repeat_throughput``
    so the timing discipline has exactly one copy."""
    return repeat_throughput(step, state, images, labels, warmup, iters,
                             repeats=1)[0]


def make_lm_bench(*, mesh, seq_axis, batch, seq_len, layers, d_model,
                 heads, vocab, flash, dtype=None, lr=3e-4, spmd=False,
                 compression=None):
    """Build the LM benchmark workload ONE way — ``bench.py`` and
    ``examples/jax_lm_benchmark.py`` share it so their numbers describe
    the same program: exact sharded LM loss through
    ``DistributedOptimizer`` on a (data, seq) mesh. Returns
    ``(step, state, tokens)``; ``flash=None`` means the auto default.
    ``spmd=True`` builds the GSPMD LM step (``make_lm_train_step(
    spmd=True)`` — batch sharding only) and ``compression`` the wire
    format, so ``bench.py --spmd`` runs the same workload through every
    exchange variant."""
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)

    if dtype is None:
        dtype = (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
                 else jnp.float32)
    cfg = TransformerConfig(vocab_size=vocab, num_layers=layers,
                            num_heads=heads, d_model=d_model,
                            d_ff=4 * d_model, dtype=dtype,
                            sequence_axis=seq_axis,
                            flash_attention=flash)
    # init single-device (no seq sharding, no kernel) so params exist
    # before the sharded step compiles — same trick both callers used
    init_cfg = TransformerConfig(**{**cfg.__dict__, "sequence_axis": None,
                                    "flash_attention": False})
    tx = hvd.DistributedOptimizer(
        optax.adamw(lr), axes=("data", "seq") if seq_axis else ("data",),
        compression=compression)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)),
                         jnp.int32)
    state = training.create_train_state(Transformer(init_cfg), tx,
                                        jax.random.PRNGKey(0), tokens[:1])
    step = training.make_lm_train_step(Transformer(cfg), tx, mesh=mesh,
                                       batch_axis="data",
                                       seq_axis=seq_axis, spmd=spmd)
    return step, state, tokens
