"""Shared scaffold for the repo's benchmark scripts (bench.py,
bench_scaling.py): model registry, synthetic batch synthesis, and the
warmup + timed-loop throughput measurement (reference pattern:
``examples/pytorch_synthetic_benchmark.py:95-115``). One copy, so dtype
and donation semantics cannot drift between scripts."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def model_registry():
    from horovod_tpu import models
    return {"resnet18": models.ResNet18, "resnet50": models.ResNet50,
            "resnet101": models.ResNet101, "vgg16": models.VGG16}


def compute_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (emulated bf16 on CPU is
    slow and proves nothing)."""
    return (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
            else jnp.float32)


def make_model(name, dtype=None, num_classes=1000):
    dtype = dtype if dtype is not None else compute_dtype()
    return model_registry()[name](num_classes=num_classes, dtype=dtype)


def synthetic_batch(global_batch, image_size, dtype=None, num_classes=1000,
                    seed=0):
    dtype = dtype if dtype is not None else compute_dtype()
    rng = np.random.default_rng(seed)
    images = jnp.asarray(rng.standard_normal(
        (global_batch, image_size, image_size, 3)), dtype)
    labels = jnp.asarray(rng.integers(0, num_classes,
                                      size=(global_batch,)), jnp.int32)
    return images, labels


def sync(x):
    """Force TRUE completion by reading ONE element back to the host.

    ``jax.block_until_ready`` is NOT sufficient through an async
    execution tunnel (measured round 4: it returned in ~20 us while
    8192-cubed matmuls were still in flight, inflating throughput ~6x);
    a host readback cannot complete before the value exists anywhere.
    The element is sliced on-device first so the readback moves 2-4
    bytes — transferring a whole buffer would add a size-dependent,
    cold/warm-varying cost that poisons slope timing.
    """
    import jax.numpy as jnp
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.ravel(leaf)[0])


class WindowTime(float):
    """A ``slope_window`` duration. ``upper_bound`` is True when the
    inverted-window fallback reported the FULL window time (fixed costs
    included) instead of a slope difference — a conservative bound, not
    a measurement. Callers that publish medians can count these so
    bound samples are distinguishable in the reported runs."""

    upper_bound = False

    def __new__(cls, value, upper_bound=False):
        obj = super().__new__(cls, value)
        obj.upper_bound = upper_bound
        return obj


def slope_window(step_once, state, iters, base_iters=2):
    """THE timing primitive (one copy — every bench path uses it).

    Times ``iters`` iterations by the slope method: run a short
    ``base_iters`` window and a ``base_iters + iters`` window, each
    terminated by a forced readback (``sync``), and return their
    difference. The readback guarantees real completion and its ~100 ms
    tunnel cost — like every other fixed dispatch cost — cancels in the
    difference.

    ``step_once(state) -> (state, syncable)`` advances ONE iteration and
    must thread state so no two calls see identical inputs (the tunnel
    memoizes pure calls on repeated inputs — BENCH_NOTES.md).
    Returns ``(dt_for_iters, state)``; the duration is a ``WindowTime``
    whose ``upper_bound`` flag marks the inverted-window fallback.

    Before the timed windows, ONE untimed flush iteration runs and is
    synced: the base window is a single short measurement, so any one-time
    cost left pending by earlier work in the process (deferred autotune/
    warm-up executables draining through the async tunnel, a first-touch
    compile) would land in it and DEFLATE the slope while passing as a
    clean measurement — a 10 ms/iter step measured 0.0127 s for 5 iters
    with ``upper_bound=False`` when run right after the fusion autotuner
    (VERDICT r5 "sharpest finding"). The flush pins that residue outside
    both windows.
    """
    def window(k, st):
        out = None
        t0 = time.perf_counter()
        for _ in range(k):
            st, out = step_once(st)
        sync(out)
        return time.perf_counter() - t0, st

    _, state = window(1, state)  # untimed flush: absorb one-time residue
    t_base, state = window(base_iters, state)
    t_full, state = window(base_iters + iters, state)
    if t_full <= t_base:
        # jitter inversion (fixed-cost noise exceeded the work): retry
        # once, then fall back to the FULL window time — an upper bound
        # including fixed costs, so the published rate can only be
        # conservative. (Clamping the difference would publish an
        # absurd multi-billion-rate sample; raising would turn tiny
        # smoke runs on loaded CI machines into flaky failures.)
        t_base, state = window(base_iters, state)
        t_full, state = window(base_iters + iters, state)
        if t_full <= t_base:
            import warnings
            warnings.warn(
                f"slope window inverted twice (base {t_base:.4f}s >= "
                f"full {t_full:.4f}s over {iters} iters); reporting the "
                f"full-window upper bound — increase iters for a real "
                f"measurement", stacklevel=2)
            return WindowTime(t_full, upper_bound=True), state
    return WindowTime(t_full - t_base), state


def repeat_throughput(step, state, images, labels, warmup, iters,
                      repeats, base_iters=2):
    """``repeats`` slope-timed windows (``slope_window``) over a
    continuously evolving state (donation-safe: the caller's state is
    consumed once and threaded through), returning a list of
    ``(img_per_sec, dt)`` where ``dt`` is a ``WindowTime`` — check its
    ``upper_bound`` flag to tell slope measurements from inverted-window
    conservative bounds. Warmup (first repeat only) covers compilation;
    later windows are warm by construction."""
    for _ in range(warmup):
        state, loss = step(state, images, labels)
        sync(loss)
    runs = []
    for _ in range(repeats):
        dt, state = slope_window(
            lambda st: step(st, images, labels), state, iters,
            base_iters=base_iters)
        runs.append((images.shape[0] * iters / dt, dt))
    return runs


def timed_throughput(step, state, images, labels, warmup, iters):
    """img/s of ``step`` over one slope-timed window (readback-
    terminated base + full windows, difference reported — see
    ``slope_window``). The single-window view of ``repeat_throughput``
    so the timing discipline has exactly one copy."""
    return repeat_throughput(step, state, images, labels, warmup, iters,
                             repeats=1)[0]


def make_lm_bench(*, mesh, seq_axis, batch, seq_len, layers, d_model,
                 heads, vocab, flash, dtype=None, lr=3e-4):
    """Build the LM benchmark workload ONE way — ``bench.py`` and
    ``examples/jax_lm_benchmark.py`` share it so their numbers describe
    the same program: exact sharded LM loss through
    ``DistributedOptimizer`` on a (data, seq) mesh. Returns
    ``(step, state, tokens)``; ``flash=None`` means the auto default."""
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)

    if dtype is None:
        dtype = (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
                 else jnp.float32)
    cfg = TransformerConfig(vocab_size=vocab, num_layers=layers,
                            num_heads=heads, d_model=d_model,
                            d_ff=4 * d_model, dtype=dtype,
                            sequence_axis=seq_axis,
                            flash_attention=flash)
    # init single-device (no seq sharding, no kernel) so params exist
    # before the sharded step compiles — same trick both callers used
    init_cfg = TransformerConfig(**{**cfg.__dict__, "sequence_axis": None,
                                    "flash_attention": False})
    tx = hvd.DistributedOptimizer(
        optax.adamw(lr), axes=("data", "seq") if seq_axis else ("data",))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)),
                         jnp.int32)
    state = training.create_train_state(Transformer(init_cfg), tx,
                                        jax.random.PRNGKey(0), tokens[:1])
    step = training.make_lm_train_step(Transformer(cfg), tx, mesh=mesh,
                                       batch_axis="data",
                                       seq_axis=seq_axis)
    return step, state, tokens
