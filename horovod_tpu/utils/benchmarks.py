"""Shared scaffold for the repo's benchmark scripts (bench.py,
bench_scaling.py): model registry, synthetic batch synthesis, and the
warmup + timed-loop throughput measurement (reference pattern:
``examples/pytorch_synthetic_benchmark.py:95-115``). One copy, so dtype
and donation semantics cannot drift between scripts."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def model_registry():
    from horovod_tpu import models
    return {"resnet18": models.ResNet18, "resnet50": models.ResNet50,
            "resnet101": models.ResNet101, "vgg16": models.VGG16}


def compute_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (emulated bf16 on CPU is
    slow and proves nothing)."""
    return (jnp.bfloat16 if jax.devices()[0].platform == "tpu"
            else jnp.float32)


def make_model(name, dtype=None, num_classes=1000):
    dtype = dtype if dtype is not None else compute_dtype()
    return model_registry()[name](num_classes=num_classes, dtype=dtype)


def synthetic_batch(global_batch, image_size, dtype=None, num_classes=1000,
                    seed=0):
    dtype = dtype if dtype is not None else compute_dtype()
    rng = np.random.default_rng(seed)
    images = jnp.asarray(rng.standard_normal(
        (global_batch, image_size, image_size, 3)), dtype)
    labels = jnp.asarray(rng.integers(0, num_classes,
                                      size=(global_batch,)), jnp.int32)
    return images, labels


def repeat_throughput(step, state, images, labels, warmup, iters,
                      repeats):
    """``repeats`` back-to-back timed windows over a continuously
    evolving state (donation-safe: the caller's state is consumed once
    and threaded through), returning a list of ``(img_per_sec, dt)``.
    Warmup runs only before the first window — later windows are warm by
    construction. Each step consumes the previous state, so no two
    executions are identical and the whole sequence really executes."""
    runs = []
    for r in range(repeats):
        for _ in range(warmup if r == 0 else 0):
            state, loss = step(state, images, labels)
            jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step(state, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        runs.append((images.shape[0] * iters / dt, dt))
    return runs


def timed_throughput(step, state, images, labels, warmup, iters):
    """img/s of ``step`` over one timed window (async dispatch, one
    block at the end — the sequential state dependency makes the final
    block cover every step). The single-window view of
    ``repeat_throughput`` so the timing discipline has exactly one
    copy."""
    return repeat_throughput(step, state, images, labels, warmup, iters,
                             repeats=1)[0]
