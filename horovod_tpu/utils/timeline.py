"""Chrome-tracing-format timeline (``chrome://tracing`` / Perfetto).

Reference: ``horovod/common/timeline.cc`` — a dedicated writer thread fed by
a lockfree queue records per-tensor phases NEGOTIATING → TOP_LEVEL →
ACTIVITY (``timeline.h:47-77``), enabled by ``HOROVOD_TIMELINE=<file>`` on
the coordinator (``operations.cc:388-395``).

TPU version: the same event vocabulary for host-side phases (negotiation,
enqueue, fusion planning, step dispatch); device-side time lives in the XLA
profiler, so ``instant`` markers are emitted around dispatch to let users
line the two traces up. Events are queued to a writer thread so the hot
path never blocks on file IO (same design as the reference).
"""

import json
import queue
import threading
import time


class Timeline:
    NEGOTIATING = "NEGOTIATING"
    TOP_LEVEL = "TOP_LEVEL"

    def __init__(self, path, mark_cycles=False):
        self._path = path
        self._mark_cycles = mark_cycles
        self._queue = queue.Queue()
        self._start = time.perf_counter()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._closed = False
        self._thread = threading.Thread(target=self._writer_loop,
                                        name="hvd_tpu_timeline", daemon=True)
        self._thread.start()

    # -- event API (mirrors timeline.h naming) ------------------------------
    def _ts_us(self):
        return int((time.perf_counter() - self._start) * 1e6)

    def _emit(self, ev):
        if not self._closed:
            self._queue.put(ev)

    def negotiate_start(self, tensor_name, request_type):
        self._emit({"name": request_type, "cat": self.NEGOTIATING, "ph": "B",
                    "ts": self._ts_us(), "pid": 0, "tid": tensor_name})

    def negotiate_rank_ready(self, tensor_name, rank):
        self._emit({"name": f"rank_{rank}_ready", "ph": "i",
                    "ts": self._ts_us(), "pid": 0, "tid": tensor_name,
                    "s": "t"})

    def negotiate_end(self, tensor_name):
        self._emit({"name": "", "ph": "E", "ts": self._ts_us(), "pid": 0,
                    "tid": tensor_name})

    def start_activity(self, tensor_name, activity):
        self._emit({"name": activity, "ph": "B", "ts": self._ts_us(),
                    "pid": 0, "tid": tensor_name})

    def end_activity(self, tensor_name):
        self._emit({"name": "", "ph": "E", "ts": self._ts_us(), "pid": 0,
                    "tid": tensor_name})

    def instant(self, name, args=None):
        ev = {"name": name, "ph": "i", "ts": self._ts_us(), "pid": 0,
              "tid": "marker", "s": "g"}
        if args:
            ev["args"] = args
        self._emit(ev)

    def mark_cycle(self, n):
        if self._mark_cycles:
            self.instant(f"CYCLE_{n}")

    def bucket_marker(self, kind, index, nbytes):
        """BUCKET_RS / BUCKET_AG markers from the overlapped gradient-
        exchange pipeline (``ops.fusion``): emitted at trace time (the
        schedule is compiled once), they document which buckets exist and
        their wire bytes so the XLA profiler's device trace can be read
        against the emitted schedule."""
        self.instant(f"BUCKET_{kind}", args={"bucket": index,
                                             "bytes": int(nbytes)})

    def membership(self, event, details=None):
        """Instant marker for an elastic-membership change (host set
        updated, rendezvous epoch opened, worker failure blamed) so
        recovery gaps are visible next to the step trace."""
        self.instant(f"MEMBERSHIP_{event}", args=details or None)

    # -- writer thread -------------------------------------------------------
    def _writer_loop(self):
        first = True
        while True:
            ev = self._queue.get()
            if ev is None:
                break
            if not first:
                self._file.write(",\n")
            json.dump(ev, self._file)
            first = False
        self._file.write("\n]\n")
        self._file.close()

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5)
