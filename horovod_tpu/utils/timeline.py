"""Chrome-tracing-format timeline (``chrome://tracing`` / Perfetto).

Reference: ``horovod/common/timeline.cc`` — a dedicated writer thread fed by
a lockfree queue records per-tensor phases NEGOTIATING → TOP_LEVEL →
ACTIVITY (``timeline.h:47-77``), enabled by ``HOROVOD_TIMELINE=<file>`` on
the coordinator (``operations.cc:388-395``).

TPU version: the same event vocabulary for host-side phases (negotiation,
enqueue, fusion planning, step dispatch); device-side time lives in the XLA
profiler, so ``instant`` markers are emitted around dispatch to let users
line the two traces up. Events are queued to a writer thread so the hot
path never blocks on file IO (same design as the reference).

Cross-rank correlation (the telemetry plane): every rank writes its OWN
trace with its rank as the Chrome ``pid`` (plus ``process_name`` /
``process_sort_index`` metadata), a ``hvd_clock_sync`` event pins local
``ts=0`` to Unix time, counter events ("C" phase) carry registry metrics
onto the track, and flow events ("s"/"t"/"f") link step dispatch to the
bucket markers it schedules. ``horovod_tpu.telemetry.merge`` combines the
per-rank files into one aligned trace.

Crash tolerance: the writer flushes after every queue drain, so a hard
crash loses at most the events still in the queue and leaves a file that
is valid JSON minus the closing ``]`` — which the merge tool repairs.
``close()`` is idempotent, drains everything enqueued (including events
racing with close from other threads), then joins the writer.
"""

import json
import queue
import threading
import time

from horovod_tpu.telemetry.merge import CLOCK_SYNC


class Timeline:
    NEGOTIATING = "NEGOTIATING"
    TOP_LEVEL = "TOP_LEVEL"

    def __init__(self, path, mark_cycles=False, rank=0, host=None):
        self._path = path
        self._mark_cycles = mark_cycles
        self._pid = int(rank)
        self._queue = queue.Queue()
        self._start = time.perf_counter()
        unix_us = time.time() * 1e6
        self._file = open(path, "w")
        self._file.write("[\n")
        self._closed = False
        self._close_lock = threading.Lock()
        self._flow_id = 0
        label = f"rank {rank}" + (f" ({host})" if host else "")
        self._emit({"name": "process_name", "ph": "M", "pid": self._pid,
                    "args": {"name": label}})
        self._emit({"name": "process_sort_index", "ph": "M",
                    "pid": self._pid, "args": {"sort_index": self._pid}})
        self._emit({"name": CLOCK_SYNC, "ph": "i", "ts": 0,
                    "pid": self._pid, "tid": "marker", "s": "p",
                    "args": {"unix_time_us": unix_us, "rank": self._pid}})
        self._thread = threading.Thread(target=self._writer_loop,
                                        name="hvd_tpu_timeline", daemon=True)
        self._thread.start()

    # -- event API (mirrors timeline.h naming) ------------------------------
    def _ts_us(self):
        return int((time.perf_counter() - self._start) * 1e6)

    def _emit(self, ev):
        # check-and-put under the close lock: an emitter can no longer
        # pass the closed check, get preempted, and put onto a queue the
        # writer already finished — every accepted event precedes the
        # close sentinel
        with self._close_lock:
            if not self._closed:
                # hvd-lint: disable=HVD-LOCKORDER -- the queue is UNBOUNDED so put() never blocks; the lock only orders the closed check against close()
                self._queue.put(ev)

    def negotiate_start(self, tensor_name, request_type):
        self._emit({"name": request_type, "cat": self.NEGOTIATING, "ph": "B",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": tensor_name})

    def negotiate_rank_ready(self, tensor_name, rank):
        self._emit({"name": f"rank_{rank}_ready", "ph": "i",
                    "ts": self._ts_us(), "pid": self._pid,
                    "tid": tensor_name, "s": "t"})

    def negotiate_end(self, tensor_name):
        self._emit({"name": "", "ph": "E", "ts": self._ts_us(),
                    "pid": self._pid, "tid": tensor_name})

    def start_activity(self, tensor_name, activity):
        self._emit({"name": activity, "ph": "B", "ts": self._ts_us(),
                    "pid": self._pid, "tid": tensor_name})

    def end_activity(self, tensor_name):
        self._emit({"name": "", "ph": "E", "ts": self._ts_us(),
                    "pid": self._pid, "tid": tensor_name})

    def instant(self, name, args=None):
        ev = {"name": name, "ph": "i", "ts": self._ts_us(),
              "pid": self._pid, "tid": "marker", "s": "g"}
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name, values):
        """Chrome counter event ("C" phase): ``values`` is a flat
        name->number dict rendered as a stacked counter track — the
        bridge that puts registry metrics (step ms, examples/sec) on the
        same time axis as the trace slices."""
        self._emit({"name": name, "ph": "C", "ts": self._ts_us(),
                    "pid": self._pid, "args": {
                        k: float(v) for k, v in values.items()}})

    def flow_start(self, name, flow_id=None):
        """Open a flow arrow (ph "s"); returns the flow id to pass to
        :meth:`flow_point` / :meth:`flow_end`. Used to link a step
        dispatch to the bucket collectives it schedules."""
        if flow_id is None:
            self._flow_id += 1
            flow_id = self._flow_id
        self._emit({"name": name, "cat": "flow", "ph": "s",
                    "id": int(flow_id), "ts": self._ts_us(),
                    "pid": self._pid, "tid": "marker"})
        return flow_id

    def flow_point(self, name, flow_id):
        """A flow waypoint (ph "t") binding to the enclosing slice."""
        self._emit({"name": name, "cat": "flow", "ph": "t",
                    "id": int(flow_id), "ts": self._ts_us(),
                    "pid": self._pid, "tid": "marker", "bp": "e"})

    def flow_end(self, name, flow_id):
        self._emit({"name": name, "cat": "flow", "ph": "f",
                    "id": int(flow_id), "ts": self._ts_us(),
                    "pid": self._pid, "tid": "marker", "bp": "e"})

    def mark_cycle(self, n):
        if self._mark_cycles:
            self.instant(f"CYCLE_{n}")

    def bucket_marker(self, kind, index, nbytes, flow_id=None):
        """BUCKET_RS / BUCKET_AG markers from the overlapped gradient-
        exchange pipeline (``ops.fusion``): emitted at trace time (the
        schedule is compiled once), they document which buckets exist and
        their wire bytes so the XLA profiler's device trace can be read
        against the emitted schedule. ``flow_id`` links the marker back
        to the step dispatch that traced it."""
        self.instant(f"BUCKET_{kind}", args={"bucket": index,
                                             "bytes": int(nbytes)})
        if flow_id is not None:
            self.flow_point(f"BUCKET_{kind}", flow_id)

    def membership(self, event, details=None):
        """Instant marker for an elastic-membership change (host set
        updated, rendezvous epoch opened, worker failure blamed) so
        recovery gaps are visible next to the step trace."""
        self.instant(f"MEMBERSHIP_{event}", args=details or None)

    # -- writer thread -------------------------------------------------------
    def _write_one(self, ev, first):
        if not first:
            self._file.write(",\n")
        json.dump(ev, self._file)

    def _writer_loop(self):
        first = True
        done = False
        while not done:
            ev = self._queue.get()
            if ev is None:
                done = True
            else:
                self._write_one(ev, first)
                first = False
            # drain whatever else queued up, then flush ONCE: a crash
            # after any flush leaves valid-JSON-minus-"]" on disk
            while True:
                try:
                    ev = self._queue.get_nowait()
                except queue.Empty:
                    break
                if ev is None:
                    done = True  # keep draining: events enqueued by
                    continue     # threads racing close() still land
                self._write_one(ev, first)
                first = False
            self._file.flush()
        self._file.write("\n]\n")
        self._file.close()

    def close(self):
        """Idempotent drain-then-join: stop accepting events, let the
        writer drain everything already enqueued (including events that
        raced this call), and join it. If the writer cannot finish in
        time the file stays ``]``-less — still loadable after
        ``telemetry.merge`` repair."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5)
