"""Shared stdlib HTTP service scaffolding.

Two subsystems serve HTTP from a daemon ``ThreadingHTTPServer``: the
per-rank telemetry plane (``telemetry/server.py`` — /metrics, /healthz,
/flightrec, /profile) and the serving frontend (``serve/server.py`` —
streaming /generate). Both need the same boilerplate — a quiet handler
base with a content-length'd ``_respond``, an ephemeral-port-capable
bind, a named daemon serve thread, and an idempotent stop that joins —
and ``run/rendezvous.py`` already grew a third hand-rolled copy for the
launcher KV store (kept separate: its HMAC-authenticated PUT/DELETE
protocol shares none of this surface). This module is the one copy the
two service planes build on.

Port-collision policy stays with the caller: :meth:`HttpService.start`
raises the bind ``OSError`` untouched — ``runtime/services.py`` logs and
runs without a scrape plane, ``hvdrun`` pre-validates its
``--metrics-port`` fan-out, and ``bin/hvd-serve`` treats a taken port as
fatal. One mechanism, three policies.
"""

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger("horovod_tpu")


class QuietHandler(BaseHTTPRequestHandler):
    """Handler base: stderr chatter demoted to debug logging, plus the
    ``_respond`` helpers every endpoint uses. ``log_name`` labels the
    debug lines with the owning service."""

    log_name = "http"

    def log_message(self, fmt, *args):  # no stderr chatter
        logger.debug(self.log_name + " server: " + fmt, *args)

    def _respond(self, code, body, ctype):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _respond_json(self, code, obj):
        self._respond(code, json.dumps(obj), "application/json")


class HttpService:
    """start/stop lifecycle around one daemon ``ThreadingHTTPServer``.

    ``port=0`` binds an ephemeral port (the bound port is in ``.port``
    after :meth:`start`). Subclasses provide :meth:`_handler_class` —
    typically a closure over ``self`` returning a :class:`QuietHandler`
    subclass — and may extend :meth:`stop` (idempotent, joins the serve
    thread) with their own teardown."""

    thread_name = "hvd_tpu_http"

    def __init__(self, addr="127.0.0.1", port=0):
        self._addr = addr
        self._want_port = port
        self._httpd = None
        self._thread = None
        self.port = None

    def _handler_class(self):
        raise NotImplementedError

    def start(self):
        # a taken port raises OSError here, untouched — the caller owns
        # the collision policy (module docstring)
        self._httpd = ThreadingHTTPServer((self._addr, self._want_port),
                                          self._handler_class())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=self.thread_name, daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
