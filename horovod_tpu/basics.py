"""Process-level lifecycle and identity: init / shutdown / rank / size.

Rebuilds the surface of ``horovod/common/basics.py:22-213`` (ctypes calls
into ``horovod_init``/``horovod_rank``/... exported at
``horovod/common/operations.cc:641-778``) for TPU. Identity mapping:

* ``rank()``/``size()``         — this process among all launched processes.
  The ``hvdrun`` launcher starts one process per TPU chip (single-host) or
  per TPU VM (multi-host pods), mirroring one-process-per-GPU in the
  reference (``horovod/run/gloo_run.py:53-111`` slot allocation).
* ``local_rank()``/``local_size()``   — within this host.
* ``cross_rank()``/``cross_size()``   — across hosts/slices (DCN axis).
* ``num_devices()``             — total TPU chips in the mesh; inside a
  compiled step, the per-chip identity is ``mesh_rank()`` from
  ``horovod_tpu.ops.collective``.

Unlike the reference there is no background communication thread here: on
TPU the data plane is compiled into the step function by XLA, so ``init()``
only establishes identity, the mesh, and host-side services (controller
client, timeline, stall inspector).
"""

import atexit
import logging
import os
import threading

import jax

from horovod_tpu.config import Config
from horovod_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger("horovod_tpu")

_lock = threading.Lock()


class _State:
    """Process-global state (TPU analogue of ``HorovodGlobalState``,
    ``horovod/common/global_state.h:42-122``, minus the background thread)."""

    def __init__(self):
        self.initialized = False
        self.config = None
        self.mesh = None
        self.controller = None  # host-side controller client (set when used)
        self.timeline = None
        self.stall_inspector = None
        self.metrics_server = None
        self.flight_recorder = None
        self.ledger = None  # goodput time ledger (telemetry/ledger.py)
        self.preempt_handler = None  # graceful eviction (elastic/preempt.py)
        self.joined = False


_state = _State()


def _configure_logging(cfg):
    level = getattr(logging, cfg.log_level.upper(), logging.WARNING)
    fmt = "[%(levelname)s rank " + str(cfg.rank) + "] %(message)s"
    if not cfg.log_hide_timestamp:
        fmt = "%(asctime)s " + fmt
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(fmt))
    logger.handlers[:] = [handler]
    logger.setLevel(level)


def init(num_slices=None, devices=None):
    """Initialize horovod_tpu (idempotent, like ``InitializeHorovodOnce``,
    ``horovod/common/operations.cc:584``).

    Reads the launcher env contract (``HOROVOD_RANK/SIZE/...``), joins the
    multi-process JAX runtime when launched multi-process, and installs the
    global device mesh.
    """
    with _lock:
        if _state.initialized:
            return
        cfg = Config.from_env()
        _configure_logging(cfg)

        # XLA overlap flags (async collectives + latency-hiding scheduler)
        # must be in the environment before the first backend touch, or
        # the bucketed reduce-scatter pipeline compiles but never overlaps
        from horovod_tpu import config as config_lib
        config_lib.apply_xla_flags(cfg)

        # Multi-process: join the distributed JAX runtime so jax.devices()
        # spans every chip in the job. The coordinator address is provided by
        # the hvdrun launcher (TPU analogue of the gloo rendezvous address,
        # gloo_context.cc:41-50). cluster.ensure_distributed is the one
        # sanctioned jax.distributed.initialize call site (HVD-DISTINIT)
        # and also arms the CPU gloo collectives + forced per-process
        # device count before the first backend touch.
        from horovod_tpu.cluster import procmesh
        multiproc = procmesh.ensure_distributed(cfg)

        if multiproc and jax.process_count() > 1 and devices is None and \
                num_slices in (None, jax.process_count()):
            # ONE logical mesh spanning every process: dcn outer axis =
            # the process tier (DCN), data minor axis = this host's ICI
            # tier (docs/SCALING.md).
            m = procmesh.build_process_mesh()
            procmesh.assert_process_contiguous(m)
        else:
            if num_slices is None:
                num_slices = cfg.cross_size if cfg.cross_size > 1 else 1
            m = mesh_lib.build_mesh(devices=devices, num_slices=num_slices)
        mesh_lib.set_mesh(m)

        _state.config = cfg
        _state.mesh = m
        _state.initialized = True

        # Host-side services (timeline, stall inspector, controller client)
        # attach lazily; see horovod_tpu.runtime.
        from horovod_tpu.runtime import services
        services.start(_state)

        logger.info(
            "horovod_tpu initialized: rank=%d size=%d local=%d/%d cross=%d/%d "
            "mesh=%s devices=%d", cfg.rank, cfg.size, cfg.local_rank,
            cfg.local_size, cfg.cross_rank, cfg.cross_size,
            dict(zip(m.axis_names, m.devices.shape)), m.devices.size)
    atexit.register(shutdown)


def shutdown():
    """Tear down host-side services (``horovod_shutdown``,
    ``operations.cc:687``)."""
    with _lock:
        if not _state.initialized:
            return
        from horovod_tpu.runtime import services
        services.stop(_state)
        # a later init() may see a different device set (tests rebuild
        # meshes; elastic re-inits after membership changes) — the eager
        # path must not reuse a proc mesh over departed devices
        from horovod_tpu.ops import collective
        collective.invalidate_proc_mesh()
        _state.initialized = False
        _state.mesh = None
        _state.config = None


def is_initialized():
    return _state.initialized


def _cfg():
    if not _state.initialized:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call horovod_tpu.init()")
    return _state.config


def rank():
    """Rank of this process among all launched processes."""
    return _cfg().rank


def size():
    """Number of launched processes."""
    return _cfg().size


def local_rank():
    return _cfg().local_rank


def local_size():
    return _cfg().local_size


def cross_rank():
    return _cfg().cross_rank


def cross_size():
    return _cfg().cross_size


def num_devices():
    """Total TPU chips in the global mesh (the data-parallel world size of
    the compiled data plane)."""
    if not _state.initialized:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call horovod_tpu.init()")
    return _state.mesh.devices.size


def mesh():
    """The global ``jax.sharding.Mesh`` installed by ``init()``."""
    if not _state.initialized:
        raise RuntimeError(
            "horovod_tpu has not been initialized; call horovod_tpu.init()")
    return _state.mesh


def data_axes():
    """Axis names gradients are reduced over, e.g. ``('data',)`` or
    ``('dcn', 'data')``."""
    return mesh_lib.data_axis_names(mesh())


def mpi_threads_supported():
    """Parity shim for ``hvd.mpi_threads_supported()``
    (``horovod/common/basics.py``): there is no MPI on TPU VMs; the control
    plane is TCP. Always False."""
    return False


def mpi_built():
    """Parity probe (reference ``basics.py:162``): MPI-free by design —
    the control plane is TCP, the data plane XLA/ICI + host rings."""
    return False


def mpi_enabled():
    return False


_gloo_loadable = None  # caches only a positive probe (cannot un-load)


def gloo_built():
    """Parity probe (reference ``basics.py:181``): the role Gloo plays
    in the reference (TCP collectives without MPI) is filled by the
    built-in C++ core — True when the native library is present and
    loadable. Loadability only: a capability probe must never kick off
    the make-based build (that is ``_core.build()``'s job at init).
    A successful load is cached (repeated ``CDLL`` calls would pile up
    dlopen references); a negative answer is re-probed, since init may
    build the library later in the process."""
    global _gloo_loadable
    import ctypes
    import os

    from horovod_tpu import _core
    if _core._lib is not None or _gloo_loadable:
        return True
    if not os.path.exists(_core._LIB_PATH):
        return False
    try:
        ctypes.CDLL(_core._LIB_PATH)
        _gloo_loadable = True
        return True
    except OSError:
        return False


_nccl_preinit_warned = False  # warn once per process, not per probe


def nccl_built():
    """Parity probe (reference ``basics.py:189``): the "NCCL of TPU" is
    the XLA/ICI collective path. Returns an int like the reference
    (which returns the NCCL version code): 0 when no TPU backend is
    live, 1 otherwise — code that version-gates NCCL-specific features
    (``nccl_built() >= 21000``) correctly takes its non-NCCL path here,
    while plain truthiness probes see "built".

    Before ``hvd.init()`` this returns 0 WITHOUT touching
    ``jax.devices()``: a capability probe must not initialize the local
    JAX backend out from under a pending ``jax.distributed`` setup in a
    multi-process pod. Probe after ``init()`` for the real answer."""
    if not is_initialized():
        global _nccl_preinit_warned
        if not _nccl_preinit_warned:
            _nccl_preinit_warned = True
            logger.warning(
                "nccl_built() probed before hvd.init(): the TPU backend "
                "is not attached yet, so this reports 0 (not built). "
                "Probe again after init() for the real answer.")
        return 0
    try:
        return int(any(d.platform == "tpu" for d in jax.devices()))
    # hvd-lint: disable=HVD-EXCEPT -- device probe: backend errors mean no TPU, report 0
    except Exception:
        return 0


def ddl_built():
    return False


def ccl_built():
    return False
