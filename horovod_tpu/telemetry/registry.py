"""Rank-local metrics registry: counters, gauges, histograms.

The reference exposes job health through four disconnected channels (the
coordinator Timeline, the stall inspector's log lines, the autotuner CSV,
and whatever the user's own loop prints). This registry is the single
substrate they all feed here: every subsystem records into process-local
metric objects, and the same data leaves the process three ways —

* the Prometheus text endpoint (``telemetry/server.py``),
* compact snapshots on the elastic KV heartbeat path
  (``elastic/worker.py`` -> ``elastic/driver.py`` cluster view),
* Chrome-trace counter events (``utils/timeline.py`` "C" phase).

Hot-path discipline: recording a sample is a lock acquire + a float add
(counters/gauges) or a bisect into STATIC bucket bounds plus one slot
write into a PREALLOCATED reservoir (histograms). No dicts, lists, or
strings are allocated per observation; label children are resolved once
at instrument-creation time and cached by the caller.
"""

import bisect
import math
import threading

# Default latency buckets (seconds): 1 ms .. ~107 s, x2 per bucket —
# wide enough for both a TPU step (ms) and an elastic recovery (tens of s).
DEFAULT_BUCKETS = tuple(0.001 * (2 ** i) for i in range(18))


def _fmt(v):
    """Prometheus float formatting: integers bare, +Inf spelled."""
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(names, values):
    if not names:
        return ""
    inner = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Common base: a named family with optional label dimensions.

    A family with labels holds one child per label-value tuple; a family
    without labels is its own single child. ``labels(...)`` is meant to be
    called ONCE at instrument-creation time (the returned child is the
    zero-allocation handle the hot path keeps)."""

    kind = "untyped"

    def __init__(self, name, help="", label_names=()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children = {}  # label values tuple -> child

    def labels(self, *values):
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values!r}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _self_child(self):
        """The label-less singleton child."""
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; use .labels()")
        return self.labels()

    def _each(self):
        with self._lock:
            return list(self._children.items())


class Counter(_Metric):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    class _Child:
        __slots__ = ("_lock", "value")

        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0.0

        def inc(self, n=1.0):
            if n < 0:
                raise ValueError("counters only go up")
            with self._lock:
                self.value += n

    def _new_child(self):
        return Counter._Child()

    def inc(self, n=1.0):
        self._self_child().inc(n)

    @property
    def value(self):
        return self._self_child().value

    def render(self, out, name=None):
        name = name or self.name
        for lv, child in self._each():
            out.append("%s%s %s" % (name,
                                    _fmt_labels(self.label_names, lv),
                                    _fmt(child.value)))

    def sample(self):
        if not self.label_names:
            return self.value
        return {lv: c.value for lv, c in self._each()}


class Gauge(_Metric):
    """Point-in-time value. ``set_function`` registers a collect-time
    callback — the trick that lets a gauge report a value living in a
    device array (last loss, last grad-norm) WITHOUT forcing a host sync
    on the training hot path: the readback happens when something
    scrapes, not when the step runs."""

    kind = "gauge"

    class _Child:
        __slots__ = ("_lock", "_value", "_fn")

        def __init__(self):
            self._lock = threading.Lock()
            self._value = 0.0
            self._fn = None

        def set(self, v):
            with self._lock:
                self._value = float(v)
                self._fn = None

        def inc(self, n=1.0):
            with self._lock:
                self._value += n

        def dec(self, n=1.0):
            self.inc(-n)

        def set_function(self, fn):
            with self._lock:
                self._fn = fn

        @property
        def value(self):
            with self._lock:
                fn = self._fn
                if fn is None:
                    return self._value
            try:
                return float(fn())
            # hvd-lint: disable=HVD-EXCEPT -- gauge callback: NaN marks a failed read
            except Exception:
                return float("nan")

    def _new_child(self):
        return Gauge._Child()

    def set(self, v):
        self._self_child().set(v)

    def inc(self, n=1.0):
        self._self_child().inc(n)

    def dec(self, n=1.0):
        self._self_child().dec(n)

    def set_function(self, fn):
        self._self_child().set_function(fn)

    @property
    def value(self):
        return self._self_child().value

    def render(self, out, name=None):
        name = name or self.name
        for lv, child in self._each():
            out.append("%s%s %s" % (name,
                                    _fmt_labels(self.label_names, lv),
                                    _fmt(child.value)))

    def sample(self):
        if not self.label_names:
            return self.value
        return {lv: c.value for lv, c in self._each()}


class Histogram(_Metric):
    """Prometheus histogram (cumulative static buckets + _sum/_count)
    with a bounded reservoir for quantile estimates in snapshots.

    The reservoir is PREALLOCATED and overwritten in place (algorithm R:
    after it fills, sample i replaces a uniformly random slot with
    probability size/i) — observing never allocates, and the snapshot's
    p50/p90 stay representative of the whole run, not just the tail."""

    kind = "histogram"

    def __init__(self, name, help="", label_names=(),
                 buckets=DEFAULT_BUCKETS, reservoir_size=256):
        super().__init__(name, help, label_names)
        self._buckets = tuple(sorted(buckets))
        self._reservoir_size = reservoir_size

    class _Child:
        __slots__ = ("_lock", "bounds", "counts", "sum", "count",
                     "_res", "_res_n", "_rng")

        def __init__(self, bounds, reservoir_size):
            import random
            self._lock = threading.Lock()
            self.bounds = bounds
            self.counts = [0] * (len(bounds) + 1)  # +1 for +Inf
            self.sum = 0.0
            self.count = 0
            self._res = [0.0] * reservoir_size
            self._res_n = 0
            self._rng = random.Random(0x5EED)

        def observe(self, v):
            v = float(v)
            with self._lock:
                self.counts[bisect.bisect_left(self.bounds, v)] += 1
                self.sum += v
                self.count += 1
                n, size = self._res_n, len(self._res)
                if n < size:
                    self._res[n] = v
                else:
                    j = self._rng.randrange(n + 1)
                    if j < size:
                        self._res[j] = v
                self._res_n = n + 1

        def quantile(self, q):
            with self._lock:
                n = min(self._res_n, len(self._res))
                if not n:
                    return 0.0
                vals = sorted(self._res[:n])
            return vals[min(int(q * n), n - 1)]

    def _new_child(self):
        return Histogram._Child(self._buckets, self._reservoir_size)

    def observe(self, v):
        self._self_child().observe(v)

    @property
    def count(self):
        return self._self_child().count

    @property
    def sum(self):
        return self._self_child().sum

    def quantile(self, q):
        return self._self_child().quantile(q)

    def render(self, out, name=None):
        name = name or self.name
        for lv, child in self._each():
            with child._lock:
                counts = list(child.counts)
                total, s = child.count, child.sum
            cum = 0
            for bound, c in zip(child.bounds, counts):
                cum += c
                lv_le = lv + (_fmt(bound),)
                out.append("%s_bucket%s %d" % (
                    name,
                    _fmt_labels(self.label_names + ("le",), lv_le), cum))
            out.append("%s_bucket%s %d" % (
                name,
                _fmt_labels(self.label_names + ("le",), lv + ("+Inf",)),
                total))
            out.append("%s_sum%s %s" % (
                name, _fmt_labels(self.label_names, lv), _fmt(s)))
            out.append("%s_count%s %d" % (
                name, _fmt_labels(self.label_names, lv), total))

    def sample(self):
        def one(child):
            return {"count": child.count, "sum": child.sum,
                    "p50": child.quantile(0.50),
                    "p90": child.quantile(0.90),
                    "max": child.quantile(1.0)}
        if not self.label_names:
            return one(self._self_child())
        return {lv: one(c) for lv, c in self._each()}


class MetricsRegistry:
    """Name -> metric family. Creation is get-or-create so subsystems can
    declare the same instrument independently; a kind/label mismatch on
    an existing name is a programming error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._aliases = {}  # canonical name -> deprecated scrape alias

    def install_aliases(self, aliases):
        """Serve each canonical family under a deprecated name too
        (scrape-time only: snapshots and heartbeats stay canonical).
        The one-release migration path for the ``horovod_*`` ->
        ``hvd_*`` rename (docs/OBSERVABILITY.md deprecation note)."""
        with self._lock:
            self._aliases.update(aliases)

    def _get_or_create(self, cls, name, help, label_names, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.__name__} "
                        f"labels={tuple(label_names)} but exists as "
                        f"{type(m).__name__} labels={m.label_names}")
                return m
            m = cls(name, help=help, label_names=label_names, **kwargs)
            if not m.label_names:
                m._self_child()  # a zero-valued family must still render
            self._metrics[name] = m
            return m

    def counter(self, name, help="", label_names=()):
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name, help="", label_names=()):
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name, help="", label_names=(),
                  buckets=DEFAULT_BUCKETS, reservoir_size=256):
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets,
                                   reservoir_size=reservoir_size)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def render_prometheus(self):
        """The Prometheus text exposition format (version 0.0.4).
        Aliased families render twice: canonically, then under the
        deprecated name with a HELP line pointing migrations at the
        canonical one."""
        with self._lock:
            metrics = sorted(self._metrics.items())
            aliases = dict(self._aliases)
        lines = []
        for name, m in metrics:
            if m.label_names and not m._each():
                # A labelled family with no children yet would emit a
                # HELP/TYPE header with zero sample lines — invalid for
                # strict expfmt parsers. Unlabelled families always have
                # their self-child, so they still render at zero.
                continue
            if m.help:
                lines.append("# HELP %s %s" % (
                    name, m.help.replace("\\", "\\\\").replace("\n", " ")))
            lines.append("# TYPE %s %s" % (name, m.kind))
            m.render(lines)
        for name, m in metrics:
            legacy = aliases.get(name)
            if legacy is None:
                continue
            if m.label_names and not m._each():
                continue
            lines.append("# HELP %s DEPRECATED alias of %s; the "
                         "horovod_* names are removed next release"
                         % (legacy, name))
            lines.append("# TYPE %s %s" % (legacy, m.kind))
            m.render(lines, name=legacy)
        return "\n".join(lines) + "\n"

    def snapshot(self):
        """Plain-dict view of every metric: counters/gauges -> float,
        histograms -> {count, sum, p50, p90, max}. Labelled families map
        'name{a=x,b=y}' -> value. This is what rides the elastic KV
        heartbeats and the BENCH json ``telemetry`` block."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out = {}
        for name, m in metrics:
            s = m.sample()
            if m.label_names and isinstance(s, dict):
                for lv, v in s.items():
                    key = name + _fmt_labels(m.label_names, lv)
                    out[key] = v
            else:
                out[name] = s
        return out


_default = MetricsRegistry()


def get_registry():
    """The process-wide default registry (every built-in instrument
    records here)."""
    return _default
