"""Merge per-rank Chrome traces into one Perfetto-loadable trace.

Each rank writes its own host trace (``utils/timeline.py``; per-rank
paths are derived from ``HOROVOD_TIMELINE`` by ``runtime/services.py``).
This tool unifies them:

* **repair** — a crashed or still-running rank leaves a JSON array with
  no closing ``]`` (or a half-written final event). :func:`load_events`
  parses what is recoverable instead of failing the whole merge.
* **pid assignment** — every event of rank r lands under ``pid=r`` with
  ``process_name`` / ``process_sort_index`` metadata, so Perfetto shows
  one labelled track group per rank.
* **clock alignment** — each trace carries a ``clock_sync`` event
  recording the Unix time at its local ``ts=0`` (``Timeline`` emits it
  at construction). All ranks are shifted onto the earliest rank's
  clock, so cross-rank causality (a straggler's step finishing late, a
  membership interrupt landing mid-step) reads directly off the merged
  view. NTP-quality alignment only — good to ~ms across hosts, exact
  within one host.

CLI::

    python -m horovod_tpu.telemetry.merge -o merged.json trace.rank*.json
    hvdrun --merge-timeline merged.json trace.rank*.json
"""

import argparse
import glob as _glob
import json
import re
import sys

CLOCK_SYNC = "hvd_clock_sync"
# Flow events in this category carry GLOBALLY allocated ids (the serve
# tracer's request-hop arrows, serve/tracing.py): one id deliberately
# spans several pids, so the merge must NOT per-rank-namespace it.
GLOBAL_FLOW_CAT = "hvd_global_flow"
_RANK_RE = re.compile(r"\.rank(\d+)\.")


def load_events(path):
    """Load one trace file, repairing truncation: trailing-``]`` repair
    first, then progressively dropping half-written tail events."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    t = text.strip()
    if t.startswith("{"):  # object-format trace from another tool
        raise ValueError(f"{path}: unrecoverable non-array trace")
    if not t.startswith("["):
        raise ValueError(f"{path}: not a Chrome trace JSON array")
    # cut back to the last complete event object, then close the array;
    # a few iterations cover a half-written event containing nested "}"
    end = len(t)
    for _ in range(64):
        cut = t.rfind("}", 0, end)
        if cut < 0:
            return []  # nothing complete — an empty-but-valid trace
        candidate = t[:cut + 1].rstrip().rstrip(",") + "\n]"
        try:
            return json.loads(candidate)
        except json.JSONDecodeError:
            end = cut
    raise ValueError(f"{path}: could not repair truncated trace")


def _rank_of(path, events, fallback):
    """Rank identity: the clock_sync event's args win, else the
    ``.rank<N>.`` filename convention, else positional order."""
    for ev in events:
        if ev.get("name") == CLOCK_SYNC:
            rank = ev.get("args", {}).get("rank")
            if rank is not None:
                return int(rank)
    m = _RANK_RE.search(path)
    if m:
        return int(m.group(1))
    return fallback


def _clock_base_us(events):
    """Unix microseconds at this trace's ts=0, from clock_sync."""
    for ev in events:
        if ev.get("name") == CLOCK_SYNC:
            args = ev.get("args", {})
            if "unix_time_us" in args:
                return float(args["unix_time_us"]) - float(ev.get("ts", 0))
    return None


def merge_traces(paths, out_path=None):
    """Merge ``paths`` (repairing each) into one event list; write it to
    ``out_path`` when given. Returns the merged event list."""
    if not paths:
        raise ValueError("no trace files to merge")
    loaded = []
    for i, path in enumerate(paths):
        events = load_events(path)
        rank = _rank_of(path, events, fallback=i)
        loaded.append((rank, path, events, _clock_base_us(events)))
    known = [base for _, _, _, base in loaded if base is not None]
    zero_us = min(known) if known else 0.0

    merged = []
    for rank, path, events, base in loaded:
        shift = (base - zero_us) if base is not None else 0.0
        named = False
        for ev in events:
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            if ev.get("ph") in ("s", "t", "f") and "id" in ev \
                    and ev.get("cat") != GLOBAL_FLOW_CAT:
                # flow ids are per-rank counters; Chrome binds s/t/f
                # globally by (cat, id), so un-namespaced ids would draw
                # bogus cross-rank arrows. GLOBAL_FLOW_CAT ids are
                # allocated fleet-wide and WANT to cross pids (a
                # re-dispatched request's hop arrow).
                ev["id"] = int(ev["id"]) + rank * 1_000_000
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                named = True
            merged.append(ev)
        if not named:
            merged.append({"name": "process_name", "ph": "M", "pid": rank,
                           "args": {"name": f"rank {rank}"}})
            merged.append({"name": "process_sort_index", "ph": "M",
                           "pid": rank, "args": {"sort_index": rank}})
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged


def expand_inputs(inputs):
    """Expand globs (the launcher shell may not have) and dedupe."""
    paths = []
    for item in inputs:
        hits = sorted(_glob.glob(item)) if any(c in item for c in "*?[") \
            else [item]
        for h in hits:
            if h not in paths:
                paths.append(h)
    return paths


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.merge",
        description="Merge per-rank horovod_tpu Chrome traces into one "
                    "Perfetto-loadable trace with aligned clocks.")
    parser.add_argument("-o", "--output", required=True,
                        help="merged trace output path")
    parser.add_argument("traces", nargs="+",
                        help="per-rank trace files (globs ok)")
    args = parser.parse_args(argv)
    paths = expand_inputs(args.traces)
    if not paths:
        print("merge-timeline: no input traces matched", file=sys.stderr)
        return 1
    events = merge_traces(paths, args.output)
    print(f"merged {len(paths)} trace(s), {len(events)} events "
          f"-> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
