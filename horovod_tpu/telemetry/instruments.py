"""The framework's standard instrument catalogue.

One module owns every built-in metric name so the Prometheus scrape, the
KV heartbeat snapshot, the bench ``telemetry`` block and the docs
catalogue (docs/OBSERVABILITY.md) cannot drift apart. Subsystems call
the ``record_*`` helpers; nothing else hardcodes a metric name.

Enablement: instrumentation that would CHANGE a compiled program (the
grad-norm output in ``training.make_train_step``) or add per-step host
work is gated on :func:`enabled` — on when a metrics endpoint is
configured (``HOROVOD_METRICS_PORT``) or ``HOROVOD_TELEMETRY=1``, so a
job that never asked for telemetry runs byte-identical programs.
Registry writes themselves are always safe to make (they are how the
elastic driver's launcher-side metrics work with no endpoint at all).
"""

import os
import time

from horovod_tpu.telemetry.registry import get_registry

# Names are canonically ``hvd_*``. The catalogue used to mix
# ``horovod_*`` (step/collective/elastic) and ``hvd_*`` (wire/ckpt/data)
# prefixes; the old names remain available for ONE release as scrape-
# time aliases (``LEGACY_ALIASES`` below, rendered by the registry with
# a deprecation HELP line) and are then removed — re-point dashboards at
# the ``hvd_*`` names (docs/OBSERVABILITY.md deprecation note).
# -- step / training plane --------------------------------------------------
STEP_TOTAL = "hvd_step_total"
STEP_SECONDS = "hvd_step_latency_seconds"
STEP_DISPATCH_SECONDS = "hvd_step_dispatch_seconds"
MICROBATCH_SECONDS = "hvd_microbatch_seconds"
EXAMPLES_TOTAL = "hvd_examples_total"
EXAMPLES_PER_SEC = "hvd_examples_per_second"
LOSS = "hvd_loss"
GRAD_NORM = "hvd_grad_norm"
# -- compilation ------------------------------------------------------------
COMPILE_CACHE_HITS = "hvd_compile_cache_hits_total"
COMPILE_CACHE_MISSES = "hvd_compile_cache_misses_total"
COMPILE_SECONDS = "hvd_compile_seconds_total"
# -- collectives / fusion ---------------------------------------------------
COLLECTIVE_CALLS = "hvd_collective_calls_total"
COLLECTIVE_BYTES = "hvd_collective_bytes_total"
COLLECTIVE_LOGICAL_BYTES = "hvd_collective_logical_bytes_total"
BUCKET_FILL_RATIO = "hvd_bucket_fill_ratio"
BUCKET_DISPATCH_SECONDS = "hvd_bucket_dispatch_seconds"
# -- wire compression (ops/compression.py + the fusion pipeline) ------------
WIRE_BYTES = "hvd_wire_bytes_total"
WIRE_LOGICAL_BYTES = "hvd_wire_logical_bytes_total"
WIRE_COMPRESSION_RATIO = "hvd_wire_compression_ratio"
# -- elastic ----------------------------------------------------------------
RENDEZVOUS_EPOCHS = "hvd_rendezvous_epochs_total"
BLACKLIST_HOSTS = "hvd_blacklist_hosts"
RECOVERY_SECONDS = "hvd_recovery_seconds"
STRAGGLER_RATIO = "hvd_straggler_step_time_ratio"
# -- preemption / graceful eviction (elastic/preempt.py, chaos soak) --------
PREEMPTIONS_TOTAL = "hvd_preemptions_total"
DRAIN_SECONDS = "hvd_drain_seconds"
GRACE_COMMIT_SECONDS = "hvd_grace_commit_seconds"
# -- stall inspector --------------------------------------------------------
STALLED_RANKS = "hvd_stalled_ranks"
# -- async sharded checkpointing (horovod_tpu/ckpt) -------------------------
CKPT_SAVE_SECONDS = "hvd_ckpt_save_seconds"
CKPT_BLOCKING_SECONDS = "hvd_ckpt_blocking_seconds"
CKPT_BYTES_WRITTEN = "hvd_ckpt_bytes_written"
CKPT_INFLIGHT = "hvd_ckpt_inflight"
# -- data plane (horovod_tpu/data prefetch loaders) -------------------------
DATA_WAIT_SECONDS = "hvd_data_wait_seconds"
DATA_QUEUE_DEPTH = "hvd_data_queue_depth"
DATA_BYTES_STAGED = "hvd_data_bytes_staged_total"
DATA_BATCHES = "hvd_data_batches_total"
DATA_LOAD_SECONDS = "hvd_data_load_seconds"
# -- serving plane (horovod_tpu/serve, docs/SERVING.md) ---------------------
SERVE_REQUESTS = "hvd_serve_requests_total"
SERVE_TOKENS = "hvd_serve_tokens_total"
SERVE_QUEUE_DEPTH = "hvd_serve_queue_depth"
SERVE_KV_BLOCKS = "hvd_serve_kv_blocks_in_use"
SERVE_TTFT_SECONDS = "hvd_serve_ttft_seconds"
SERVE_TTFT_ADMISSION_SECONDS = "hvd_serve_ttft_admission_seconds"
SERVE_INTER_TOKEN_SECONDS = "hvd_serve_inter_token_seconds"
SERVE_CACHED_PREFILL_TOKENS = "hvd_serve_cached_prefill_tokens_total"
SERVE_REPLICAS = "hvd_serve_replicas"
SERVE_REDISPATCH_TOTAL = "hvd_serve_redispatch_total"
SERVE_WEIGHT_SWAP_SECONDS = "hvd_serve_weight_swap_seconds"
# -- goodput ledger (telemetry/ledger.py, docs/OBSERVABILITY.md) ------------
TIME_SECONDS = "hvd_time_seconds_total"
GOODPUT_RATIO = "hvd_goodput_ratio"
# -- compiled-step X-ray (telemetry/xprof.py, hvd-doctor xray) --------------
XRAY_DEVICE_SECONDS = "hvd_xray_device_seconds"
XRAY_BUCKETED_FRACTION = "hvd_xray_bucketed_fraction"
XRAY_EXPOSED_SECONDS = "hvd_xray_exposed_collective_seconds"
XRAY_COLLECTIVE_GBPS = "hvd_xray_collective_bandwidth_gbps"
# -- process identity -------------------------------------------------------
BUILD_INFO = "hvd_build_info"

# canonical -> deprecated name, served as scrape-time duplicates for one
# release (the registry renders each aliased family twice)
LEGACY_ALIASES = {
    STEP_TOTAL: "horovod_step_total",
    STEP_SECONDS: "horovod_step_latency_seconds",
    STEP_DISPATCH_SECONDS: "horovod_step_dispatch_seconds",
    MICROBATCH_SECONDS: "horovod_microbatch_seconds",
    EXAMPLES_TOTAL: "horovod_examples_total",
    EXAMPLES_PER_SEC: "horovod_examples_per_second",
    LOSS: "horovod_loss",
    GRAD_NORM: "horovod_grad_norm",
    COMPILE_CACHE_HITS: "horovod_compile_cache_hits_total",
    COMPILE_CACHE_MISSES: "horovod_compile_cache_misses_total",
    COMPILE_SECONDS: "horovod_compile_seconds_total",
    COLLECTIVE_CALLS: "horovod_collective_calls_total",
    COLLECTIVE_BYTES: "horovod_collective_bytes_total",
    COLLECTIVE_LOGICAL_BYTES: "horovod_collective_logical_bytes_total",
    BUCKET_FILL_RATIO: "horovod_bucket_fill_ratio",
    BUCKET_DISPATCH_SECONDS: "horovod_bucket_dispatch_seconds",
    RENDEZVOUS_EPOCHS: "horovod_rendezvous_epochs_total",
    BLACKLIST_HOSTS: "horovod_blacklist_hosts",
    RECOVERY_SECONDS: "horovod_recovery_seconds",
    STRAGGLER_RATIO: "horovod_straggler_step_time_ratio",
    STALLED_RANKS: "horovod_stalled_ranks",
}

# every metric this framework registers, in catalogue order — the
# contract tests/test_telemetry.py enforces against the table in
# docs/OBSERVABILITY.md (both directions)
CATALOGUE = (
    STEP_TOTAL, STEP_SECONDS, STEP_DISPATCH_SECONDS, MICROBATCH_SECONDS,
    EXAMPLES_TOTAL, EXAMPLES_PER_SEC, LOSS, GRAD_NORM,
    COMPILE_CACHE_HITS, COMPILE_CACHE_MISSES, COMPILE_SECONDS,
    COLLECTIVE_CALLS, COLLECTIVE_BYTES, COLLECTIVE_LOGICAL_BYTES,
    WIRE_BYTES, WIRE_LOGICAL_BYTES, WIRE_COMPRESSION_RATIO,
    BUCKET_FILL_RATIO, BUCKET_DISPATCH_SECONDS,
    RENDEZVOUS_EPOCHS, BLACKLIST_HOSTS, RECOVERY_SECONDS, STRAGGLER_RATIO,
    PREEMPTIONS_TOTAL, DRAIN_SECONDS, GRACE_COMMIT_SECONDS,
    STALLED_RANKS,
    CKPT_BLOCKING_SECONDS, CKPT_SAVE_SECONDS, CKPT_BYTES_WRITTEN,
    CKPT_INFLIGHT,
    DATA_WAIT_SECONDS, DATA_LOAD_SECONDS, DATA_QUEUE_DEPTH,
    DATA_BYTES_STAGED, DATA_BATCHES,
    SERVE_REQUESTS, SERVE_TOKENS, SERVE_QUEUE_DEPTH, SERVE_KV_BLOCKS,
    SERVE_TTFT_SECONDS, SERVE_TTFT_ADMISSION_SECONDS,
    SERVE_INTER_TOKEN_SECONDS,
    SERVE_CACHED_PREFILL_TOKENS, SERVE_REPLICAS,
    SERVE_REDISPATCH_TOTAL, SERVE_WEIGHT_SWAP_SECONDS,
    TIME_SECONDS, GOODPUT_RATIO,
    XRAY_DEVICE_SECONDS, XRAY_BUCKETED_FRACTION,
    XRAY_EXPOSED_SECONDS, XRAY_COLLECTIVE_GBPS,
    BUILD_INFO,
)

# the default registry serves the legacy names on every scrape until the
# deprecation window closes
get_registry().install_aliases(LEGACY_ALIASES)


def enabled(env=None):
    """True when program-shaping / per-step instrumentation should be on."""
    env = env if env is not None else os.environ
    if env.get("HOROVOD_TELEMETRY", "").lower() not in ("", "0", "false",
                                                        "no", "off"):
        return True
    try:
        from horovod_tpu import basics
        cfg = basics._state.config
        if cfg is not None:
            return cfg.metrics_port is not None
    # hvd-lint: disable=HVD-EXCEPT -- init-order probe; the env fallback below answers
    except Exception:
        pass
    return env.get("HOROVOD_METRICS_PORT", "") != ""


class StepInstruments:
    """Per-train-step recorder shared by ``make_train_step`` wrappers and
    ``elastic_train_loop``. One instance per built step function; all
    instances feed the same registry families.

    Step *latency* is the wall time between successive step calls (in
    steady state the dispatch queue is full, so inter-call time IS the
    device step time); step *dispatch* is the time the jitted call itself
    held the host. Loss and grad-norm are stashed as device arrays and
    only read back when something scrapes (deferred gauges) — recording a
    step never forces a sync."""

    def __init__(self, registry=None, accum_steps=1):
        r = registry if registry is not None else get_registry()
        self.registry = r
        self._accum = max(1, accum_steps)
        self.steps = r.counter(STEP_TOTAL, "Completed train-step calls")
        self.examples = r.counter(EXAMPLES_TOTAL,
                                  "Examples consumed by train steps")
        self.step_seconds = r.histogram(
            STEP_SECONDS, "Wall time between successive train-step calls "
            "(steady-state device step time)")
        self.dispatch_seconds = r.histogram(
            STEP_DISPATCH_SECONDS,
            "Host time spent dispatching the compiled step")
        self.micro_seconds = r.histogram(
            MICROBATCH_SECONDS,
            "Per-microbatch share of the step wall time (step/accum)")
        self.examples_per_sec = r.gauge(
            EXAMPLES_PER_SEC, "Examples/sec from the last step interval")
        self.loss = r.gauge(LOSS, "Last step loss (deferred readback)")
        self.grad_norm = r.gauge(
            GRAD_NORM, "Gradient L2 norm of the last step "
            "(deferred readback; see docs/OBSERVABILITY.md for the "
            "per-path definition)")
        self._last_call = None

    def record_step(self, batch, dispatch_s, loss=None, grad_norm=None,
                    timeline=None, step_no=None):
        now = time.perf_counter()
        self.steps.inc()
        self.examples.inc(batch)
        self.dispatch_seconds.observe(dispatch_s)
        interval = None
        if self._last_call is not None:
            interval = now - self._last_call
            self.step_seconds.observe(interval)
            self.micro_seconds.observe(interval / self._accum)
            if interval > 0:
                self.examples_per_sec.set(batch / interval)
        self._last_call = now
        if loss is not None:
            self.loss.set_function(_deferred_scalar(loss))
        if grad_norm is not None:
            self.grad_norm.set_function(_deferred_scalar(grad_norm))
        if timeline is not None:
            if interval:  # same zero guard as the gauge above
                timeline.counter("step", {
                    "step_ms": round(interval * 1e3, 3),
                    "examples_per_sec": round(batch / interval, 1)})
            if step_no is not None:
                timeline.instant("STEP_DISPATCH",
                                 args={"step": int(step_no),
                                       "dispatch_ms":
                                           round(dispatch_s * 1e3, 3)})


def _deferred_scalar(x):
    """Collect-time readback of a (possibly device) scalar."""
    def read():
        try:
            import jax
            return float(jax.device_get(x))
        # hvd-lint: disable=HVD-EXCEPT -- deferred gauge read: NaN marks an unreadable device value
        except Exception:
            return float("nan")
    return read


# per-(metric, label) child handles, resolved once and reused — the
# cached-child discipline registry.py prescribes for hot callers (the
# eager path dispatches collectives per step)
_child_cache = {}


def _calls_child(op_name):
    child = _child_cache.get(("calls", op_name))
    if child is None:
        child = get_registry().counter(
            COLLECTIVE_CALLS, "Collective op dispatches (trace-time for "
            "compiled programs, per-call for eager)",
            label_names=("op",)).labels(op_name)
        _child_cache[("calls", op_name)] = child
    return child


def _bytes_child(op_name):
    child = _child_cache.get(("bytes", op_name))
    if child is None:
        child = get_registry().counter(
            COLLECTIVE_BYTES, "Wire bytes moved by collective dispatches "
            "(COMPRESSED width when a wire format is active)",
            label_names=("op",)).labels(op_name)
        _child_cache[("bytes", op_name)] = child
    return child


def _logical_bytes_child(op_name):
    child = _child_cache.get(("logical", op_name))
    if child is None:
        child = get_registry().counter(
            COLLECTIVE_LOGICAL_BYTES,
            "Uncompressed (logical) bytes behind each collective dispatch; "
            "equals " + COLLECTIVE_BYTES + " when no wire compression is "
            "active — the per-op compression ratio is logical/wire",
            label_names=("op",)).labels(op_name)
        _child_cache[("logical", op_name)] = child
    return child


def _wire_dtype_children(dtype_name):
    pair = _child_cache.get(("wire_dtype", dtype_name))
    if pair is None:
        r = get_registry()
        pair = (
            r.counter(WIRE_BYTES,
                      "Bytes actually put on the interconnect per LOGICAL "
                      "payload dtype (wire payload + quantizer scales; "
                      "non-float leaves ride at full width)",
                      label_names=("dtype",)).labels(dtype_name),
            r.counter(WIRE_LOGICAL_BYTES,
                      "Uncompressed bytes of the same payloads, per "
                      "logical dtype",
                      label_names=("dtype",)).labels(dtype_name),
        )
        _child_cache[("wire_dtype", dtype_name)] = pair
    return pair


_ratio_gauge_installed = False


def _ensure_ratio_gauge():
    """``hvd_wire_compression_ratio``: cumulative logical/wire byte ratio
    across every collective dispatch (1.0 = nothing compressed). Derived
    at collect time from the two counter families so it can never drift
    from them."""
    global _ratio_gauge_installed
    if _ratio_gauge_installed:
        return
    r = get_registry()

    def _total(fam):
        if fam is None:
            return 0.0
        s = fam.sample()
        return sum(s.values()) if isinstance(s, dict) else float(s)

    def ratio():
        w = _total(r.get(COLLECTIVE_BYTES))
        lg = _total(r.get(COLLECTIVE_LOGICAL_BYTES))
        return (lg / w) if w > 0 else 1.0

    r.gauge(WIRE_COMPRESSION_RATIO,
            "Cumulative logical/wire byte ratio over all collective "
            "dispatches (1.0 = uncompressed)").set_function(ratio)
    _ratio_gauge_installed = True


def _bucket_children(kind):
    pair = _child_cache.get(("bucket", kind))
    if pair is None:
        r = get_registry()
        pair = (
            r.histogram(BUCKET_FILL_RATIO, "Used fraction of each fusion "
                        "bucket's padded size",
                        buckets=tuple(i / 10 for i in range(1, 11)),
                        label_names=("kind",)).labels(kind),
            r.histogram(BUCKET_DISPATCH_SECONDS,
                        "Host time to pack+dispatch one bucket collective",
                        label_names=("kind",)).labels(kind),
        )
        _child_cache[("bucket", kind)] = pair
    return pair


def record_collective(op_name, nbytes, logical_nbytes=None):
    """Per-op call count + wire bytes. Called from the collective
    dispatch functions, i.e. at TRACE time on the compiled path (the
    counts describe the collectives baked into each compiled program)
    and per call on the eager path — docs/OBSERVABILITY.md explains how
    to read the two.

    ``nbytes`` is what actually crosses the interconnect (COMPRESSED
    width when a wire format is active); ``logical_nbytes`` is the
    uncompressed payload behind it (defaults to ``nbytes``) — the
    compression ratio is derivable from the two counters, and
    ``hvd_wire_compression_ratio`` pre-derives the cumulative one."""
    _calls_child(op_name).inc()
    _bytes_child(op_name).inc(max(0, int(nbytes)))
    _logical_bytes_child(op_name).inc(
        max(0, int(nbytes if logical_nbytes is None else logical_nbytes)))
    _ensure_ratio_gauge()


def record_compiled_collective(op_name, calls, nbytes, logical_nbytes=None):
    """Account collectives read off a COMPILED module (the GSPMD path —
    ``parallel/gspmd.record_compiled_collectives``): there is no Python
    dispatch to count per call, so the whole module's per-op totals are
    recorded at once, in the same ``hvd_collective_*`` families the
    per-dispatch path uses. Recorded once per compile — like the
    trace-time counters, the numbers describe one compiled step."""
    _calls_child(op_name).inc(max(0, int(calls)))
    _bytes_child(op_name).inc(max(0, int(nbytes)))
    _logical_bytes_child(op_name).inc(
        max(0, int(nbytes if logical_nbytes is None else logical_nbytes)))
    _ensure_ratio_gauge()


def record_bucket(kind, fill_ratio, nbytes, dispatch_s=None,
                  logical_nbytes=None, dtype=None):
    """Bucketed reduce-scatter/all-gather pipeline instrumentation.
    ``nbytes`` is wire width, ``logical_nbytes`` uncompressed width, and
    ``dtype`` the bucket's LOGICAL dtype — feeding the per-dtype
    logical-vs-wire accounting (non-float buckets are never narrowed, so
    their two counters advance in lockstep)."""
    fill, dispatch = _bucket_children(kind)
    fill.observe(fill_ratio)
    wire = max(0, int(nbytes))
    logical = max(0, int(nbytes if logical_nbytes is None
                         else logical_nbytes))
    _bytes_child(f"bucket_{kind}").inc(wire)
    _logical_bytes_child(f"bucket_{kind}").inc(logical)
    if dtype is not None:
        w_child, l_child = _wire_dtype_children(str(dtype))
        w_child.inc(wire)
        l_child.inc(logical)
    _ensure_ratio_gauge()
    if dispatch_s is not None:
        dispatch.observe(dispatch_s)


def record_xray(summary, registry=None):
    """Mirror a compiled-step X-ray summary (``telemetry/xprof.py``)
    into the ``hvd_xray_*`` gauge family so the last capture's
    attribution rides every scrape: per-category device seconds (idle
    included), the bucketed-fraction honesty gate, and per-collective
    exposed seconds + effective exchange bandwidth. Gauges, not
    counters — each capture REPLACES the previous one's values (an
    X-ray is a snapshot of K steps, not a running total)."""
    r = registry if registry is not None else get_registry()
    dev = r.gauge(XRAY_DEVICE_SECONDS,
                  "Device time per op category over the last X-ray "
                  "capture (K compiled steps)",
                  label_names=("category",))
    for cat, sec in summary.get("device_seconds", {}).items():
        dev.labels(cat).set(sec)
    r.gauge(XRAY_BUCKETED_FRACTION,
            "Share of last-capture device time the X-ray classifier "
            "could name (1 - unattributed; gated at 0.95 by "
            "bench.py --spmd)").set(summary.get("bucketed_fraction", 0.0))
    exposed = r.gauge(XRAY_EXPOSED_SECONDS,
                      "Collective in-flight time NOT hidden behind "
                      "compute over the last X-ray capture",
                      label_names=("op",))
    gbps = r.gauge(XRAY_COLLECTIVE_GBPS,
                   "Effective exchange bandwidth per collective over "
                   "the last X-ray capture (aggregate HLO bytes / "
                   "in-flight seconds)",
                   label_names=("op",))
    for op, slot in summary.get("collectives", {}).items():
        exposed.labels(op).set(slot.get("exposed_seconds", 0.0))
        if "effective_gbps" in slot:
            gbps.labels(op).set(slot["effective_gbps"])


class CkptInstruments:
    """The checkpoint subsystem's four instruments, resolved once per
    ``AsyncCheckpointer``: end-to-end save latency (snapshot through
    manifest commit), the training-thread stall alone (snapshot + any
    in-flight-budget wait — the number the async design minimizes),
    cumulative shard bytes, and the current in-flight save count."""

    def __init__(self, registry=None):
        r = registry if registry is not None else get_registry()
        self.save_seconds = r.histogram(
            CKPT_SAVE_SECONDS,
            "End-to-end checkpoint save seconds (snapshot -> shard write "
            "-> manifest commit), overlapped with training")
        self.blocking_seconds = r.histogram(
            CKPT_BLOCKING_SECONDS,
            "Seconds the TRAINING thread was blocked per save (device->"
            "host snapshot + in-flight-budget wait)")
        self.bytes_written = r.counter(
            CKPT_BYTES_WRITTEN, "Checkpoint shard bytes written by this "
            "rank (serialized msgpack, pre-filesystem)")
        self.inflight = r.gauge(
            CKPT_INFLIGHT, "Checkpoint saves snapshotted but not yet "
            "manifest-committed")


def ckpt_instruments(registry=None):
    return CkptInstruments(registry)


class DataInstruments:
    """The prefetch loader's instruments (docs/DATA.md): the seconds the
    TRAINING thread blocked waiting for a batch (the number the prefetch
    design minimizes — in a healthy pipeline it is ~0 and step time is
    pure compute), the producer-side assembly+staging time per batch,
    the prefetch queue depth after each fetch (persistently 0 = the
    producer can't keep up; ~depth = compute-bound, the good case), and
    the cumulative batches / bytes staged onto device."""

    def __init__(self, registry=None):
        r = registry if registry is not None else get_registry()
        self.wait_seconds = r.histogram(
            DATA_WAIT_SECONDS,
            "Seconds the training thread blocked waiting for the next "
            "batch (0 when the prefetch queue had one ready)")
        self.load_seconds = r.histogram(
            DATA_LOAD_SECONDS,
            "Producer-thread seconds to assemble + stage one batch "
            "(source gather, host->device placement)")
        self.queue_depth = r.gauge(
            DATA_QUEUE_DEPTH,
            "Prefetched batches still queued right after a fetch "
            "(0 persistently = input-bound, ~depth = compute-bound)")
        self.bytes_staged = r.counter(
            DATA_BYTES_STAGED,
            "Cumulative bytes of batch data staged by the prefetch "
            "producer (host numpy width, pre-placement)")
        self.batches = r.counter(
            DATA_BATCHES, "Batches delivered to the training thread")


def data_instruments(registry=None):
    return DataInstruments(registry)


class ServeInstruments:
    """The inference server's request-level instruments
    (docs/SERVING.md, docs/OBSERVABILITY.md "Serving plane"): request
    lifecycle counts by event, generated-token throughput, scheduler
    queue depth, paged-KV pool occupancy, prefix-cache hits, and the
    two latencies a serving SLO is written against —
    time-to-first-token (arrival → first streamed token: queueing +
    prefill) and inter-token latency (the steady-state decode
    cadence).

    ``replica`` labels the per-engine GAUGES (queue depth, KV
    occupancy): a fleet's replicas share one registry, and unlabeled
    gauges would clobber each other on every scheduler tick. Counters
    and histograms stay fleet-wide families (monotonic sums aggregate
    correctly)."""

    def __init__(self, registry=None, replica="default"):
        r = registry if registry is not None else get_registry()
        self.registry = r
        self.replica = str(replica)
        self._requests = r.counter(
            SERVE_REQUESTS,
            "Generate requests by lifecycle event (submitted / "
            "completed / failed)", label_names=("event",))
        self.submitted = self._requests.labels("submitted")
        self.completed = self._requests.labels("completed")
        self.failed = self._requests.labels("failed")
        self.tokens = r.counter(
            SERVE_TOKENS, "Tokens generated and streamed to clients")
        self.cached_prefill_tokens = r.counter(
            SERVE_CACHED_PREFILL_TOKENS,
            "Prompt tokens whose prefill was skipped via prefix-cache "
            "block reuse (kvcache.PrefixCache)")
        self.queue_depth = r.gauge(
            SERVE_QUEUE_DEPTH,
            "Requests admitted-pending (queued behind KV blocks or "
            "batch slots), per engine replica",
            label_names=("replica",)).labels(self.replica)
        self.kv_blocks = r.gauge(
            SERVE_KV_BLOCKS, "Paged-KV pool blocks currently allocated "
            "to live sequences, per engine replica",
            label_names=("replica",)).labels(self.replica)
        self.ttft_seconds = r.histogram(
            SERVE_TTFT_SECONDS,
            "Time to first token: request arrival -> first streamed "
            "token (queueing + prefill)")
        self.ttft_admission_seconds = r.histogram(
            SERVE_TTFT_ADMISSION_SECONDS,
            "Time to first token from KV admission -> first streamed "
            "token (prefill only; the arrival-based histogram folds "
            "queue wait in, this one separates it)")
        self.inter_token_seconds = r.histogram(
            SERVE_INTER_TOKEN_SECONDS,
            "Gap between successive streamed tokens of one request "
            "(steady-state decode cadence)",
            buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5,
                     1.0, 2.5))
        self.weight_swap_seconds = serve_weight_swap_histogram(r)


def serve_instruments(registry=None, replica="default"):
    return ServeInstruments(registry, replica=replica)


def serve_replicas_gauge(registry=None):
    """The one declaration of ``hvd_serve_replicas`` — fleet replica
    counts by state (``ready`` / ``draining`` / ``dead``), recorded by
    the fleet router (serve/fleet/router.py)."""
    r = registry if registry is not None else get_registry()
    return r.gauge(SERVE_REPLICAS,
                   "Serve-fleet replicas by state (ready / draining / "
                   "dead)", label_names=("state",))


def serve_redispatch_counter(registry=None):
    """The one declaration of ``hvd_serve_redispatch_total`` — streams
    cut by a replica eviction and continued on a survivor
    (serve/fleet/router.py zero-drop re-dispatch hops)."""
    r = registry if registry is not None else get_registry()
    return r.counter(
        SERVE_REDISPATCH_TOTAL,
        "Streams cut mid-generation and re-dispatched onto a surviving "
        "replica (each count is one hop)")


def serve_weight_swap_histogram(registry=None):
    """The one declaration of ``hvd_serve_weight_swap_seconds``, shared
    by the engine (in-step staged-swap application) and the router (the
    per-replica drain -> stage -> swap -> ready rolling-reload window) so
    both record into one family."""
    r = registry if registry is not None else get_registry()
    return r.histogram(
        SERVE_WEIGHT_SWAP_SECONDS,
        "Weight-swap stall windows: engine in-step staged-swap "
        "application and router per-replica rolling-reload "
        "(drain -> stage -> swap -> ready)",
        buckets=(.001, .005, .01, .05, .1, .5, 1.0, 5.0, 15.0, 60.0))


def build_info_labels(config=None):
    """The process's identity labels for ``hvd_build_info`` (and for the
    goodput report header): framework version, jax version, backend,
    world size. Values degrade to "unknown" rather than raising —
    identity must never break startup."""
    def safe(fn):
        try:
            return str(fn())
        # hvd-lint: disable=HVD-EXCEPT -- build-info labels are best-effort strings
        except Exception:
            return "unknown"

    def world():
        if config is not None and getattr(config, "size", None):
            return config.size
        return int(os.environ.get("HOROVOD_SIZE", "1"))

    def backend():
        import jax
        return jax.default_backend()

    def version():
        import horovod_tpu
        return horovod_tpu.__version__

    def jax_version():
        import jax
        return jax.__version__

    return {"version": safe(version), "jax": safe(jax_version),
            "backend": safe(backend), "world": safe(world)}


def build_info_gauge(config=None, registry=None):
    """Register the standard-practice ``hvd_build_info`` gauge: constant
    1 with the identity as labels, so every scrape (and every dump that
    embeds the labels) is self-describing."""
    r = registry if registry is not None else get_registry()
    labels = build_info_labels(config)
    g = r.gauge(BUILD_INFO,
                "Constant 1; the labels identify this build/process "
                "(framework version, jax version, backend, world size)",
                label_names=("version", "jax", "backend", "world"))
    g.labels(labels["version"], labels["jax"], labels["backend"],
             labels["world"]).set(1)
    return g


def stalled_ranks_gauge(registry=None):
    """The one declaration of ``hvd_stalled_ranks`` — the stall
    inspector records into it; ``runtime/services.py`` pre-registers it
    so scrapes expose 0 before (or without) an inspector."""
    r = registry if registry is not None else get_registry()
    return r.gauge(STALLED_RANKS,
                   "Ranks whose last progress is older than the stall "
                   "warning threshold")


def kv_snapshot(registry=None):
    """Compact per-rank snapshot for the elastic KV heartbeat path —
    just what the driver's cluster view needs (step progress, step-time
    quantiles, examples/sec, wire bytes), a few hundred bytes riding a
    channel that already exists."""
    r = registry if registry is not None else get_registry()
    out = {}
    steps = r.get(STEP_TOTAL)
    if steps is not None:
        out["step"] = steps.value
    hist = r.get(STEP_SECONDS)
    if hist is not None and hist.count:
        out["step_seconds_p50"] = hist.quantile(0.5)
        out["step_seconds_p90"] = hist.quantile(0.9)
    eps = r.get(EXAMPLES_PER_SEC)
    if eps is not None:
        out["examples_per_sec"] = eps.value
    cbytes = r.get(COLLECTIVE_BYTES)
    if cbytes is not None:
        sample = cbytes.sample()
        if isinstance(sample, dict):
            out["collective_bytes"] = sum(sample.values())
    # the goodput ledger's phase totals (telemetry/ledger.py) ride the
    # same heartbeat so the driver's cluster_view can aggregate a live
    # fleet-wide goodput gauge — nonzero phases only, rounded compact
    tsec = r.get(TIME_SECONDS)
    if tsec is not None:
        sample = tsec.sample()
        if isinstance(sample, dict):
            phases = {lv[0]: round(v, 3) for lv, v in sample.items()
                      if v > 0}
            if phases:
                out["goodput"] = phases
    return out


_compile_listener_installed = False


def install_compile_listeners():
    """Count jax compilation-cache hits/misses and compile seconds via
    ``jax.monitoring`` events. Idempotent; silently unavailable on jax
    builds without the monitoring hooks."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    try:
        from jax import monitoring
    # hvd-lint: disable=HVD-EXCEPT -- jax.monitoring absent on this version
    except Exception:
        return
    r = get_registry()
    hits = r.counter(COMPILE_CACHE_HITS,
                     "jax compilation-cache hits this process")
    misses = r.counter(COMPILE_CACHE_MISSES,
                       "jax compilation-cache misses this process")
    compile_s = r.counter(COMPILE_SECONDS,
                          "Cumulative seconds spent in XLA compilation")

    def on_event(event, **kwargs):
        # a telemetry listener must NEVER throw into jax's dispatch path
        try:
            if "cache_hit" in event or event.endswith("cache_hits"):
                hits.inc()
            elif "cache_miss" in event or event.endswith("cache_misses"):
                misses.inc()
        # hvd-lint: disable=HVD-EXCEPT -- a listener must never break compilation
        except Exception:
            pass

    def on_duration(event, duration, **kwargs):
        try:
            # some jax events report negative/relative durations; only
            # positive compile times are meaningful to accumulate
            if "compil" in event and duration > 0:
                compile_s.inc(duration)
                # the goodput ledger books compilation out of the step
                # interval it lands in (first dispatch), so a compile-
                # heavy run cannot masquerade as compute
                from horovod_tpu.telemetry import ledger as ledger_lib
                ledger_lib.get_ledger().charge("compile", duration)
        # hvd-lint: disable=HVD-EXCEPT -- a listener must never break compilation
        except Exception:
            pass

    try:
        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
        _compile_listener_installed = True
    # hvd-lint: disable=HVD-EXCEPT -- monitoring registration is optional
    except Exception:
        pass
