"""The goodput ledger: run-level time attribution.

The telemetry plane records *events* and the flight recorder records
*forensics*; this module accounts for *time*. A per-rank
:class:`TimeLedger` classifies every wall-clock second of a run into
exclusive phases, so "where did my time go" has a number instead of a
guess — and ROADMAP item 5's "<5% goodput loss on preemptible capacity"
claim becomes testable.

Phases (exclusive — each second lands in exactly one):

* ``compute``             — the residual of each train-step interval
  after the explicitly-measured stalls below are subtracted: the time
  the accelerator had work. Collectives *hidden* behind the step
  (the compiled overlap pipeline) are compute by design — only exposed
  dispatch time is charged separately.
* ``exposed_collective``  — host time spent dispatching eager
  collectives (time the step could not hide). Under ``spmd=True`` this
  phase is STRUCTURALLY zero — the collectives are compiled into the
  step and their time books as ``compute``; the step wrappers call
  :meth:`TimeLedger.note_compiled_path` so snapshots/dumps carry a
  ``compiled_path`` flag and the report annotates the zero instead of
  implying "no exposed comms" (run ``hvd-doctor xray`` for the
  device-side split).
* ``data_wait``           — the training thread blocked on the input
  pipeline (``hvd_data_wait_seconds``'s source, charged here too).
* ``ckpt_stall``          — the blocking portion of checkpoint saves
  (snapshot + budget wait + any flush the training thread sat in).
* ``compile``             — XLA compilation (jax.monitoring durations).
* ``rendezvous_recovery`` — elastic recovery: rollback, restore from
  checkpoint, re-rendezvous sync.
* ``preemption``          — planned-churn cost: the graceful-eviction
  window (bounded grace commit + doomed-host announcement) when a spot
  notice / SIGTERM evicts this rank (``elastic/preempt.py``), and the
  scripted eviction spans of ``bench.py --churn``.
* ``stall_idle``          — unattributed gaps longer than
  ``IDLE_THRESHOLD_S`` settled outside a step (the job was parked and
  nothing claimed the time — the "something is wrong" bucket).
* ``overhead``            — small unattributed non-step gaps (host
  bookkeeping between phases).

Mechanics: subsystems ``charge(phase, seconds)`` the stalls they
measure anyway; the train-step wrapper calls ``settle_step()`` after
each step, which closes the interval since the previous settle and
books the residual as ``compute``. ``settle_idle()`` (scrape/shutdown
path) books a non-step residual as ``stall_idle``/``overhead``.
Charges are clipped to the interval they fall in, so the phase sum can
never exceed wall time; the remainder of an *unfinished* interval shows
up as ``unattributed_seconds`` in a live snapshot and collapses to ~0
after a final settle (bench.py enforces <2%).

The ledger is pure host-side bookkeeping: it never touches traced
code, so compiled programs are byte-identical with it on or off
(``HOROVOD_GOODPUT=0`` disables it), and a settle is a few dict adds —
well under the 2% step-overhead budget the plane already meets.

Registry mirror: ``hvd_time_seconds_total{phase=...}`` counters and the
``hvd_goodput_ratio`` gauge (compute / attributed wall) update at every
settle, ride the KV heartbeat snapshots (``instruments.kv_snapshot``)
into the elastic driver's fleet view, and land in every BENCH json.
``write_dump()`` drops ``goodput.rank<r>.json`` next to the
flight-recorder dumps at shutdown; ``telemetry/report.py`` (and
``hvd-doctor perf``) aggregates them into the end-of-run report.
"""

import json
import logging
import os
import threading
import time

logger = logging.getLogger("horovod_tpu")

PHASES = ("compute", "exposed_collective", "data_wait", "ckpt_stall",
          "compile", "rendezvous_recovery", "preemption", "stall_idle",
          "overhead")

# an unattributed non-step gap at least this long is a stall, not
# bookkeeping overhead
IDLE_THRESHOLD_S = 0.5

DUMP_PREFIX = "goodput.rank"


def dominant_sink(phases):
    """The largest non-compute phase of a ``{phase: seconds}`` mapping —
    ``(phase, seconds)``, or ``(None, 0.0)`` when nothing non-compute
    was charged. The ONE sink-naming policy, shared by the live ledger
    and the end-of-run report (telemetry/report.py)."""
    sinks = {p: s for p, s in phases.items() if p != "compute" and s > 0}
    if not sinks:
        return None, 0.0
    phase = max(sinks, key=sinks.get)
    return phase, sinks[phase]


def enabled(env=None):
    """Ledger on/off (default ON — it is host-side floats only; the
    compiled program is identical either way)."""
    env = env if env is not None else os.environ
    return env.get("HOROVOD_GOODPUT", "1").lower() not in (
        "0", "false", "no", "off")


class _Bracket:
    """One open blocking-phase span (``TimeLedger.phase``)."""

    __slots__ = ("label", "charge_phase", "health", "opened", "accounted",
                 "inner")

    def __init__(self, label, charge_phase, health, now):
        self.label = label
        self.charge_phase = charge_phase
        self.health = health
        self.opened = now
        self.accounted = now  # everything before this is already booked
        self.inner = 0.0      # seconds sub-charges claimed inside the span


class _PhaseContext:
    def __init__(self, ledger, label, charge_phase, health):
        self._ledger = ledger
        self._label = label
        self._charge = charge_phase
        self._health = health
        self._bracket = None

    def __enter__(self):
        self._bracket = self._ledger._open_bracket(
            self._label, self._charge, self._health)
        return self

    def __exit__(self, *exc):
        self._ledger._close_bracket(self._bracket)
        return False


class TimeLedger:
    """Per-rank exclusive-phase time accounting (module docstring)."""

    def __init__(self, clock=time.perf_counter, registry=None,
                 enabled=None, idle_threshold=IDLE_THRESHOLD_S):
        self._clock = clock
        self._registry = registry
        self.enabled = globals()["enabled"]() if enabled is None \
            else bool(enabled)
        self._idle_threshold = idle_threshold
        self._lock = threading.Lock()
        self._totals = {p: 0.0 for p in PHASES}
        self._pending = {p: 0.0 for p in PHASES}
        self._open = []          # stack of _Bracket
        self._t0 = None
        self._mark = None
        self._steps_settled = 0
        self._counters = None    # phase -> registry counter child
        self._gauge_installed = False
        self.compiled_path = False  # any spmd step settled this run

    # -- lifecycle ----------------------------------------------------------
    @property
    def started(self):
        return self._t0 is not None

    def start(self, now=None):
        """Open the run clock (idempotent; the first charge/settle does
        it implicitly)."""
        if not self.enabled:
            return
        with self._lock:
            self._start_locked(self._now(now))

    def _now(self, now=None):
        return self._clock() if now is None else now

    def _start_locked(self, now):
        if self._t0 is None:
            self._t0 = now
            self._mark = now
            self._install_instruments()

    # -- recording ----------------------------------------------------------
    def charge(self, phase, seconds, now=None):
        """Attribute ``seconds`` of the current (unsettled) interval to
        ``phase``. Called by the subsystems that measure their own
        stalls (loader wait, ckpt blocking, compile listener, eager
        dispatch). Thread-safe, allocation-free, no-op when disabled."""
        if not self.enabled or seconds <= 0:
            return
        if phase not in self._totals:
            phase = "overhead"
        with self._lock:
            self._start_locked(self._now(now))
            self._pending[phase] += seconds
            if self._open:
                # a measured sub-stall inside an open bracket (e.g. a
                # ckpt flush inside elastic recovery) claims its span —
                # the bracket books only what is left, keeping phases
                # exclusive
                self._open[-1].inner += seconds

    def note_compiled_path(self):
        """Mark this run as a compiled-path (GSPMD) run: its
        ``exposed_collective`` phase is structurally zero because the
        collectives live inside the compiled step. Snapshots, dumps and
        ``hvd-doctor perf`` annotate the zero instead of implying no
        exposed comms — the device-side answer is ``hvd-doctor xray``.
        Called by the spmd step wrappers; idempotent, a bool store."""
        self.compiled_path = True

    def phase(self, label, charge=None, health=True):
        """Context manager bracketing a blocking span: the elapsed time
        (minus any sub-charges made inside it) is charged to ``charge``
        (default: ``label`` when it names a phase, else ``overhead``).
        While open, ``health=True`` brackets flip ``/healthz`` to 503
        with ``label`` as the reported phase (docs/OBSERVABILITY.md)."""
        if charge is None:
            charge = label if label in PHASES else "overhead"
        return _PhaseContext(self, label, charge, health)

    def _open_bracket(self, label, charge_phase, health):
        # brackets open even when accounting is disabled: the /healthz
        # 503-during-transition contract rides on them and must not be
        # switched off by a perf-bookkeeping opt-out (HOROVOD_GOODPUT=0
        # only stops the time charges)
        with self._lock:
            now = self._now()
            if self.enabled:
                self._start_locked(now)
            b = _Bracket(label, charge_phase, health, now)
            self._open.append(b)
            return b

    def _close_bracket(self, bracket):
        if bracket is None:
            return
        with self._lock:
            now = self._now()
            try:
                self._open.remove(bracket)
            except ValueError:
                return
            if not self.enabled:
                return
            seg = max(0.0, now - bracket.accounted - bracket.inner)
            if seg > 0:
                self._pending[bracket.charge_phase] += seg
            if self._open:
                # the child's span is spoken for from the parent's point
                # of view — but only the part since the parent's own
                # accounting point (a settle mid-nesting already booked
                # the earlier part through both brackets)
                parent = self._open[-1]
                parent.inner += now - max(bracket.opened, parent.accounted)

    def _open_bracket_spans(self, now):
        """Unbooked seconds per open bracket, nested spans counted once:
        brackets form a stack (all opened on the training thread), so a
        child's span since the parent's accounting point is the child's
        to claim — the parent books only what is left. Returns
        ``[(bracket, seconds)]``; callers hold the lock."""
        out = []
        inner_claim = 0.0
        prev = None  # the bracket nested immediately inside this one
        for b in reversed(self._open):
            if prev is not None:
                inner_claim = now - max(prev.opened, b.accounted)
            out.append((b, max(0.0,
                               now - b.accounted - b.inner - inner_claim)))
            prev = b
        return out

    def active_health_label(self):
        """The innermost open health-relevant bracket label, or None —
        what ``/healthz`` reports (503) while a rank is parked in
        recovery/restore. Works with accounting disabled too: health
        semantics are not a perf-opt-out casualty."""
        with self._lock:
            for b in reversed(self._open):
                if b.health:
                    return b.label
        return None

    # -- settling -----------------------------------------------------------
    def settle_step(self, now=None):
        """Close the interval since the last settle at a train-step
        boundary: measured charges keep their phases, the residual is
        ``compute``. Called by the step wrappers after every step."""
        self._settle("step", now)

    def settle_idle(self, now=None):
        """Close the interval outside a step (scrape, shutdown, report):
        the residual is ``stall_idle`` when it exceeds the idle
        threshold, ``overhead`` otherwise."""
        self._settle("idle", now)

    def _settle(self, kind, now=None):
        if not self.enabled:
            return
        with self._lock:
            now = self._now(now)
            self._start_locked(now)
            # book the elapsed portion of any open bracket first so a
            # settle mid-recovery attributes the parked time correctly
            # (innermost-first: a nested child's span subtracts from its
            # parent instead of booking twice)
            for b, seg in self._open_bracket_spans(now):
                if seg > 0:
                    self._pending[b.charge_phase] += seg
                b.accounted = now
                b.inner = 0.0
            gap = max(0.0, now - self._mark)
            total = sum(self._pending.values())
            if total > gap:
                # overlapping measurements (nested stalls double-timed):
                # scale proportionally so the interval is explained
                # exactly once
                scale = (gap / total) if total > 0 else 0.0
                for p in self._pending:
                    self._pending[p] *= scale
                total = gap
            residual = gap - total
            if kind == "step":
                self._pending["compute"] += residual
                self._steps_settled += 1
            elif residual >= self._idle_threshold:
                self._pending["stall_idle"] += residual
            else:
                self._pending["overhead"] += residual
            for p, v in self._pending.items():
                if v > 0:
                    self._totals[p] += v
                    if self._counters is not None:
                        self._counters[p].inc(v)
                self._pending[p] = 0.0
            self._mark = now

    # -- reading ------------------------------------------------------------
    def snapshot(self, now=None):
        """Live view (does NOT settle): booked totals plus pending
        charges and open-bracket elapsed; ``unattributed_seconds`` is
        the tail of the current interval that has not been classified
        yet (→ ~0 after a final settle)."""
        with self._lock:
            now = self._now(now)
            phases = dict(self._totals)
            for p, v in self._pending.items():
                phases[p] += v
            if self.enabled:
                for b, seg in self._open_bracket_spans(now):
                    phases[b.charge_phase] += seg
            wall = (now - self._t0) if self._t0 is not None else 0.0
            attributed = sum(phases.values())
            if attributed > wall > 0:
                attributed = wall  # clock skew guard
            unattributed = max(0.0, wall - attributed)
            ratio = (phases["compute"] / attributed) if attributed > 0 \
                else 1.0
            return {
                "phases": phases,
                "wall_seconds": wall,
                "attributed_seconds": attributed,
                "unattributed_seconds": unattributed,
                "goodput_ratio": ratio,
                "steps": self._steps_settled,
                "compiled_path": self.compiled_path,
            }

    def finalize(self, now=None):
        """Final settle + snapshot: after this the snapshot explains
        (within float noise) every second since the run clock opened."""
        self.settle_idle(now)
        return self.snapshot(now)

    def dominant_sink(self, snapshot=None):
        """The largest non-compute phase of ``snapshot`` (or the live
        one) — ``(phase, seconds)``, or ``(None, 0.0)`` when nothing was
        charged."""
        snap = snapshot if snapshot is not None else self.snapshot()
        return dominant_sink(snap["phases"])

    # -- registry mirror ----------------------------------------------------
    def _install_instruments(self):
        if self._counters is not None:
            return
        try:
            from horovod_tpu.telemetry import instruments as _tele
            from horovod_tpu.telemetry.registry import get_registry
            reg = self._registry if self._registry is not None \
                else get_registry()
            fam = reg.counter(
                _tele.TIME_SECONDS,
                "Wall-clock seconds attributed to each goodput-ledger "
                "phase (exclusive; docs/OBSERVABILITY.md, 'Where did my "
                "time go')", label_names=("phase",))
            self._counters = {p: fam.labels(p) for p in PHASES}
            ledger = self

            def _ratio():
                return ledger.snapshot()["goodput_ratio"]

            reg.gauge(
                _tele.GOODPUT_RATIO,
                "compute / attributed wall time of this run's goodput "
                "ledger (1.0 = every attributed second was productive "
                "compute)").set_function(_ratio)
            self._gauge_installed = True
        # hvd-lint: disable=HVD-EXCEPT -- the ledger must never break training
        except Exception:  # the ledger must never break training
            logger.debug("goodput ledger: registry mirror unavailable",
                         exc_info=True)
            self._counters = None

    # -- dumps --------------------------------------------------------------
    def write_dump(self, directory, rank, extra=None):
        """Finalize and write ``goodput.rank<r>.json`` into
        ``directory`` (atomically) — the per-rank half of the end-of-run
        report (``telemetry/report.py`` / ``hvd-doctor perf``)."""
        if not self.enabled or not self.started:
            return None
        snap = self.finalize()
        payload = {
            "goodput": 1,
            "rank": int(rank),
            "wall_clock": time.time(),
            "phases": {p: round(s, 6) for p, s in snap["phases"].items()},
            "wall_seconds": round(snap["wall_seconds"], 6),
            "unattributed_seconds": round(snap["unattributed_seconds"], 6),
            "goodput_ratio": round(snap["goodput_ratio"], 6),
            "steps": snap["steps"],
            "compiled_path": snap["compiled_path"],
        }
        try:
            from horovod_tpu.telemetry import instruments as _tele
            payload["build_info"] = _tele.build_info_labels()
        # hvd-lint: disable=HVD-EXCEPT -- build info is optional dump metadata
        except Exception:
            pass
        if extra:
            payload.update(extra)
        path = os.path.join(directory, f"{DUMP_PREFIX}{int(rank)}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            logger.warning("goodput ledger: dump to %s failed", path,
                           exc_info=True)
            return None
        return path


# -- the process ledger ------------------------------------------------------

_ledger = None
_ledger_lock = threading.Lock()


def get_ledger():
    """The process-wide ledger (created lazily; ``reset_run()`` at
    ``hvd.init`` gives each run a fresh one)."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = TimeLedger()
        return _ledger


def reset_run(registry=None):
    """Open a fresh run ledger (called from ``runtime/services.start``
    so sequential init/shutdown cycles in one process each get their own
    attribution window). The registry counters stay cumulative — only
    the run-level snapshot resets."""
    global _ledger
    with _ledger_lock:
        _ledger = TimeLedger(registry=registry)
        if _ledger.enabled:
            _ledger.start()
    if _ledger.enabled:
        # compile time must reach the ledger even when no metrics
        # endpoint is configured (the listener records into the always-
        # safe registry either way)
        try:
            from horovod_tpu.telemetry import instruments as _tele
            _tele.install_compile_listeners()
        # hvd-lint: disable=HVD-EXCEPT -- compile listeners are optional
        except Exception:
            logger.debug("goodput ledger: compile listeners unavailable",
                         exc_info=True)
    return _ledger
