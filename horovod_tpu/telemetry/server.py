"""The per-rank HTTP observability plane (stdlib only).

Four endpoints, served from a daemon ``ThreadingHTTPServer`` that
``runtime/services.py`` starts alongside the controller/stall services
when ``HOROVOD_METRICS_PORT`` is configured:

* ``GET /metrics``  — the registry in Prometheus text format,
* ``GET /healthz``  — liveness JSON (rank identity + step progress);
  200 while serving, **503** with a ``phase`` field while the rank is
  parked in an elastic transition (re-rendezvous, checkpoint restore),
* ``GET /flightrec`` — the flight recorder's current ring as JSON
  (``horovod_tpu.diag``); ``?dump=1`` also writes the on-disk
  ``flightrec.rank<r>.json`` — the on-demand black-box pull,
* ``GET /profile?seconds=N`` — on-demand ``jax.profiler`` device trace:
  starts a capture into ``HOROVOD_PROFILE_DIR`` (default
  ``/tmp/horovod_tpu_profile``), stops it after N seconds on a worker
  thread, responds immediately with the output directory. The worker
  then runs the capture through the compiled-step X-ray parser
  (``telemetry/xprof.py``) and drops an ``xray.rank<r>.json`` next to
  the trace, so the dump is never a bare capture nobody can read:
  ``?wait=1`` blocks the response until capture+parse finish and
  returns the attribution summary inline, ``?result=1`` fetches the
  last capture's summary, and ``hvd-doctor xray <dir>`` reads the same
  artifacts offline. Load the raw trace in TensorBoard/XProf or
  Perfetto and line it up with the host trace via
  docs/OBSERVABILITY.md.

Security note (docs/OBSERVABILITY.md): the server binds
``HOROVOD_METRICS_ADDR`` = 127.0.0.1 by default. The endpoints are
UNAUTHENTICATED — ``/profile`` writes to local disk on request — so bind
a non-loopback address only on networks where every peer is trusted
(the same trust model as the launcher's control plane).
"""

import json
import logging
import threading
import time
from urllib.parse import parse_qs, urlparse

from horovod_tpu.telemetry.registry import get_registry
from horovod_tpu.utils.httpd import HttpService, QuietHandler

logger = logging.getLogger("horovod_tpu")

DEFAULT_PROFILE_DIR = "/tmp/horovod_tpu_profile"


class MetricsServer(HttpService):
    """One rank's scrape endpoint. ``port=0`` binds an ephemeral port
    (the bound port is in ``.port`` after ``start()``). Built on the
    shared ``utils/httpd`` scaffolding (the serving frontend,
    ``serve/server.py``, is the other tenant)."""

    thread_name = "hvd_tpu_metrics"

    def __init__(self, addr="127.0.0.1", port=0, registry=None,
                 health_fn=None, profile_dir=None):
        super().__init__(addr=addr, port=port)
        self.registry = registry if registry is not None else get_registry()
        self._health_fn = health_fn
        self.profile_dir = profile_dir or DEFAULT_PROFILE_DIR
        self._profile_lock = threading.Lock()
        self._profile_active = False
        self._profile_thread = None
        self._profile_cancel = threading.Event()
        self._profile_summary = None  # last capture's X-ray attribution

    # -- profiling ----------------------------------------------------------
    def _start_profile(self, seconds):
        """Kick off a jax.profiler capture on a worker thread and return
        immediately (a cold profiler start can take >10 s — the HTTP
        handler must not block on it). One capture at a time; the guard
        holds until the capture is stopped and written. ``stop()``
        cancels a running capture and JOINS the thread — a profiler
        native call racing interpreter teardown segfaults the process,
        turning a clean worker exit into a blamed failure."""
        with self._profile_lock:
            if self._profile_active:
                return None  # already capturing
            self._profile_active = True
            self._profile_cancel.clear()

        def _capture():
            import jax
            try:
                jax.profiler.start_trace(self.profile_dir)
                self._profile_cancel.wait(seconds)
                jax.profiler.stop_trace()
                self._profile_summary = self._attribute_capture()
            # hvd-lint: disable=HVD-EXCEPT -- profiler capture is best-effort; the failure is logged
            except Exception:
                logger.warning("profile capture failed", exc_info=True)
            finally:
                with self._profile_lock:
                    self._profile_active = False

        self._profile_thread = threading.Thread(
            target=_capture, daemon=True, name="hvd_tpu_profile")
        self._profile_thread.start()
        return self.profile_dir

    def _attribute_capture(self):
        """Run the finished capture through the X-ray parser
        (``telemetry/xprof.py``): drops ``xray.rank<r>.json`` next to
        the trace for ``hvd-doctor xray`` and returns the attribution
        summary the HTTP response serves (``?wait=1`` / ``?result=1``).
        A torn or empty capture returns ``{"error": ...}``."""
        from horovod_tpu.telemetry import xprof
        try:
            summary = xprof.analyze_capture(self.profile_dir)
        except ValueError as e:
            return {"error": str(e)}
        try:
            from horovod_tpu import basics
            rank = basics.rank()
        # hvd-lint: disable=HVD-EXCEPT -- uninitialized runtime defaults to rank 0
        except Exception:
            rank = 0
        xprof.write_summary(summary,
                            summary.get("capture_dir", self.profile_dir),
                            rank=rank)
        return summary

    # -- server -------------------------------------------------------------
    def _handler_class(self):
        server = self

        class Handler(QuietHandler):
            log_name = "metrics"

            def do_GET(self):
                url = urlparse(self.path)
                try:
                    if url.path == "/metrics":
                        self._respond(
                            200, server.registry.render_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif url.path == "/healthz":
                        health = {"status": "ok"}
                        if server._health_fn is not None:
                            health.update(server._health_fn() or {})
                        # a rank parked in an elastic transition
                        # (re-rendezvous, checkpoint restore) is NOT
                        # healthy-and-serving: 503 with the phase in the
                        # body, so load balancers and probes drain it
                        # instead of routing to a wedged rank
                        code = 200 if health.get("status", "ok") == "ok" \
                            else 503
                        self._respond(code, json.dumps(health),
                                      "application/json")
                    elif url.path == "/flightrec":
                        from horovod_tpu.diag import recorder as flightrec
                        rec = flightrec.get_recorder()
                        if rec is None:
                            self._respond(404, json.dumps(
                                {"error": "no flight recorder installed "
                                          "(HOROVOD_FLIGHTREC)"}),
                                "application/json")
                        else:
                            q = parse_qs(url.query)
                            # ?dump=1 additionally writes the on-disk
                            # flightrec.rank<r>.json (on-demand black box)
                            if q.get("dump", ["0"])[0] not in ("0", ""):
                                rec.dump(reason="endpoint")
                            self._respond(
                                200, json.dumps(rec.snapshot()),
                                "application/json")
                    elif url.path == "/profile":
                        q = parse_qs(url.query)
                        if q.get("result", ["0"])[0] not in ("0", ""):
                            # the last capture's attribution, no new
                            # capture started
                            s = server._profile_summary
                            if s is None:
                                self._respond(404, json.dumps(
                                    {"error": "no finished capture; "
                                              "GET /profile?seconds=N "
                                              "first"}),
                                    "application/json")
                            else:
                                self._respond(200, json.dumps(
                                    {"output_dir": server.profile_dir,
                                     "summary": s}), "application/json")
                            return
                        seconds = float(q.get("seconds", ["3"])[0])
                        seconds = min(max(seconds, 0.1), 600.0)
                        wait = q.get("wait", ["0"])[0] not in ("0", "")
                        out = server._start_profile(seconds)
                        if out is None:
                            self._respond(409, json.dumps(
                                {"error": "a profile capture is already "
                                          "running"}), "application/json")
                        elif wait:
                            # block until capture + X-ray parse finish
                            # and return the attribution inline (the
                            # cold profiler start is why async is the
                            # default; opt into the wait explicitly)
                            server._profile_thread.join(
                                timeout=seconds + 120)
                            self._respond(200, json.dumps(
                                {"profiling_seconds": seconds,
                                 "output_dir": out,
                                 "summary": server._profile_summary}),
                                "application/json")
                        else:
                            self._respond(200, json.dumps(
                                {"profiling_seconds": seconds,
                                 "output_dir": out,
                                 "result": "/profile?result=1 after the "
                                           "capture finishes, or "
                                           "hvd-doctor xray on the dir"}),
                                "application/json")
                    else:
                        self._respond(404, "not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                # hvd-lint: disable=HVD-EXCEPT -- keep the plane up; the handler reports 500 below
                except Exception as e:  # keep the plane up, report the err
                    logger.warning("metrics endpoint %s failed: %s",
                                   url.path, e)
                    try:
                        self._respond(500, f"{e}\n", "text/plain")
                    # hvd-lint: disable=HVD-EXCEPT -- the client is gone; nothing left to report to
                    except Exception:
                        pass

        return Handler

    def start(self):
        port = super().start()
        logger.info("metrics endpoint on http://%s:%d/metrics",
                    self._addr, port)
        return port

    def stop(self):
        super().stop()
        if self._profile_thread is not None:
            # end any in-flight capture NOW and wait for the profiler's
            # native write to finish before the interpreter can exit
            self._profile_cancel.set()
            self._profile_thread.join(timeout=30)
            self._profile_thread = None
