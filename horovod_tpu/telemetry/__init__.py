"""Unified telemetry plane: metrics registry, Prometheus endpoint,
cross-rank trace merge, on-demand XLA profiling.

The reference's observability is four disconnected views (coordinator
Timeline, stall-inspector warnings, autotuner CSV, user prints). Here
one rank-local registry (``registry.py``) is fed by every subsystem and
exposed three ways: the ``/metrics``+``/healthz``+``/profile`` HTTP
plane (``server.py``), compact snapshots on the elastic KV heartbeat
path (cluster view + straggler flagging in ``elastic/driver.py``), and
Chrome-trace counter events merged across ranks (``merge.py`` +
``utils/timeline.py``). docs/OBSERVABILITY.md is the catalogue.
"""

from horovod_tpu.telemetry import instruments  # noqa: F401
from horovod_tpu.telemetry import ledger  # noqa: F401
from horovod_tpu.telemetry import report  # noqa: F401
from horovod_tpu.telemetry.instruments import (  # noqa: F401
    DataInstruments,
    StepInstruments,
    build_info_gauge,
    data_instruments,
    enabled,
    install_compile_listeners,
    record_bucket,
    record_collective,
)
from horovod_tpu.telemetry.ledger import TimeLedger, get_ledger  # noqa: F401
from horovod_tpu.telemetry.merge import load_events, merge_traces  # noqa: F401
from horovod_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from horovod_tpu.telemetry.server import MetricsServer  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "MetricsServer", "StepInstruments", "DataInstruments",
    "data_instruments", "enabled", "build_info_gauge",
    "install_compile_listeners", "record_collective", "record_bucket",
    "load_events", "merge_traces", "instruments", "ledger", "report",
    "TimeLedger", "get_ledger",
]
