"""Compiled-step X-ray: device-trace time attribution for the GSPMD
hot path.

The compiled step is a time black box to the host-side planes: the
goodput ledger books the whole dispatch as one ``compute`` lump
(collectives compiled into the program are *inside* the step — its
``exposed_collective`` phase is structurally zero under ``spmd=True``),
and ``parallel/gspmd.collective_bytes_from_hlo`` prices the compiled
collectives in **bytes** but says nothing about *time*. This module
answers "where did my compiled step go" from the framework's own
captures:

1. **Capture** — :func:`capture_steps` wraps K executions of the
   already-compiled AOT executable in a ``jax.profiler`` device trace
   (the same capture ``/profile?seconds=N`` takes). The step's compiled
   program is untouched — X-ray orchestration lives entirely outside
   the jit, so programs are byte-identical with it off.
2. **Parse** — :func:`analyze_capture` loads the TraceViewer JSON the
   profiler wrote (``plugins/profile/<run>/*.trace.json(.gz)``),
   identifies **device lanes** (on TPU: every lane of a ``/device:*``
   pid; on the CPU backend: lanes whose events carry an ``hlo_op``
   arg), and buckets device time by op category — each collective kind
   (the same :data:`~horovod_tpu.parallel.gspmd.COLLECTIVE_OPS`
   authority the HLO byte parser matches, async ``-start``/``-done``
   pairs included), ``matmul_conv``, ``fusion``, other HLO ops,
   host↔device ``copy`` traffic, executor ``runtime`` overhead, and
   ``idle`` (no device lane doing anything). Time attribution is
   innermost-wins self time, so a wrapper event never double-counts
   its children.
3. **Attribute** — exposed vs **overlapped** collective time from
   timeline overlap: each collective's in-flight window (sync event
   span, or ``-start``→``-done`` for the async pairs the
   latency-hiding scheduler emits) is intersected with the union of
   compute intervals across all device lanes; the uncovered remainder
   is *exposed* — time the device spent exchanging with nothing to
   hide behind. Joined against the compiled module's per-op byte
   accounting, each collective also gets an **effective exchange
   bandwidth** (aggregate bytes moved / aggregate in-flight seconds).

The honesty gate mirrors the goodput ledger's: ``bucketed_fraction``
is the share of device time (self time + idle) the classifier could
*name* — device-lane events matching no known category count as
``unattributed`` and push it down, so a new runtime/backend event
family degrades LOUDLY instead of silently vanishing
(``bench.py --spmd`` errors below :data:`BUCKETED_GATE`).

Surfaces: ``step.xray(k)`` on the GSPMD train steps (returns the
threaded state + this summary), ``hvd-doctor xray <dir>``
(``diag/xray.py``), the ``step_attribution`` block in
``bench.py --spmd``, ``/profile?seconds=N&wait=1`` on the metrics
server, and the ``hvd_xray_*`` gauge family
(docs/OBSERVABILITY.md, "Where did my compiled step go").
"""

import glob
import gzip
import json
import logging
import os

from horovod_tpu.parallel.gspmd import (COLLECTIVE_OPS, collective_kind,
                                        collective_label)

logger = logging.getLogger("horovod_tpu")

# every category a device-lane second can land in (idle is derived —
# window minus busy — but reported in the same table)
COLLECTIVE_CATEGORIES = tuple(collective_label(op)
                              for op in COLLECTIVE_OPS)
CATEGORIES = COLLECTIVE_CATEGORIES + (
    "matmul_conv", "fusion", "other_op", "copy", "runtime",
    "unattributed", "idle")

# categories whose intervals count as "compute the scheduler can hide a
# collective behind" for the exposed-vs-overlapped split
COMPUTE_CATEGORIES = ("matmul_conv", "fusion", "other_op")

# bench.py --spmd fails its step_attribution block below this
BUCKETED_GATE = 0.95

# executor / runtime event families KNOWN to ride device lanes without
# being HLO ops (XLA:CPU thunk executor, pjrt transpose plans, stream
# bookkeeping). Anything on a device lane matching neither an HLO
# category nor one of these is UNATTRIBUTED — the loud bucket.
RUNTIME_PREFIXES = (
    "ThunkExecutor", "ThreadpoolListener", "Transpose", "TransposePlan",
    "TfrtCpu", "PjRt", "Stream", "ExecuteThunks", "XlaModule",
    "RunId", "Barrier", "EventPool", "BFCAllocator",
)

_MATMUL_ROOTS = ("dot", "conv", "convolution", "gemm", "matmul",
                 "einsum", "cudnn", "cublas")
_COPY_ROOTS = ("copy", "copy-start", "copy-done", "infeed", "outfeed",
               "send", "send-done", "recv", "recv-done", "transfer",
               "dynamic-update-slice-start", "host",
               "d2d", "h2d", "d2h")

# a lane whose hlo-op events are at least this share of its events is a
# device executor lane; the host python thread also annotates a FEW
# dispatch events with hlo_op args (~1% of its events empirically) and
# must not drag its 99% host bookkeeping into device attribution, while
# the sparsest real executor lane observed is ~45% hlo
DEVICE_LANE_HLO_FRACTION = 0.1

SUMMARY_PREFIX = "xray.rank"
VERDICTS = ("comms-bound", "compute-bound", "overlap-broken",
            "copy-bound", "idle-bound", "empty-capture")

# verdict thresholds, as fractions of total attributed device time
# (self time + idle) — documented in docs/OBSERVABILITY.md's runbook
EXPOSED_COMMS_BOUND = 0.25   # exposed collective time alone
OVERLAP_BROKEN_COLL = 0.10   # collective window share where ...
OVERLAP_BROKEN_EXPOSED = 0.5 # ... this share of it being exposed is broken
COPY_BOUND = 0.15
IDLE_BOUND = 0.35


def _event_root(name):
    """``all-reduce-start.1`` → matching root; ``loop_fusion.2`` →
    ``loop_fusion``. HLO numbering is ``.N``; keep dashes/underscores
    (they are part of op names)."""
    return name.split(".", 1)[0].split(" ", 1)[0]


def classify_device_event(name, has_hlo_arg=False):
    """Category of one device-lane event by name (the trace twin of the
    HLO byte parser's op matching — collective kinds come from the ONE
    shared classifier in ``parallel/gspmd.py``)."""
    kind, _edge = collective_kind(name)
    if kind is not None:
        return collective_label(kind)
    root = _event_root(name)
    lower = root.lower()
    if any(lower.startswith(r) for r in _MATMUL_ROOTS):
        return "matmul_conv"
    if "fusion" in lower:
        return "fusion"
    if any(lower == r or lower.startswith(r + "-") or
           lower.startswith(r + "_") for r in _COPY_ROOTS):
        return "copy"
    if has_hlo_arg:
        # a real HLO op we have no special bucket for (reduce, tanh,
        # scatter, ...): compute, named honestly
        return "other_op"
    if any(root.startswith(p) for p in RUNTIME_PREFIXES):
        return "runtime"
    return "unattributed"


# -- trace loading -----------------------------------------------------------

def load_trace_file(path):
    """One TraceViewer JSON (gz or plain) → its ``traceEvents`` list.
    Torn/truncated captures raise ``ValueError`` with the path."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
        else:
            with open(path) as f:
                doc = json.load(f)
    except (OSError, EOFError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable trace {path}: {e}") from e
    events = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(events, list):
        raise ValueError(f"{path} has no traceEvents list")
    return events


def find_capture(profile_dir):
    """The NEWEST profiler run under ``profile_dir`` and its trace
    files: ``jax.profiler`` writes ``plugins/profile/<timestamp>/
    <host>.trace.json.gz`` per capture. Returns ``(run_dir, [paths])``
    or ``(None, [])`` when nothing was captured. ``profile_dir`` may
    also BE a run dir (or hold loose ``*.trace.json`` files)."""
    runs = sorted(glob.glob(os.path.join(
        glob.escape(profile_dir), "plugins", "profile", "*")))
    candidates = ([r for r in runs if os.path.isdir(r)] or [profile_dir])
    for run in reversed(candidates):
        paths = sorted(
            glob.glob(os.path.join(glob.escape(run), "*.trace.json.gz"))
            + glob.glob(os.path.join(glob.escape(run), "*.trace.json")))
        if paths:
            return run, paths
    return None, []


# -- attribution -------------------------------------------------------------

def _merge_intervals(intervals):
    """Sorted union of ``[(start, end)]`` — total covered length is
    ``sum(e - s)`` of the result."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1][1] = e
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _overlap_seconds(window, merged):
    """Length of ``window ∩ merged`` (merged = sorted disjoint)."""
    lo, hi = window
    covered = 0.0
    for s, e in merged:
        if e <= lo:
            continue
        if s >= hi:
            break
        covered += min(e, hi) - max(s, lo)
    return covered


def _self_times(lane_events):
    """Innermost-wins self time per event of ONE lane: each event's
    duration minus the spans of events nested inside it (a
    ``ThunkExecutor::Execute`` wrapper must not double-count the HLO
    ops it ran). Events are Chrome complete events; partial overlaps
    are clipped to the enclosing span. Returns ``[(event, self_s)]``."""
    evs = sorted(lane_events, key=lambda e: (e["ts"], -e["dur"]))
    out = []
    stack = []  # indices into out, open ancestry
    for ev in evs:
        start, end = ev["ts"], ev["ts"] + ev["dur"]
        while stack and out[stack[-1]][0]["ts"] + \
                out[stack[-1]][0]["dur"] <= start:
            stack.pop()
        if stack:
            parent = out[stack[-1]]
            p_end = parent[0]["ts"] + parent[0]["dur"]
            parent[1] -= max(0.0, min(end, p_end) - start)
        out.append([ev, float(ev["dur"])])
        stack.append(len(out) - 1)
    return [(ev, max(0.0, s)) for ev, s in out]


def _device_lanes(events):
    """Group raw trace events into device lanes. A pid whose
    ``process_name`` starts with ``/device:`` is a device (TPU/GPU
    backends — every lane of it counts); otherwise a ``(pid, tid)``
    lane is a device lane when any of its events carries an ``hlo_op``
    arg (the XLA:CPU executor threads). Returns ``{(pid, tid):
    [event]}`` with events normalized to ``{ts, dur, name, hlo}`` in
    SECONDS."""
    proc_names = {}
    thread_names = {}
    lanes = {}
    lane_hlo = {}
    for e in events:
        if not e or not isinstance(e, dict):
            continue  # profilers emit empty tail elements; torn dumps
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                proc_names[e.get("pid")] = (e.get("args") or {}).get(
                    "name", "")
            elif e.get("name") == "thread_name":
                thread_names[(e.get("pid"), e.get("tid"))] = \
                    (e.get("args") or {}).get("name", "")
            continue
        if e.get("ph") not in (None, "X") or "ts" not in e:
            continue
        try:
            ts = float(e["ts"]) * 1e-6
            dur = float(e.get("dur", 0.0)) * 1e-6
        except (TypeError, ValueError):
            continue
        key = (e.get("pid"), e.get("tid"))
        has_hlo = "hlo_op" in (e.get("args") or {})
        if has_hlo:
            lane_hlo[key] = lane_hlo.get(key, 0) + 1
        lanes.setdefault(key, []).append(
            {"ts": ts, "dur": max(0.0, dur),
             "name": str(e.get("name", "")), "hlo": has_hlo})
    device = {}
    for key, lane in lanes.items():
        if str(proc_names.get(key[0], "")).startswith("/device:"):
            device[key] = lane
            continue
        # the host python thread annotates a few dispatch events with
        # hlo_op args too — only a lane MOSTLY made of hlo events is an
        # executor lane
        if str(thread_names.get(key, "")) == "python":
            continue
        if lane_hlo.get(key, 0) >= DEVICE_LANE_HLO_FRACTION * len(lane):
            device[key] = lane
    return device


def _collective_windows(lane):
    """In-flight windows ``[(kind, start, end)]`` of one lane: a sync
    collective's window is its event span; an async ``-start`` pairs
    with the NEXT ``-done`` of the same kind on the lane (the
    latency-hiding scheduler's pattern), the window reaching from the
    start event's begin to the done event's end. An unpaired start
    (torn capture) degrades to its own event span."""
    out = []
    open_starts = {}  # kind -> event
    for ev in sorted(lane, key=lambda e: e["ts"]):
        kind, edge = collective_kind(ev["name"])
        if kind is None:
            continue
        if edge == "start":
            prev = open_starts.get(kind)
            if prev is not None:  # two opens, no done: close the first
                out.append((kind, prev["ts"], prev["ts"] + prev["dur"]))
            open_starts[kind] = ev
        elif edge == "done":
            start = open_starts.pop(kind, None)
            begin = start["ts"] if start is not None else ev["ts"]
            out.append((kind, begin, ev["ts"] + ev["dur"]))
        else:
            out.append((kind, ev["ts"], ev["ts"] + ev["dur"]))
    for kind, ev in open_starts.items():
        out.append((kind, ev["ts"], ev["ts"] + ev["dur"]))
    return out


def attribute(events, steps=None):
    """The X-ray summary of one capture's raw trace events (every host
    file concatenated): per-category device self-time, idle, the
    exposed-vs-overlapped split per collective kind, and the
    ``bucketed_fraction`` honesty gate. Pure function — the synthetic-
    fixture tests drive it without a profiler run."""
    lanes = _device_lanes(events)
    categories = {c: 0.0 for c in CATEGORIES}
    compute_intervals = []
    busy_intervals = []
    windows = []
    span_lo, span_hi = None, None
    for lane in lanes.values():
        for ev, self_s in _self_times(lane):
            cat = classify_device_event(ev["name"], ev["hlo"])
            categories[cat] += self_s
            end = ev["ts"] + ev["dur"]
            busy_intervals.append((ev["ts"], end))
            if cat in COMPUTE_CATEGORIES:
                compute_intervals.append((ev["ts"], end))
            span_lo = ev["ts"] if span_lo is None else min(span_lo,
                                                           ev["ts"])
            span_hi = end if span_hi is None else max(span_hi, end)
        windows.extend(_collective_windows(lane))
    window_seconds = (span_hi - span_lo) if span_lo is not None else 0.0
    busy = _merge_intervals(busy_intervals)
    busy_seconds = sum(e - s for s, e in busy)
    idle = max(0.0, window_seconds - busy_seconds)
    categories["idle"] = idle
    compute = _merge_intervals(compute_intervals)

    collectives = {}
    for kind, s, e in windows:
        slot = collectives.setdefault(collective_label(kind), {
            "seconds": 0.0, "exposed_seconds": 0.0,
            "overlapped_seconds": 0.0, "events": 0})
        dur = max(0.0, e - s)
        hidden = _overlap_seconds((s, e), compute)
        slot["seconds"] += dur
        slot["overlapped_seconds"] += hidden
        slot["exposed_seconds"] += max(0.0, dur - hidden)
        slot["events"] += 1

    total = sum(categories.values())
    bucketed = ((total - categories["unattributed"]) / total
                if total > 0 else 0.0)
    summary = {
        "xray": 1,
        "device_lanes": len(lanes),
        "window_seconds": round(window_seconds, 9),
        "busy_seconds": round(busy_seconds, 9),
        "device_seconds": {c: round(s, 9)
                           for c, s in categories.items()},
        "bucketed_fraction": round(bucketed, 6),
        "unattributed_seconds": round(categories["unattributed"], 9),
        "collectives": {k: {f: (round(v, 9) if f != "events" else v)
                            for f, v in slot.items()}
                        for k, slot in sorted(collectives.items())},
    }
    if steps is not None:
        summary["steps"] = int(steps)
    summary["verdict"] = verdict(summary)
    return summary


def verdict(summary):
    """Name the step's dominant sink from an attribution summary — the
    fix-it table in docs/OBSERVABILITY.md keys off these:

    * ``comms-bound``    — exposed collective time ≥ 25% of device time:
      the exchange itself is the wall, overlap cannot save it.
    * ``overlap-broken`` — collectives take ≥ 10% of device time and
      over half of it is exposed: the bytes are modest but the
      scheduler is not hiding them (ordering/donation/flag problem).
    * ``copy-bound``     — host↔device copies ≥ 15% (staging problem).
    * ``idle-bound``     — no device lane busy ≥ 35% of the window (the
      host is not feeding the devices; see the goodput ledger for
      which host phase ate it).
    * ``compute-bound``  — none of the above: the device spent its time
      in matmul/fusion compute, which is the healthy verdict.
    * ``empty-capture``  — no device events parsed at all."""
    cats = summary["device_seconds"]
    total = sum(cats.values())
    if total <= 0 or summary["device_lanes"] == 0:
        return "empty-capture"
    coll_total = sum(c["seconds"]
                     for c in summary["collectives"].values())
    exposed = sum(c["exposed_seconds"]
                  for c in summary["collectives"].values())
    if exposed / total >= EXPOSED_COMMS_BOUND:
        return "comms-bound"
    if coll_total / total >= OVERLAP_BROKEN_COLL \
            and coll_total > 0 \
            and exposed / coll_total >= OVERLAP_BROKEN_EXPOSED:
        return "overlap-broken"
    if cats.get("copy", 0.0) / total >= COPY_BOUND:
        return "copy-bound"
    if cats.get("idle", 0.0) / total >= IDLE_BOUND:
        return "idle-bound"
    return "compute-bound"


def dominant_sink(summary):
    """The largest device-time category of a summary —
    ``(category, seconds)``, with exposed collective time preferred
    over raw category time when it leads (the actionable number)."""
    cats = {c: s for c, s in summary["device_seconds"].items() if s > 0}
    if not cats:
        return None, 0.0
    cat = max(cats, key=cats.get)
    return cat, cats[cat]


def join_collective_bytes(summary, compiled_collectives, steps=None):
    """Join per-collective device time against the compiled module's
    byte accounting (``step.compiled_collectives`` /
    ``gspmd.collective_bytes_from_hlo``): each kind gains
    ``bytes_per_step`` (per device) and ``effective_gbps`` — aggregate
    bytes moved across all device lanes over the captured steps,
    divided by aggregate in-flight seconds. The byte keys accept both
    raw op names and ``spmd_``-prefixed telemetry labels."""
    if not compiled_collectives:
        return summary
    steps = steps if steps is not None else summary.get("steps") or 1
    lanes = max(1, summary.get("device_lanes", 1))
    by_label = {}
    for op, tot in compiled_collectives.items():
        name = op[5:] if op.startswith("spmd_") else op
        kind, _ = collective_kind(name)
        if kind is None:  # telemetry labels are underscore-form
            kind, _ = collective_kind(name.replace("_", "-"))
        if kind is None:
            continue
        slot = by_label.setdefault(collective_label(kind), 0)
        by_label[collective_label(kind)] = slot + int(
            tot.get("bytes", 0) if isinstance(tot, dict) else tot)
    for label, slot in summary["collectives"].items():
        nbytes = by_label.get(label)
        if nbytes is None:
            continue
        slot["bytes_per_step"] = nbytes
        if slot["seconds"] > 0:
            slot["effective_gbps"] = round(
                nbytes * steps * lanes / slot["seconds"] / 1e9, 3)
    return summary


# -- capture orchestration ---------------------------------------------------

def analyze_capture(profile_dir, steps=None):
    """Parse the newest profiler run under ``profile_dir`` into an
    attribution summary (all host trace files concatenated). Raises
    ``ValueError`` when no capture exists or every file is torn."""
    run, paths = find_capture(profile_dir)
    if not paths:
        raise ValueError(f"no trace capture under {profile_dir} "
                         "(expected plugins/profile/<run>/"
                         "*.trace.json[.gz])")
    events, errors = [], []
    for p in paths:
        try:
            events.extend(load_trace_file(p))
        except ValueError as e:
            errors.append(str(e))
    if not events and errors:
        raise ValueError("; ".join(errors))
    summary = attribute(events, steps=steps)
    summary["capture_dir"] = run
    if errors:
        summary["torn_files"] = errors
    return summary


def capture_steps(run_once, steps, profile_dir):
    """Run ``run_once(i)`` K times inside one ``jax.profiler`` trace
    into ``profile_dir``, forcing each iteration to TRUE completion
    (``utils.benchmarks.sync`` — a host readback; block_until_ready
    returns early through an async execution tunnel) so the device
    lanes hold exactly the K steps. Returns the last result."""
    import jax

    from horovod_tpu.utils.benchmarks import sync

    out = None
    jax.profiler.start_trace(profile_dir)
    try:
        for i in range(steps):
            out = run_once(i)
            sync(out)
    finally:
        jax.profiler.stop_trace()
    return out


def write_summary(summary, directory, rank=0):
    """Atomically drop ``xray.rank<r>.json`` into ``directory`` — the
    artifact ``hvd-doctor xray <dir>`` aggregates (the X-ray twin of
    the goodput ledger's ``goodput.rank<r>.json``)."""
    payload = dict(summary)
    payload["rank"] = int(rank)
    path = os.path.join(directory, f"{SUMMARY_PREFIX}{int(rank)}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        logger.warning("xray: summary dump to %s failed", path,
                       exc_info=True)
        return None
    return path


def xray_run(step_fn, state, step_args, k=3, profile_dir=None,
             compiled_collectives=None, rank=None):
    """The ``step.xray(k)`` engine: one warm call OUTSIDE the trace
    (so a first-shape AOT compile never pollutes the capture), then K
    traced steps, parse, join bytes, record the ``hvd_xray_*`` gauges
    and write the summary next to the capture. ``state`` threads
    through every call (the steps donate their inputs as usual) and
    comes back with the summary: ``(state, summary)``."""
    import tempfile

    if k < 1:
        raise ValueError(f"xray needs at least one step, got k={k}")
    if profile_dir is None:
        profile_dir = tempfile.mkdtemp(prefix="hvd_xray_")
    holder = [state]

    def run_once(_i):
        new_state, loss = step_fn(holder[0], *step_args)
        holder[0] = new_state
        return loss

    new_state, _ = step_fn(holder[0], *step_args)  # warm outside trace
    holder[0] = new_state
    capture_steps(run_once, k, profile_dir)
    summary = analyze_capture(profile_dir, steps=k)
    coll = (compiled_collectives() if callable(compiled_collectives)
            else compiled_collectives)
    join_collective_bytes(summary, coll, steps=k)
    try:
        from horovod_tpu.telemetry import instruments as _tele
        _tele.record_xray(summary)
    # hvd-lint: disable=HVD-EXCEPT -- gauge mirror is best-effort; the summary is the product
    except Exception:
        logger.debug("xray: gauge mirror unavailable", exc_info=True)
    if rank is None:
        try:
            from horovod_tpu import basics
            rank = basics.rank()
        # hvd-lint: disable=HVD-EXCEPT -- uninitialized runtime defaults to rank 0
        except Exception:
            rank = 0
    write_summary(summary, profile_dir, rank=rank)
    return holder[0], summary
