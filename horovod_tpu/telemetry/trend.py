"""Bench-trajectory tooling: diff the checked-in ``BENCH_*.json``
rounds and flag regressions.

The repo accumulates one ``BENCH_r<NN>*.json`` per perf round (nine and
counting — BENCH_NOTES.md narrates them) but had no tool that reads two
of them: "did round N regress round N-1" was eyeball work. This module
loads every round, extracts the comparable series (headline
throughput, ``step_ms_*`` medians, MFU, goodput ratio, serve tokens/s
and TTFT), and compares each metric's latest value against the
previous round that reported it — a change worse than
:data:`REGRESSION_THRESHOLD` in the metric's bad direction is a
REGRESSION row (and a nonzero exit from ``bench.py --compare``).

Round files come in two shapes and both are handled: the driver
wrapper ``{"cmd", "parsed": {...}, "rc", ...}`` (rounds 1–6, 9) and a
raw bench result dict (the serve/fleet rounds). Metric direction is
inferred from the name — ``*_ms``/``*_over_*`` are lower-is-better,
throughput/MFU/goodput higher-is-better — so a new bench key joins the
trend without registration.

CLI::

    bench.py --compare [--compare-threshold 5]
    python -m horovod_tpu.telemetry.trend [dir-or-files...] [--json]
"""

import argparse
import glob
import json
import os
import sys

# flag a change worse than this fraction in the bad direction
REGRESSION_THRESHOLD = 0.05

# substrings that make a metric lower-is-better; everything else
# numeric is treated as higher-is-better (throughput, MFU, goodput)
_LOWER_IS_BETTER = ("_ms", "ttft", "step_ms", "_over_", "latency",
                    "stall", "blocking", "unattributed")

# keys that are configuration/identity, never a perf series
_SKIP = ("devices", "repeats", "rc", "n", "per_chip_batch", "requests",
         "max_new_tokens", "max_slots", "prefill_chunk", "kv_block_size",
         "kv_pool_blocks", "kv_pool_mib", "kv_pool", "seq_len", "layers",
         "d_model", "heads", "vocab", "batch", "shared_prefix",
         "prompt_len_mean", "empirical_peak_matmul_n", "rate_rps",
         "steps", "lives", "events", "wall_clock", "wall_seconds",
         "lm_seq_len", "attributed_seconds")


def direction(name):
    """``-1`` when lower is better (latencies, parity ratios), ``+1``
    when higher is better (throughput, MFU, goodput)."""
    low = name.lower()
    if any(s in low for s in _LOWER_IS_BETTER):
        return -1
    return 1


def _flatten(doc, prefix="", out=None):
    out = {} if out is None else out
    for key, val in doc.items():
        if key.startswith("_") or key in _SKIP:
            continue
        name = f"{prefix}{key}"
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[name] = float(val)
        elif isinstance(val, dict) and key in ("goodput", "single",
                                               "fleet"):
            _flatten(val, prefix=f"{name}.", out=out)
    return out


def _flatten_scaling(doc):
    """The comparable series of one ``SCALING_*.json`` sweep
    (bench_scaling.py): per-world efficiency, step time, per-chip
    throughput and goodput, keyed ``scaling.<world>.<metric>`` so a
    bent curve shows up as a regressed per-world point. ``efficiency``
    carries no lower-is-better substring -> higher-is-better, exactly
    right."""
    out = {}
    for world in doc.get("worlds", ()):
        name = world.get("world")
        if not name:
            continue
        prefix = f"scaling.{name}."
        for key in ("efficiency", "img_per_sec_per_chip",
                    "step_ms_median"):
            if isinstance(world.get(key), (int, float)):
                out[prefix + key] = float(world[key])
        goodput = world.get("goodput") or {}
        for key in ("ratio", "unattributed_frac"):
            if isinstance(goodput.get(key), (int, float)):
                out[f"{prefix}goodput.{key}"] = float(goodput[key])
    return out


def extract_metrics(doc):
    """The comparable numeric series of one round document (wrapper
    unwrapped, nested goodput/serve blocks dotted in; scaling sweeps
    dotted per world)."""
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        return {}
    if doc.get("bench") == "scaling" or "efficiency_curve" in doc:
        return _flatten_scaling(doc)
    return _flatten(doc)


def find_rounds(paths=None):
    """Resolve ``paths`` (files, dirs, or None for the repo root this
    process runs in) to the sorted list of ``BENCH_*.json`` then
    ``SCALING_*.json`` files — name order IS round order
    (``BENCH_r01`` … ``BENCH_r09``, ``SCALING_r01`` …). Scaling sweeps
    sort after the bench rounds: their metric keys (``scaling.*``)
    never collide with bench keys, so interleaving order between the
    two families is irrelevant to the diff."""
    if not paths:
        paths = ["."]
    out = []
    for p in paths:
        if os.path.isdir(p):
            root = glob.escape(p)
            out.extend(sorted(glob.glob(os.path.join(root, "BENCH_*.json"))))
            out.extend(sorted(glob.glob(
                os.path.join(root, "SCALING_*.json"))))
        else:
            out.append(p)
    return out


def load_rounds(paths):
    """``[(round_name, metrics)]`` in round order; unreadable files are
    reported in the second return value, never silently dropped."""
    rounds, skipped = [], []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            skipped.append((path, str(e)))
            continue
        name = os.path.splitext(os.path.basename(path))[0]
        rounds.append((name, extract_metrics(doc)))
    return rounds, skipped


def compare(rounds, threshold=REGRESSION_THRESHOLD):
    """The trend report: for every metric two or more rounds share,
    the full series plus the latest-vs-previous delta, flagged as a
    regression when it moves more than ``threshold`` in the metric's
    bad direction. Pure function of the loaded rounds."""
    series = {}
    for name, metrics in rounds:
        for key, val in metrics.items():
            series.setdefault(key, []).append((name, val))
    report = {"rounds": [n for n, _m in rounds], "metrics": {},
              "regressions": []}
    for key in sorted(series):
        points = series[key]
        if len(points) < 2:
            continue
        (prev_round, prev), (last_round, last) = points[-2], points[-1]
        entry = {
            "series": {n: v for n, v in points},
            "previous": {"round": prev_round, "value": prev},
            "latest": {"round": last_round, "value": last},
        }
        if prev != 0:
            change = (last - prev) / abs(prev)
            entry["change_pct"] = round(100 * change, 2)
            worse = -direction(key) * change
            entry["regressed"] = bool(worse > threshold)
            if entry["regressed"]:
                report["regressions"].append(key)
        report["metrics"][key] = entry
    return report


def format_trend(report, threshold=REGRESSION_THRESHOLD):
    lines = []
    add = lines.append
    add("==== horovod_tpu bench trend " + "=" * 36)
    add(f"rounds: {', '.join(report['rounds'])}")
    for key, entry in report["metrics"].items():
        if "change_pct" not in entry:
            continue
        arrow = "REGRESSION" if entry.get("regressed") else (
            "ok" if abs(entry["change_pct"]) <= 100 * threshold
            else "improved")
        add(f"  {key:<44} {entry['previous']['value']:>12.3f} -> "
            f"{entry['latest']['value']:>12.3f}  "
            f"{entry['change_pct']:+7.2f}%  {arrow}  "
            f"({entry['previous']['round']} -> "
            f"{entry['latest']['round']})")
    if report["regressions"]:
        add(f"REGRESSIONS (> {threshold:.0%} worse): "
            + ", ".join(report["regressions"]))
    else:
        add(f"no metric regressed more than {threshold:.0%} between its "
            "last two rounds")
    add("=" * 66)
    return "\n".join(lines)


def run(paths=None, threshold=REGRESSION_THRESHOLD, stream=None):
    """Load, compare, print. Returns the report dict, or None when
    fewer than two rounds exist."""
    stream = stream or sys.stderr
    rounds, skipped = load_rounds(find_rounds(paths))
    for path, err in skipped:
        print(f"trend: skipping {path}: {err}", file=stream)
    if len(rounds) < 2:
        print(f"trend: need at least two BENCH_*.json rounds, found "
              f"{len(rounds)}", file=stream)
        return None
    report = compare(rounds, threshold=threshold)
    print(format_trend(report, threshold=threshold), file=stream)
    return report


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.trend",
        description="Diff the checked-in BENCH_*.json perf rounds and "
                    "flag >5% regressions (step_ms, MFU, goodput, "
                    "serve tokens/s).")
    p.add_argument("paths", nargs="*",
                   help="round files or directories holding "
                        "BENCH_*.json (default: current directory)")
    p.add_argument("--threshold", type=float,
                   default=100 * REGRESSION_THRESHOLD,
                   help="regression threshold in percent (default 5)")
    p.add_argument("--json", action="store_true",
                   help="print the trend report as JSON on stdout "
                        "(prose moves to stderr)")
    args = p.parse_args(argv)
    report = run(args.paths, threshold=args.threshold / 100.0,
                 stream=sys.stderr if args.json else sys.stdout)
    if report is None:
        return 2
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
