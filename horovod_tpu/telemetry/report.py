"""The end-of-run goodput report: aggregate per-rank ledgers, name the
dominant time sink.

    hvd-doctor perf <logdir>
    hvdrun --goodput-report <logdir>
    python -m horovod_tpu.telemetry.report <logdir>

Each rank's :class:`~horovod_tpu.telemetry.ledger.TimeLedger` writes a
``goodput.rank<r>.json`` next to the flight-recorder dumps at shutdown
(``runtime/services.stop``). This module loads them, sums the phase
ledgers fleet-wide, names the dominant non-compute sink per rank and
overall, and cross-checks each rank's accounted wall time against a
merged Chrome trace when one is present — the perf mirror of the desync
doctor's hang report.

``goodput_block()`` is the BENCH json contract: the same snapshot with
the *sum ≈ wall* invariant enforced — an unattributed gap above
``UNATTRIBUTED_TOLERANCE`` of wall raises :class:`GoodputInvariantError`
so a perf regression can never hide in unaccounted time.
"""

import argparse
import json
import os
import sys

from horovod_tpu.telemetry.ledger import (DUMP_PREFIX, PHASES,
                                          dominant_sink as _dominant_sink)

# the bench invariant: phases must explain all but this fraction of wall
UNATTRIBUTED_TOLERANCE = 0.02

# a trace whose span disagrees with the ledger wall by more than this is
# flagged in the report (clock domains differ; this is a sanity bound,
# not a precision check)
TRACE_SKEW_TOLERANCE = 0.25


class GoodputInvariantError(RuntimeError):
    """The phase sum failed to explain ~100% of wall time."""


def find_dumps(logdir):
    """All ``goodput.rank*.json`` paths under ``logdir`` (recursive —
    elastic jobs write per-epoch subdirectories)."""
    out = []
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if f.startswith(DUMP_PREFIX) and f.endswith(".json") \
                    and ".tmp" not in f:
                out.append(os.path.join(root, f))
    return sorted(out)


def load_dumps(logdir):
    """Parse dumps. A rank with multiple dumps (one per elastic *life*
    — each relaunched process writes its own, in its epoch's dump dir)
    is SUMMED across them: the lives cover disjoint wall-clock windows,
    and dropping the pre-kill ones would hide exactly the recovery cost
    this report exists to expose. Returns ``(dumps_by_rank, skipped)``;
    merged entries carry ``lives`` and the newest dump's identity."""
    dumps, skipped = {}, []
    for path in find_dumps(logdir):
        try:
            with open(path) as f:
                d = json.load(f)
            if not d.get("goodput"):
                raise ValueError("not a goodput-ledger dump")
        except (OSError, ValueError) as e:
            skipped.append((path, str(e)))
            continue
        d["_path"] = path
        d["lives"] = 1
        r = int(d.get("rank", -1))
        prev = dumps.get(r)
        if prev is None:
            dumps[r] = d
            continue
        newest, older = ((d, prev) if d.get("wall_clock", 0)
                         >= prev.get("wall_clock", 0) else (prev, d))
        merged = dict(newest)  # newest life's identity/build_info wins
        merged["phases"] = {
            p: (newest.get("phases", {}).get(p, 0.0)
                + older.get("phases", {}).get(p, 0.0))
            for p in set(newest.get("phases", {}))
            | set(older.get("phases", {}))}
        for key in ("wall_seconds", "unattributed_seconds", "steps",
                    "lives"):
            merged[key] = (newest.get(key) or 0) + (older.get(key) or 0)
        attributed = sum(merged["phases"].values())
        merged["goodput_ratio"] = (
            merged["phases"].get("compute", 0.0) / attributed
            if attributed else 1.0)
        dumps[r] = merged
    return dumps, skipped


def aggregate(dumps):
    """Build the report dict from ``{rank: dump}`` — per-rank and
    fleet-wide phase totals, goodput ratios, dominant sinks. Pure
    function of the dumps (unit-testable on synthesized ledgers)."""
    per_rank = {}
    fleet = {p: 0.0 for p in PHASES}
    fleet_wall = 0.0
    fleet_unattributed = 0.0
    for r in sorted(dumps):
        d = dumps[r]
        phases = {p: float(d.get("phases", {}).get(p, 0.0)) for p in PHASES}
        wall = float(d.get("wall_seconds", sum(phases.values())))
        sink, sink_s = _dominant_sink(phases)
        attributed = sum(phases.values())
        per_rank[r] = {
            "phases": phases,
            "wall_seconds": wall,
            "unattributed_seconds": float(
                d.get("unattributed_seconds", max(0.0, wall - attributed))),
            "goodput_ratio": float(d.get(
                "goodput_ratio",
                phases["compute"] / attributed if attributed else 1.0)),
            "dominant_sink": sink,
            "dominant_sink_seconds": sink_s,
            "steps": d.get("steps"),
            "build_info": d.get("build_info"),
            "compiled_path": bool(d.get("compiled_path")),
            "path": d.get("_path"),
        }
        for p in PHASES:
            fleet[p] += phases[p]
        fleet_wall += wall
        fleet_unattributed += per_rank[r]["unattributed_seconds"]
    f_attr = sum(fleet.values())
    f_sink, f_sink_s = _dominant_sink(fleet)
    return {
        "ranks": per_rank,
        "fleet": {
            "phases": fleet,
            "wall_seconds": fleet_wall,
            "unattributed_seconds": fleet_unattributed,
            "goodput_ratio": fleet["compute"] / f_attr if f_attr else 1.0,
            "dominant_sink": f_sink,
            "dominant_sink_seconds": f_sink_s,
            "compiled_path": any(i["compiled_path"]
                                 for i in per_rank.values()),
        },
    }


def crosscheck_trace(report, trace_path):
    """Sanity-check the ledger against a merged Chrome trace
    (``hvdrun --merge-timeline``): each rank's event span in the trace
    should be within :data:`TRACE_SKEW_TOLERANCE` of its accounted wall
    time. Annotates and returns ``report['trace_check']``."""
    from horovod_tpu.telemetry.merge import load_events
    spans = {}
    for ev in load_events(trace_path):
        try:
            pid, ts = int(ev["pid"]), float(ev["ts"])
        except (KeyError, TypeError, ValueError):
            continue
        lo, hi = spans.get(pid, (ts, ts))
        spans[pid] = (min(lo, ts), max(hi, ts))
    check = {"trace": trace_path, "ranks": {}, "mismatched": []}
    for r, info in report["ranks"].items():
        if r not in spans:
            continue
        trace_s = (spans[r][1] - spans[r][0]) / 1e6  # us -> s
        wall = info["wall_seconds"]
        ok = (abs(trace_s - wall)
              <= TRACE_SKEW_TOLERANCE * max(wall, 1e-9))
        check["ranks"][r] = {"trace_span_seconds": trace_s,
                             "ledger_wall_seconds": wall, "ok": ok}
        if not ok:
            check["mismatched"].append(r)
    report["trace_check"] = check
    return check


def _pct(seconds, wall):
    return 100.0 * seconds / wall if wall > 0 else 0.0


def format_report(report):
    lines = []
    add = lines.append
    add("==== horovod_tpu goodput report " + "=" * 33)
    fleet = report["fleet"]
    wall = fleet["wall_seconds"]
    add(f"ranks: {sorted(report['ranks'])}; fleet rank-seconds: "
        f"{wall:.2f}")
    add(f"fleet goodput: {100 * fleet['goodput_ratio']:.1f}% compute")
    order = sorted(PHASES, key=lambda p: -fleet["phases"][p])
    for p in order:
        s = fleet["phases"][p]
        if s <= 0:
            continue
        add(f"  {p:<20} {s:>10.2f}s  {_pct(s, wall):5.1f}%")
    if fleet["unattributed_seconds"] > 0.005 * max(wall, 1e-9):
        add(f"  {'(unattributed)':<20} "
            f"{fleet['unattributed_seconds']:>10.2f}s  "
            f"{_pct(fleet['unattributed_seconds'], wall):5.1f}%")
    if fleet.get("compiled_path") and \
            fleet["phases"].get("exposed_collective", 0.0) == 0.0:
        add("note: compiled-path (GSPMD) run — collective time is "
            "inside the compiled step and books as compute; "
            "exposed_collective=0 is structural, not 'no comms'. "
            "Run `hvd-doctor xray` for the device-side split.")
    if fleet["dominant_sink"]:
        add(f"DOMINANT TIME SINK (fleet): {fleet['dominant_sink']} — "
            f"{fleet['dominant_sink_seconds']:.2f}s "
            f"({_pct(fleet['dominant_sink_seconds'], wall):.1f}% of wall)")
    else:
        add("DOMINANT TIME SINK (fleet): none — pure compute")
    for r, info in sorted(report["ranks"].items()):
        sink = (f"{info['dominant_sink']} "
                f"({_pct(info['dominant_sink_seconds'], info['wall_seconds']):.1f}%)"
                if info["dominant_sink"] else "none")
        add(f"rank {r}: wall {info['wall_seconds']:.2f}s, goodput "
            f"{100 * info['goodput_ratio']:.1f}%, dominant sink: {sink}"
            + (f", steps {info['steps']}"
               if info.get("steps") is not None else ""))
    bi = next((i["build_info"] for i in report["ranks"].values()
               if i.get("build_info")), None)
    if bi:
        add("build: " + ", ".join(f"{k}={v}" for k, v in sorted(bi.items())))
    tc = report.get("trace_check")
    if tc:
        if tc["mismatched"]:
            add(f"TRACE CROSS-CHECK: rank(s) {tc['mismatched']} ledger "
                f"wall disagrees with the merged trace span by more than "
                f"{int(TRACE_SKEW_TOLERANCE * 100)}% — attribution for "
                "them is suspect")
        else:
            add(f"trace cross-check: ledger wall matches {tc['trace']} "
                f"for rank(s) {sorted(tc['ranks'])}")
    add("=" * 66)
    return "\n".join(lines)


def run(logdir, trace=None, stream=None):
    """Load dumps under ``logdir``, print the report. Returns the
    report dict, or None when no dumps exist."""
    stream = stream or sys.stderr
    dumps, skipped = load_dumps(logdir)
    for path, err in skipped:
        print(f"goodput: skipping {path}: {err}", file=stream)
    if not dumps:
        print(f"goodput: no {DUMP_PREFIX}*.json dumps under {logdir}",
              file=stream)
        return None
    report = aggregate(dumps)
    if trace is None:
        # pick up the merged trace if one sits next to the dumps
        cand = os.path.join(logdir, "merged.json")
        trace = cand if os.path.exists(cand) else None
    if trace:
        try:
            crosscheck_trace(report, trace)
        except (OSError, ValueError) as e:
            print(f"goodput: trace cross-check skipped: {e}", file=stream)
    print(format_report(report), file=stream)
    return report


# -- the BENCH json block ----------------------------------------------------

def validate_goodput_block(block, tolerance=UNATTRIBUTED_TOLERANCE):
    """Enforce the *sum ≈ wall* invariant on a BENCH ``goodput`` block:
    raises :class:`GoodputInvariantError` when the unattributed gap
    exceeds ``tolerance`` of wall time (or the phase sum exceeds wall
    by more than float noise)."""
    wall = float(block.get("wall_seconds", 0.0))
    phases = block.get("phases", {})
    attributed = sum(float(v) for v in phases.values())
    if wall <= 0:
        raise GoodputInvariantError(
            f"goodput block has no wall time (wall_seconds={wall})")
    gap = wall - attributed
    if gap > tolerance * wall:
        raise GoodputInvariantError(
            f"goodput phases explain only {attributed:.3f}s of "
            f"{wall:.3f}s wall ({100 * gap / wall:.1f}% unattributed > "
            f"{100 * tolerance:.0f}% tolerance) — a phase hook is not "
            "charging its time")
    if attributed > wall * (1 + tolerance):
        raise GoodputInvariantError(
            f"goodput phases sum to {attributed:.3f}s, MORE than the "
            f"{wall:.3f}s wall — double-charged time")
    return block


def goodput_block(ledger=None, validate=True):
    """The BENCH json ``goodput`` block: finalize the (process) ledger
    and return its phase breakdown; with ``validate`` the sum≈wall
    invariant is enforced loudly (bench.py's contract — unattributed
    gaps >2% are an error, never silence)."""
    from horovod_tpu.telemetry import ledger as ledger_lib
    led = ledger_lib.get_ledger() if ledger is None else ledger
    snap = led.finalize()
    block = {
        "phases": {p: round(s, 4) for p, s in snap["phases"].items()},
        "wall_seconds": round(snap["wall_seconds"], 4),
        "unattributed_seconds": round(snap["unattributed_seconds"], 4),
        "goodput_ratio": round(snap["goodput_ratio"], 4),
        "steps": snap["steps"],
        "compiled_path": snap.get("compiled_path", False),
    }
    if validate:
        validate_goodput_block(block)
    return block


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="hvd-doctor perf",
        description="Aggregate per-rank goodput-ledger dumps "
                    "(goodput.rank*.json) into an end-of-run time-"
                    "attribution report naming the dominant time sink.")
    p.add_argument("logdir", help="directory containing goodput.rank*."
                                  "json dumps (searched recursively)")
    p.add_argument("--trace", default=None,
                   help="merged Chrome trace (hvdrun --merge-timeline "
                        "output) to cross-check ledger wall times "
                        "against (default: <logdir>/merged.json when "
                        "present)")
    p.add_argument("--json", action="store_true",
                   help="print the report dict as JSON on stdout "
                        "(the human-readable report moves to stderr)")
    args = p.parse_args(argv)
    report = run(args.logdir, trace=args.trace,
                 stream=sys.stderr if args.json else sys.stdout)
    if report is not None and args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    return 2 if report is None else 0


if __name__ == "__main__":
    sys.exit(main())
