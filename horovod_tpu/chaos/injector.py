"""ChaosMonkey: applies a seeded :class:`~horovod_tpu.chaos.plan.ChaosPlan`
to the live worker processes of an ``hvdrun`` job.

The monkey runs on its own daemon thread with an injectable clock and
sleeper (fake-clock tests drive the whole schedule in microseconds). It
deliberately holds a *reference* to the current
:class:`~horovod_tpu.run.launcher.Job` rather than a process list:
elastic runs replace the job every rendezvous epoch, and ``attach()``
retargets the remaining injections at the new epoch's workers.

Kind semantics against a POSIX process:

* ``sigterm``   — ``send_signal(SIGTERM)``: a spot eviction notice; the
  worker's graceful-eviction handler (elastic/preempt.py) gets its
  bounded grace window.
* ``sigkill``   — ``kill()``: an ungraceful host loss; no grace, no
  announcement — the driver must blame and back off via the crash path.
* ``stall``     — ``SIGSTOP`` then ``SIGCONT`` after ``duration``: a
  straggler / live-lock; peers park in collectives meanwhile.
* ``slow_disk`` — pulsed ``SIGSTOP``/``SIGCONT`` (duty-cycled) for
  ``duration``: approximates degraded I/O by periodically freezing the
  rank, which elongates its checkpoint writes and step times without
  killing it. (True fault injection at the filesystem layer needs
  privileges a test harness cannot assume.)
* ``host_sigterm`` / ``host_sigkill`` — the rank draw picks a live
  *host* (``Job.slots`` hostnames) and EVERY live rank on it gets the
  signal: preemption at the granularity it actually arrives on
  multi-host pods. The graceful form lets every rank's eviction
  handler announce the host, so the elastic driver records a *drain*
  (no blacklist penalty) rather than N crashes — drained ≠ crashed at
  host scope (elastic/driver.py Blacklist).
"""

import signal
import sys
import threading
import time

from horovod_tpu.chaos.plan import KINDS  # noqa: F401  (re-export)

# slow_disk duty cycle: frozen 40% of each 250ms period
_SLOW_DISK_PERIOD_S = 0.25
_SLOW_DISK_DUTY = 0.4


def _log(msg):
    sys.stderr.write(f"hvd-chaos: {msg}\n")
    sys.stderr.flush()


class ChaosMonkey:
    """Schedules a plan's injections against a live job."""

    def __init__(self, plan, clock=time.monotonic, sleep=time.sleep):
        self.plan = plan
        self.injections_done = []   # (Injection, rank, pid) applied
        self._attempted = 0         # injections attempted (host kinds
        #                             append one done-entry PER RANK)
        self._clock = clock
        self._sleep = sleep
        self._job = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    def attach(self, job):
        """(Re)target the monkey at ``job``'s processes. The first call
        also starts the scheduler thread; elastic re-launches call it
        again each epoch so pending injections hit the NEW workers."""
        with self._lock:
            self._job = job
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="hvd_tpu_chaos", daemon=True)
            self._thread.start()
            _log(f"armed: {self.plan.describe()}")
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=5.0)

    def done(self):
        return max(self._attempted, len(self.injections_done)) \
            >= len(self.plan.injections) or self._stop.is_set()

    # -- scheduler ---------------------------------------------------------

    def _run(self):
        start = self._clock()
        for inj in self.plan.injections:
            while not self._stop.is_set():
                remaining = start + inj.at - self._clock()
                if remaining <= 0:
                    break
                self._sleep(min(0.25, remaining))
            if self._stop.is_set():
                return
            self._apply(inj)
            self._attempted += 1
        _log(f"plan complete: {len(self.injections_done)} injection(s) "
             f"applied")

    def _live_procs(self):
        with self._lock:
            job = self._job
        if job is None:
            return []
        return [(rank, p) for rank, p in enumerate(job.procs)
                if p.poll() is None]

    def _hostname(self, rank):
        with self._lock:
            job = self._job
        slots = getattr(job, "slots", None)
        if slots and rank < len(slots):
            return slots[rank].hostname
        return "local"  # no slot map: the whole job is one host

    def _apply_host(self, inj, live):
        """Host-granularity kinds: the draw picks a live HOST; every
        live rank on it gets the signal."""
        hosts = {}
        for rank, proc in live:
            hosts.setdefault(self._hostname(rank), []).append((rank, proc))
        names = sorted(hosts)
        target = names[inj.rank % len(names)]
        sig = (signal.SIGKILL if inj.kind == "host_sigkill"
               else signal.SIGTERM)
        hit = []
        for rank, proc in hosts[target]:
            try:
                if sig == signal.SIGKILL:
                    proc.kill()
                else:
                    proc.send_signal(sig)
            except OSError as e:
                _log(f"{inj.kind} -> host {target} rank {rank}: {e}")
                continue
            self.injections_done.append(
                (inj, rank, getattr(proc, "pid", None)))
            hit.append(rank)
        _log(f"t+{inj.at:.1f}s {inj.kind} -> host {target} "
             f"(ranks {hit})")

    def _apply(self, inj):
        live = self._live_procs()
        if not live:
            _log(f"skip {inj.kind} at t+{inj.at:.1f}s: no live processes")
            return
        if inj.kind in ("host_sigterm", "host_sigkill"):
            return self._apply_host(inj, live)
        rank, proc = live[inj.rank % len(live)]
        try:
            if inj.kind == "sigterm":
                proc.send_signal(signal.SIGTERM)
            elif inj.kind == "sigkill":
                proc.kill()
            elif inj.kind == "stall":
                self._freeze(proc, inj.duration)
            elif inj.kind == "slow_disk":
                self._pulse(proc, inj.duration)
        except OSError as e:
            _log(f"{inj.kind} -> rank {rank}: {e}")
            return
        self.injections_done.append((inj, rank, getattr(proc, "pid", None)))
        _log(f"t+{inj.at:.1f}s {inj.kind} -> rank {rank} "
             f"(pid {getattr(proc, 'pid', '?')})"
             + (f" for {inj.duration:.1f}s"
                if inj.kind in ("stall", "slow_disk") else ""))

    def _freeze(self, proc, duration):
        proc.send_signal(signal.SIGSTOP)
        try:
            end = self._clock() + max(0.0, duration)
            while not self._stop.is_set():
                remaining = end - self._clock()
                if remaining <= 0:
                    break
                self._sleep(min(0.25, remaining))
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGCONT)

    def _pulse(self, proc, duration):
        end = self._clock() + max(0.0, duration)
        while not self._stop.is_set() and self._clock() < end \
                and proc.poll() is None:
            self._freeze(proc, _SLOW_DISK_PERIOD_S * _SLOW_DISK_DUTY)
            self._sleep(_SLOW_DISK_PERIOD_S * (1.0 - _SLOW_DISK_DUTY))
