"""horovod_tpu.chaos — seeded fault injection against live hvdrun jobs.

The harness half of the preemption-native training story
(docs/ELASTIC.md, "Running on spot capacity"): a :class:`ChaosPlan` is a
deterministic, seeded schedule of injections (SIGTERM / SIGKILL / stall
/ slow-disk) and a :class:`ChaosMonkey` applies it to the worker
processes of a running :class:`~horovod_tpu.run.launcher.Job`.
``hvdrun --chaos=<spec>`` arms one for soak runs; tests drive the
injector with fake clocks and fake processes.
"""

from horovod_tpu.chaos.injector import ChaosMonkey
from horovod_tpu.chaos.plan import (KINDS, ChaosPlan, Injection,
                                    parse_spec)

__all__ = ["ChaosPlan", "ChaosMonkey", "Injection", "KINDS",
           "parse_spec"]
