"""Seeded chaos plans: the deterministic schedule half of the harness.

A plan is a list of :class:`Injection`\\ s — *when* (seconds since the
monkey started), *what* (``sigterm`` / ``sigkill`` / ``stall`` /
``slow_disk``, or the host-granularity ``host_sigterm`` /
``host_sigkill``), *whom* (a rank draw the injector maps onto the live
processes — or, for host kinds, onto the live *hosts* — with a modulo,
so the plan does not need to know np), and for
the pausing kinds, *how long*. Everything is derived from one
``random.Random(seed)``: the same spec always produces byte-identical
schedules, which is what makes a chaos soak reproducible and a
goodput-under-churn bench comparable across runs.

Spec syntax (``hvdrun --chaos=<spec>``): either a path to a JSON file
(``{"seed": 7, "interval": 5, ...}`` or a pre-expanded
``{"injections": [...]}``), or an inline ``key=value`` comma list::

    --chaos "seed=7,interval=2.5,kinds=sigterm+sigkill,count=6"

Keys: ``seed`` (int, default 0), ``interval`` (mean seconds between
injections, default 5), ``jitter`` (0..1 fraction of interval, default
0.5), ``kinds`` (``+``-separated subset of the kinds above, default
``sigterm``), ``count`` (default 8), ``duration`` (stall/slow-disk
seconds, default 2).
"""

import dataclasses
import json
import os
import random

KINDS = ("sigterm", "sigkill", "stall", "slow_disk",
         # host granularity: the draw picks a HOST and every rank on
         # it gets the signal — a spot eviction (host_sigterm) or
         # outright loss (host_sigkill) of a whole machine, which is
         # how preemption actually arrives on multi-host pods
         # (docs/SCALING.md)
         "host_sigterm", "host_sigkill")

_DEFAULTS = {"seed": 0, "interval": 5.0, "jitter": 0.5,
             "kinds": ("sigterm",), "count": 8, "duration": 2.0}

# the raw rank draw's range; the injector maps it onto live processes
# with a modulo (plans are np-agnostic)
_RANK_DRAW = 1 << 16


@dataclasses.dataclass(frozen=True)
class Injection:
    """One scheduled fault."""

    at: float           # seconds after the monkey starts
    kind: str           # one of KINDS
    rank: int           # raw draw; target = rank % len(live processes)
    duration: float = 0.0   # stall / slow_disk only

    def as_dict(self):
        return dataclasses.asdict(self)


class ChaosPlan:
    """An immutable, seeded injection schedule."""

    def __init__(self, injections, spec=None):
        self.injections = tuple(sorted(injections, key=lambda i: i.at))
        self.spec = spec
        for inj in self.injections:
            if inj.kind not in KINDS:
                raise ValueError(
                    f"chaos: unknown injection kind {inj.kind!r} "
                    f"(expected one of {KINDS})")

    @classmethod
    def generate(cls, seed=0, interval=5.0, jitter=0.5, kinds=("sigterm",),
                 count=8, duration=2.0, spec=None):
        """Expand knobs into a schedule with one ``random.Random(seed)``
        — fully deterministic per (seed, knobs)."""
        kinds = tuple(kinds)
        for k in kinds:
            if k not in KINDS:
                raise ValueError(f"chaos: unknown kind {k!r} "
                                 f"(expected one of {KINDS})")
        if interval <= 0:
            raise ValueError("chaos: interval must be > 0")
        if not 0 <= jitter <= 1:
            raise ValueError("chaos: jitter must be in [0, 1]")
        rng = random.Random(seed)
        injections = []
        t = 0.0
        for _ in range(max(0, int(count))):
            t += interval * (1.0 + jitter * (2.0 * rng.random() - 1.0))
            kind = rng.choice(kinds)
            injections.append(Injection(
                at=round(t, 6), kind=kind, rank=rng.randrange(_RANK_DRAW),
                duration=float(duration) if kind in ("stall", "slow_disk")
                else 0.0))
        return cls(injections, spec=spec)

    def describe(self):
        kinds = sorted({i.kind for i in self.injections})
        last = self.injections[-1].at if self.injections else 0.0
        return (f"{len(self.injections)} injection(s) of {kinds} "
                f"over {last:.1f}s"
                + (f" [{self.spec}]" if self.spec else ""))

    def as_dict(self):
        return {"injections": [i.as_dict() for i in self.injections],
                "spec": self.spec}


def _parse_inline(spec):
    knobs = dict(_DEFAULTS)
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"chaos: expected key=value, got {item!r}")
        key, _, val = item.partition("=")
        key = key.strip()
        val = val.strip()
        if key not in _DEFAULTS:
            raise ValueError(
                f"chaos: unknown spec key {key!r} "
                f"(expected one of {sorted(_DEFAULTS)})")
        try:
            if key in ("seed", "count"):
                knobs[key] = int(val)
            elif key == "kinds":
                knobs[key] = tuple(k for k in val.split("+") if k)
            else:
                knobs[key] = float(val)
        except ValueError as e:
            raise ValueError(f"chaos: bad value for {key}: {val!r}") from e
    return knobs


def parse_spec(spec):
    """``--chaos`` spec -> :class:`ChaosPlan` (module docstring syntax).
    Raises ``ValueError`` on anything malformed, so the CLI can reject
    the flag before launching workers."""
    if not spec or not str(spec).strip():
        raise ValueError("chaos: empty spec")
    spec = str(spec).strip()
    if os.path.isfile(spec):
        with open(spec) as f:
            try:
                data = json.load(f)
            except ValueError as e:
                raise ValueError(f"chaos: {spec} is not valid JSON: {e}") \
                    from e
        if not isinstance(data, dict):
            raise ValueError(f"chaos: {spec} must hold a JSON object")
        if "injections" in data:
            injections = [Injection(
                at=float(i["at"]), kind=str(i["kind"]),
                rank=int(i.get("rank", 0)),
                duration=float(i.get("duration", 0.0)))
                for i in data["injections"]]
            return ChaosPlan(injections, spec=spec)
        knobs = dict(_DEFAULTS)
        for key, val in data.items():
            if key not in _DEFAULTS:
                raise ValueError(f"chaos: unknown spec key {key!r} in "
                                 f"{spec}")
            knobs[key] = tuple(val) if key == "kinds" else val
        return ChaosPlan.generate(spec=spec, **knobs)
    return ChaosPlan.generate(spec=spec, **_parse_inline(spec))
