"""Environment-variable configuration knobs.

Mirrors the reference's env-knob surface (``horovod/common/common.h:61-88``,
parsed at ``horovod/common/operations.cc:387-484`` and
``horovod/common/utils/env_parser.cc``) with the same ``HOROVOD_*`` names so
users of the reference find the knobs they know. Launcher rank contract
mirrors ``horovod/run/gloo_run.py:210-236``.
"""

import dataclasses
import os


def _env_int(name, default):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return int(v)


def _env_float(name, default):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return float(v)


def _env_bool(name, default=False):
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v.lower() not in ("0", "false", "no", "off")


def _env_str(name, default=None):
    v = os.environ.get(name)
    return default if v in (None, "") else v


# Default tensor-fusion buffer size: 64 MB, matching the reference default
# (horovod/common/operations.cc:403).
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
# Background-cycle time in ms (reference default 5 ms, operations.cc:407).
DEFAULT_CYCLE_TIME_MS = 5.0
# Response-cache capacity (reference default 1024, global_state.h:88).
DEFAULT_CACHE_CAPACITY = 1024
# Stall-warning threshold in seconds (reference 60 s, stall_inspector.h).
DEFAULT_STALL_WARNING_TIME = 60.0


@dataclasses.dataclass
class Config:
    """Snapshot of all HOROVOD_* knobs at ``init()`` time."""

    # --- process identity (set by the hvdrun launcher; gloo_run.py:210) ---
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    # --- control plane (reference: HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT) ---
    controller_addr: str = None
    controller_port: int = 0
    rendezvous_addr: str = None
    rendezvous_port: int = 0

    # --- process mesh (hvdrun --spmd-procs; cluster/procmesh.py) ---
    # number of jax.distributed processes forming the one logical mesh
    # (0 = HOROVOD_SIZE when a coordinator address is set)
    spmd_procs: int = 0
    # virtual CPU devices this process contributes to the mesh (0 = the
    # backend default; CPU-only, stands in for a TPU host's local chips)
    spmd_local_devices: int = 0
    # cross-process collectives impl for XLA:CPU (default "gloo")
    cpu_collectives: str = None

    # --- data plane tuning ---
    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD
    # Default collective wire format for DistributedOptimizer(
    # compression=None): one of None (uncompressed), "bf16"/"fp16",
    # "float16", "fp8_e4m3"/"fp8", "fp8_e5m2", "int8"
    # (ops/compression.by_name). The autotuner's wire axis installs its
    # winner here (docs/AUTOTUNE.md); an explicit compression= argument
    # always wins over the config value.
    wire_dtype: str = None
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    batch_d2d_memcopies: bool = True

    # --- XLA overlap scheduling (bucketed reduce-scatter pipeline) ---
    # Compiling the pipeline is only half the job: without the async-
    # collective + latency-hiding scheduler flags XLA serializes each
    # reduce-scatter behind the compute that precedes it and the overlap
    # never materializes on device.
    xla_async_collectives: bool = True
    xla_latency_hiding_scheduler: bool = True

    # --- observability ---
    timeline: str = None
    timeline_mark_cycles: bool = False
    log_level: str = "warning"
    log_hide_timestamp: bool = False
    # metrics endpoint (telemetry/server.py): None = disabled, 0 = bind
    # an ephemeral port. The launcher assigns base_port + local_rank per
    # rank (run/launcher.py). Loopback by default — the endpoints are
    # unauthenticated (security note in docs/OBSERVABILITY.md).
    metrics_port: int = None
    metrics_addr: str = "127.0.0.1"
    profile_dir: str = None
    # flight recorder (horovod_tpu/diag): None = auto — on for
    # multi-process jobs (where post-mortem forensics matter and a
    # launcher owns the dump dir), off for single-process library use
    # (no surprise signal handlers inside a host application).
    # HOROVOD_FLIGHTREC=0/1 forces; _CAPACITY bounds the ring;
    # _DIR is where flightrec.rank<r>.json dumps land (hvdrun plumbs
    # this to --output-dir or a run-scoped temp dir).
    flightrec: bool = None
    flightrec_capacity: int = 4096
    flightrec_dir: str = None

    @property
    def flightrec_enabled(self):
        return self.size > 1 if self.flightrec is None else self.flightrec

    # --- stall inspector (stall_inspector.h:30-70) ---
    stall_check_disable: bool = False
    stall_warning_time: float = DEFAULT_STALL_WARNING_TIME
    stall_shutdown_time: float = 0.0

    # --- autotune (parameter_manager.h) ---
    autotune: bool = False
    autotune_log: str = None
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8

    # --- adasum ---
    adasum_chunk_size: int = 1 << 26

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            rank=_env_int("HOROVOD_RANK", 0),
            size=_env_int("HOROVOD_SIZE", 1),
            local_rank=_env_int("HOROVOD_LOCAL_RANK", 0),
            local_size=_env_int("HOROVOD_LOCAL_SIZE", 1),
            cross_rank=_env_int("HOROVOD_CROSS_RANK", 0),
            cross_size=_env_int("HOROVOD_CROSS_SIZE", 1),
            controller_addr=_env_str("HOROVOD_CONTROLLER_ADDR"),
            controller_port=_env_int("HOROVOD_CONTROLLER_PORT", 0),
            rendezvous_addr=_env_str("HOROVOD_GLOO_RENDEZVOUS_ADDR"),
            rendezvous_port=_env_int("HOROVOD_GLOO_RENDEZVOUS_PORT", 0),
            spmd_procs=_env_int("HOROVOD_SPMD_PROCS", 0),
            spmd_local_devices=_env_int("HOROVOD_SPMD_LOCAL_DEVICES", 0),
            cpu_collectives=_env_str("HOROVOD_CPU_COLLECTIVES"),
            fusion_threshold=_env_int(
                "HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD),
            wire_dtype=_env_str("HOROVOD_WIRE_DTYPE"),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME",
                                     DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_env_int("HOROVOD_CACHE_CAPACITY",
                                    DEFAULT_CACHE_CAPACITY),
            xla_async_collectives=_env_bool(
                "HOROVOD_XLA_ASYNC_COLLECTIVES", True),
            xla_latency_hiding_scheduler=_env_bool(
                "HOROVOD_XLA_LATENCY_HIDING_SCHEDULER", True),
            hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"),
            batch_d2d_memcopies=_env_bool("HOROVOD_BATCH_D2D_MEMCOPIES", True),
            timeline=_env_str("HOROVOD_TIMELINE"),
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            metrics_port=_env_int("HOROVOD_METRICS_PORT", None),
            metrics_addr=_env_str("HOROVOD_METRICS_ADDR", "127.0.0.1"),
            profile_dir=_env_str("HOROVOD_PROFILE_DIR"),
            flightrec=(None if _env_str("HOROVOD_FLIGHTREC") is None
                       else _env_bool("HOROVOD_FLIGHTREC")),
            flightrec_capacity=_env_int("HOROVOD_FLIGHTREC_CAPACITY", 4096),
            flightrec_dir=_env_str("HOROVOD_FLIGHTREC_DIR"),
            log_level=_env_str("HOROVOD_LOG_LEVEL", "warning"),
            log_hide_timestamp=_env_bool("HOROVOD_LOG_HIDE_TIME"),
            stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE"),
            stall_warning_time=_env_float(
                "HOROVOD_STALL_CHECK_TIME_SECONDS", DEFAULT_STALL_WARNING_TIME),
            stall_shutdown_time=_env_float(
                "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0),
            autotune=_env_bool("HOROVOD_AUTOTUNE"),
            autotune_log=_env_str("HOROVOD_AUTOTUNE_LOG"),
            autotune_warmup_samples=_env_int("HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
                                             3),
            autotune_steps_per_sample=_env_int(
                "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10),
            autotune_bayes_opt_max_samples=_env_int(
                "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20),
            autotune_gaussian_process_noise=_env_float(
                "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8),
            adasum_chunk_size=_env_int("HOROVOD_ADASUM_CHUNK_SIZE", 1 << 26),
        )


def xla_overlap_flags(cfg):
    """The libtpu/XLA flags that let the compiler actually overlap the
    bucketed reduce-scatter pipeline with backward compute: async
    collectives (collectives become start/done pairs other work can slide
    between) and the latency-hiding scheduler (which does the sliding).
    Returned as ``--flag=value`` strings for ``LIBTPU_INIT_ARGS``."""
    flags = []
    if cfg.xla_latency_hiding_scheduler:
        flags.append("--xla_tpu_enable_latency_hiding_scheduler=true")
    if cfg.xla_async_collectives:
        flags += [
            "--xla_tpu_enable_async_collective_fusion=true",
            "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
            "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
            "--xla_tpu_overlap_compute_collective_tc=true",
        ]
    return flags


def apply_xla_flags(cfg, env=None):
    """Merge :func:`xla_overlap_flags` into ``LIBTPU_INIT_ARGS`` — must run
    before the first jax backend touch (``basics.init()`` does). libtpu
    reads the variable once at initialization; CPU/GPU builds never read
    it, so this is a no-op off TPU. Flags the user already set (matched by
    name) are left exactly as the user wrote them."""
    env = os.environ if env is None else env
    existing = env.get("LIBTPU_INIT_ARGS", "")
    have = {f.split("=", 1)[0] for f in existing.split()}
    add = [f for f in xla_overlap_flags(cfg)
           if f.split("=", 1)[0] not in have]
    if add:
        env["LIBTPU_INIT_ARGS"] = " ".join(
            ([existing] if existing else []) + add)
        if _tpu_backend_already_live():
            import logging
            logging.getLogger("horovod_tpu").warning(
                "hvd.init() ran AFTER the jax TPU backend was initialized "
                "(something touched jax.devices()/arrays first): libtpu "
                "already read LIBTPU_INIT_ARGS, so the async-collective/"
                "latency-hiding scheduler flags were NOT picked up and the "
                "overlapped gradient pipeline will not overlap. Call "
                "hvd.init() before any jax work, or export the flags "
                "yourself (docs/PERFORMANCE.md).")
    return add


def _tpu_backend_already_live():
    """True when a TPU backend is already initialized in this process —
    the point after which LIBTPU_INIT_ARGS edits are silently ignored.
    Probes only; never initializes a backend itself."""
    try:
        from jax._src import xla_bridge
        if not xla_bridge.backends_are_initialized():
            return False
        import jax
        return any(d.platform == "tpu" for d in jax.devices())
    # hvd-lint: disable=HVD-EXCEPT -- internal-API probe across jax versions; False is safe
    except Exception:  # pragma: no cover - internal API drift
        return False
