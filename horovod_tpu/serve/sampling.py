"""Real sampling for the serving plane: temperature / top-p / seeds.

Greedy argmax stays the default (deterministic — the property the
engine's "continuous batching is bit-identical to single-shot" test
contract is built on). This module adds stochastic decoding WITHOUT
giving that determinism up:

* **Per-request seeds.** Every sampled token's randomness comes from
  ``fold_in(PRNGKey(seed), token_index)`` where ``token_index`` is the
  token's ABSOLUTE position in the sequence (prompt tokens count). The
  key depends only on (seed, position) — not on batch composition, not
  on which replica runs the request, not on how the prompt was chunked
  — so the same seed + prompt reproduces the same stream on any
  replica, across a mid-flight weight reload of the same params, and
  even when a fleet router re-dispatches a half-finished request to
  another replica with ``prompt + generated-so-far`` as the new prompt
  (the continuation's first sampled token sits at the same absolute
  index, hence draws the same key).
* **One batched dispatch.** :func:`sample_tokens` is pure and
  batch-shaped: the engine threads per-slot ``seeds``/``temperature``/
  ``top_p`` arrays through its ONE compiled decode program; per-slot
  keys are derived inside the program. No per-request dispatches, no
  recompiles (the knobs are runtime arrays, not static constants).
* **Bitwise-greedy at temperature 0.** ``temperature <= 0`` selects
  the plain ``argmax`` lane — not a limit of a softmax, the identical
  integer — so deterministic requests keep matching the greedy oracle
  bit-for-bit while sharing the batch with sampled ones.

Top-p (nucleus) filtering keeps the smallest logit-ranked set whose
probability mass reaches ``top_p`` (always at least the top token),
then draws via Gumbel-max over the surviving logits — one argmax, no
host-side categorical draw.
"""

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs. The default is greedy decoding
    (``temperature=0``), matching the engine's deterministic
    contract; ``seed`` only matters once ``temperature > 0``."""

    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0 < self.top_p <= 1:
            raise ValueError("top_p must be in (0, 1]")
        int(self.seed)  # must be integral


GREEDY = SamplingParams()


def sample_tokens(logits, seeds, indices, temperature, top_p):
    """Batched per-slot next-token selection: ``[B, V]`` logits →
    ``[B]`` int32 token ids.

    ``seeds``/``indices``/``temperature``/``top_p`` are ``[B]``
    arrays; ``indices[i]`` is the ABSOLUTE index of the token being
    sampled for slot ``i`` (len(prompt) + generated so far) — the
    fold-in that makes streams position-deterministic (module
    docstring). Slots with ``temperature <= 0`` take the bitwise
    argmax lane."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # the zero-temperature lane's scaled logits are discarded by the
    # final where; guard the division so they are merely unused, not NaN
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits.astype(jnp.float32) / safe_t[:, None].astype(
        jnp.float32)
    # nucleus cutoff in sorted space: keep while the mass BEFORE a
    # token is < top_p (the top token's "before" mass is 0 — always in)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = mass_before < top_p[:, None].astype(jnp.float32)
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    nucleus = jnp.where(scaled >= cutoff, scaled, -jnp.inf)

    def draw(seed, index, row):
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed.astype(jnp.uint32)),
            index.astype(jnp.uint32))
        gumbel = jax.random.gumbel(key, row.shape, jnp.float32)
        return jnp.argmax(row + gumbel).astype(jnp.int32)

    sampled = jax.vmap(draw)(seeds, indices, nucleus)
    return jnp.where(temperature > 0, sampled, greedy)
