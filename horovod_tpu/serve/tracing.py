"""Request-scoped tracing for the serving plane (ISSUE 18).

The training side can explain every second of wall clock (goodput
ledger, flight recorder, ``hvd-doctor perf``); this module brings the
same per-event attribution to the request path, modeled on Horovod's
Timeline: every phase a request passes through — router queue, scoring
and dispatch, KV admission (or backpressure), each prefill chunk and
decode iteration it rode, weight-swap windows it overlapped, eviction
hops, HTTP first-byte — becomes a span on one per-request timeline.

Design constraints, in order:

* **Tracing off costs nothing.** ``ServeTracer.from_env()`` returns
  ``None`` when no knob is set; untraced requests carry ``trace=None``
  and every engine hot-path hook is gated behind a single attribute /
  int check. Compiled programs never see tracing (it is pure host-side
  bookkeeping), so dispatch behavior is byte-identical — the same
  discipline the train step enforces (tests assert both).
* **Recording is lock-cheap.** :class:`RequestTrace` records via plain
  ``list.append`` (atomic under the GIL); the engine scheduler thread,
  the router, the pump and the HTTP frontend all record into one trace
  concurrently without taking a lock. Sorting, gap classification and
  attribution happen once, in :meth:`RequestTrace.finalize`.
* **Attribution tiles the timeline.** Solid spans cover measured work;
  :meth:`finalize` computes the complement gaps inside
  ``[start, end]`` and classifies each by the phase the request was in
  when the gap opened (queued -> ``queue``, admitted-but-waiting ->
  ``prefill_wait`` / ``decode_wait``, cut -> ``redispatch``, ...). Only
  a gap with no known phase stays unattributed, which is what the
  bench's >= 98 % ``tail_attribution`` gate polices.

Sampling: ``HOROVOD_SERVE_TRACE`` (``1``/``all`` or a fraction),
per-request ``trace=true``, and — when ``HOROVOD_SERVE_TRACE_SLO_MS``
is set — tail sampling: every request records cheaply, but only those
finishing over the SLO (plus sampled/forced ones) are kept.

Export: ndjson dumps (one finalized trace per line — the input format
of ``hvd-doctor serve``, diag/serve_doctor.py) and Chrome traces
through the existing ``telemetry/merge.py`` machinery — one pid per
actor (router, then replicas), clock-sync alignment, and request-hop
flow arrows in :data:`~horovod_tpu.telemetry.merge.GLOBAL_FLOW_CAT` so
the merge keeps them crossing pids. See docs/OBSERVABILITY.md
("Debugging a slow request").
"""

import collections
import itertools
import json
import os
import threading
import time

from horovod_tpu.telemetry import merge as merge_lib

# The span-name table. Every kind emitted anywhere in the serving stack
# must be listed here, and every entry must have a phase in
# diag/serve_doctor.py's PHASE_OF_KIND classifier — hvd-lint HVD-METRIC
# asserts both directions (analysis/rules/metric.py), same pattern as
# the metric-name drift check.
SPAN_KINDS = (
    "queue",         # waiting for admission (router and/or engine queue)
    "dispatch",      # router scoring + handoff to a replica engine
    "kv_wait",       # at the admission head, backpressured on KV blocks
    "prefill",       # one prefill-chunk dispatch the request rode
    "prefill_wait",  # admitted, waiting for its next prefill turn
    "decode",        # one batched decode iteration the request rode
    "decode_wait",   # decoding, waiting for its next iteration
    "weight_swap",   # a staged-weight swap window the request overlapped
    "redispatch",    # cut by an eviction, resuming on a survivor
    "stream",        # HTTP frontend first-byte / frame write
)

UNATTRIBUTED = "unattributed"

# phase marks (RequestTrace.phase) -> the gap kind charged while the
# request sits in that phase with no solid span covering the time
_GAP_KIND_OF_PHASE = {
    "queued": "queue",
    "kv_wait": "kv_wait",
    "prefilling": "prefill_wait",
    "decoding": "decode_wait",
    "redispatching": "redispatch",
}

TRACE_ENV = "HOROVOD_SERVE_TRACE"
TRACE_DIR_ENV = "HOROVOD_SERVE_TRACE_DIR"
TRACE_SLO_ENV = "HOROVOD_SERVE_TRACE_SLO_MS"

NDJSON_NAME = "servetrace.ndjson"

_ON = ("1", "true", "on", "all", "yes")
_OFF = ("", "0", "false", "off", "no", "none")


class RequestTrace:
    """Span recorder for ONE request's lifetime across actors.

    The record path (:meth:`span` / :meth:`event` / :meth:`phase`) is
    plain list appends — no lock; concurrent recorders interleave
    safely under the GIL and :meth:`finalize` sorts once at the end.
    Timestamps come from the owning tracer's injectable monotonic
    clock (the router's, fleet-wide), never ``time.time``.
    """

    __slots__ = ("request_id", "keep", "start", "end", "result",
                 "_clock", "_spans", "_events", "_phases")

    def __init__(self, request_id, clock=time.monotonic, keep=True,
                 start=None):
        self.request_id = str(request_id)
        self.keep = keep
        self._clock = clock
        self.start = clock() if start is None else start
        self.end = None
        self.result = None
        self._spans = []   # (kind, t0, t1, actor, attrs-or-None)
        self._events = []  # (name, t, attrs-or-None)
        self._phases = []  # (t, phase)

    def now(self):
        return self._clock()

    def span(self, kind, t0, t1, actor=None, **attrs):
        """Record a closed [t0, t1] span of measured work."""
        self._spans.append((kind, t0, t1, actor, attrs or None))

    def event(self, name, t, **attrs):
        """Record an instant (submit, admitted, cut, resumed, done...)."""
        self._events.append((name, t, attrs or None))

    def phase(self, t, phase):
        """Mark a phase transition — classifies later gaps at >= t."""
        if self._phases and self._phases[-1][1] == phase:
            return
        self._phases.append((t, phase))

    @staticmethod
    def _phase_at(phases, t):
        cur = None
        for pt, name in phases:
            if pt <= t + 1e-9:
                cur = name
            else:
                break
        return cur

    def finalize(self, end=None):
        """Sort spans, tile ``[start, end]`` with solid spans + classified
        gaps, pair cut/resumed events into hop windows, and cache the
        JSON-ready dict. Idempotent."""
        if self.result is not None:
            return self.result
        self.end = self._clock() if end is None else end
        start, end_t = self.start, max(self.end, self.start)
        phases = sorted(self._phases)
        solid = sorted((s for s in self._spans if s[2] > s[1]),
                       key=lambda s: (s[1], s[2]))
        spans_out = []
        for kind, t0, t1, actor, attrs in solid:
            d = {"kind": kind, "t0": t0, "t1": t1}
            if actor:
                d["actor"] = actor
            if attrs:
                d.update(attrs)
            spans_out.append(d)
        # complement gaps inside [start, end], classified by the phase
        # in force when each gap opens
        gaps, cursor = [], start
        for _kind, t0, t1, _actor, _attrs in solid:
            if t0 > cursor:
                gaps.append((cursor, min(t0, end_t)))
            cursor = max(cursor, t1)
            if cursor >= end_t:
                break
        if cursor < end_t:
            gaps.append((cursor, end_t))
        unattributed = 0.0
        for g0, g1 in gaps:
            if g1 <= g0:
                continue
            kind = _GAP_KIND_OF_PHASE.get(self._phase_at(phases, g0))
            if kind is None:
                kind = UNATTRIBUTED
                unattributed += g1 - g0
            spans_out.append({"kind": kind, "t0": g0, "t1": g1,
                              "gap": True})
        spans_out.sort(key=lambda s: (s["t0"], s["t1"]))
        events = sorted(self._events, key=lambda e: e[1])
        events_out = []
        for name, t, attrs in events:
            d = {"name": name, "t": t}
            if attrs:
                d.update(attrs)
            events_out.append(d)
        # a hop window opens at each "cut" and closes at the next
        # "resumed" (first token on the survivor) or the end — the
        # doctor charges everything inside it to the re-dispatch hop.
        # The open edge reaches back to the drain notice that doomed
        # the replica (when one was recorded): time spent parked on a
        # draining replica that then cut the stream was eviction-caused
        # from the notice, not just from the grace expiry.
        cuts = [(t, a.get("actor")) for n, t, a in events if n == "cut"]
        resumes = [t for n, t, _ in events if n == "resumed"]
        drains = [(t, a.get("actor")) for n, t, a in events
                  if n == "drain" and a.get("on")]
        hop_windows = []
        prev_end = start
        for c, actor in cuts:
            c0 = c
            for dt, dactor in drains:
                if prev_end <= dt <= c and dactor == actor:
                    c0 = min(c0, dt)
                    break
            r = next((t for t in resumes if t > c), end_t)
            hop_windows.append([c0, max(c0, r)])
            prev_end = hop_windows[-1][1]
        latency = max(0.0, end_t - start)
        attributed = max(0.0, latency - unattributed)
        self.result = {
            "request_id": self.request_id,
            "start": start,
            "end": end_t,
            "latency_s": latency,
            "attributed_s": attributed,
            "attributed_fraction":
                1.0 if latency <= 0.0 else attributed / latency,
            "hops": len(hop_windows),
            "hop_windows": hop_windows,
            "spans": spans_out,
            "events": events_out,
        }
        return self.result


class ServeTracer:
    """Sampling controller + sink for :class:`RequestTrace` objects.

    ``begin`` decides whether a request records at all (forced /
    deterministically sampled / SLO tail-armed); ``finish`` finalizes,
    applies the SLO keep-upgrade, retains the dict in a bounded deque
    and appends an ndjson line when ``out_dir`` is set. Whoever called
    ``begin`` owns the trace and must call ``finish`` exactly once —
    the engine for direct submits, the router for fleet requests.
    """

    def __init__(self, sample=1.0, slo_ms=None, out_dir=None,
                 clock=time.monotonic, max_keep=10000):
        self.sample = max(0.0, min(1.0, float(sample)))
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.out_dir = out_dir
        self._clock = clock
        # chrome ts=0 <-> unix anchor, captured together at construction
        self._base_t = clock()
        self._base_unix_us = time.time() * 1e6
        self._lock = threading.Lock()
        self._count = 0
        self._flow_ids = itertools.count(1)
        self._kept = collections.deque(maxlen=max_keep)
        self._ndjson = None

    @classmethod
    def from_env(cls, env=None, clock=time.monotonic, out_dir=None):
        """Build a tracer from the HOROVOD_SERVE_TRACE* knobs; ``None``
        when every knob is unset/off (the zero-cost default)."""
        env = os.environ if env is None else env
        raw = (env.get(TRACE_ENV) or "").strip().lower()
        slo = (env.get(TRACE_SLO_ENV) or "").strip()
        out = out_dir or env.get(TRACE_DIR_ENV) or None
        if raw in _OFF and not slo and not out:
            return None
        if raw in _ON:
            sample = 1.0
        elif raw in _OFF:
            # dir/SLO alone arm tail-or-forced tracing, sample nothing
            sample = 0.0
        else:
            try:
                sample = float(raw)
            except ValueError:
                sample = 1.0
        try:
            slo_ms = float(slo) if slo else None
        except ValueError:
            slo_ms = None
        return cls(sample=sample, slo_ms=slo_ms, out_dir=out, clock=clock)

    def begin(self, request_id, force=False):
        """A :class:`RequestTrace` when this request should record,
        else ``None``. ``keep`` starts False for SLO-armed-only traces
        (tail sampling: record now, decide at finish)."""
        with self._lock:
            self._count += 1
            n = self._count
        f = self.sample
        sampled = f >= 1.0 or (f > 0.0
                               and int(n * f) > int((n - 1) * f))
        if not (force or sampled or self.slo_ms is not None):
            return None
        return RequestTrace(request_id, clock=self._clock,
                            keep=bool(force or sampled))

    def finish(self, trace, end=None):
        """Finalize and retain (or drop, for under-SLO tail samples)."""
        if trace is None:
            return None
        result = trace.finalize(end=end)
        if self.slo_ms is not None \
                and result["latency_s"] * 1e3 >= self.slo_ms:
            trace.keep = True
            result["slo_exceeded"] = True
        if not trace.keep:
            return None
        with self._lock:
            self._kept.append(result)
            if self.out_dir is not None:
                if self._ndjson is None:
                    os.makedirs(self.out_dir, exist_ok=True)
                    self._ndjson = open(
                        os.path.join(self.out_dir, NDJSON_NAME), "a")
                self._ndjson.write(json.dumps(result) + "\n")
                self._ndjson.flush()
        return result

    def traces(self):
        with self._lock:
            return list(self._kept)

    def clear(self):
        with self._lock:
            self._kept.clear()

    def close(self):
        with self._lock:
            if self._ndjson is not None:
                self._ndjson.close()
                self._ndjson = None

    def write_ndjson(self, path):
        """Dump every kept trace as one-JSON-per-line — the input
        format of ``hvd-doctor serve``."""
        traces = self.traces()
        with open(path, "w") as fh:
            for tr in traces:
                fh.write(json.dumps(tr) + "\n")
        return len(traces)

    # -- Chrome export ---------------------------------------------------

    def _ts_us(self, t):
        return (t - self._base_t) * 1e6

    def chrome_files(self, out_dir, traces=None):
        """One Chrome-trace JSON array per actor (pid = actor index;
        router first, replicas sorted after), each with the standard
        clock-sync event so ``telemetry/merge.py`` aligns and labels
        them; request hops become cross-pid flow arrows in
        ``GLOBAL_FLOW_CAT``. Returns the written paths."""
        traces = self.traces() if traces is None else list(traces)
        actors = set()
        for tr in traces:
            for sp in tr["spans"]:
                if sp.get("actor"):
                    actors.add(sp["actor"])
            for ev in tr["events"]:
                # a cut replica may have queued the stream without ever
                # running it — no spans, but its lane must exist for
                # the hop arrow to land on
                if ev.get("actor") and ev["name"] in ("cut", "resumed"):
                    actors.add(ev["actor"])
        actors = sorted(actors, key=lambda a: (a != "router", a))
        if not actors:
            actors = ["router"]
        index = {a: i for i, a in enumerate(actors)}
        per_actor = {a: [] for a in actors}
        tids = {}
        for tr in traces:
            tid = tids.setdefault(tr["request_id"], len(tids) + 1)
            for sp in tr["spans"]:
                actor = sp.get("actor") or actors[0]
                if actor not in index:  # dump merged from another fleet
                    continue
                args = {k: v for k, v in sp.items()
                        if k not in ("kind", "t0", "t1", "actor")}
                args["request"] = tr["request_id"]
                per_actor[actor].append({
                    "name": sp["kind"], "cat": "hvd_serve", "ph": "X",
                    "ts": round(self._ts_us(sp["t0"]), 3),
                    "dur": round(max(0.0, sp["t1"] - sp["t0"]) * 1e6, 3),
                    "tid": tid, "args": args})
            # one arrow per hop: the "cut" event on the doomed replica
            # -> the next "resumed" event on its survivor, one
            # GLOBALLY-allocated id so the merge keeps it crossing pids
            # (event-based: a stream cut while still queued has no span
            # on the doomed replica at all)
            resumes = [e for e in tr["events"] if e["name"] == "resumed"
                       and e.get("actor") in index]
            for ce in tr["events"]:
                if ce["name"] != "cut" or ce.get("actor") not in index:
                    continue
                re_ = next((r for r in resumes if r["t"] > ce["t"]),
                           None)
                if re_ is None:
                    continue
                with self._lock:
                    fid = next(self._flow_ids)
                per_actor[ce["actor"]].append({
                    "name": "redispatch", "cat": merge_lib.GLOBAL_FLOW_CAT,
                    "ph": "s", "id": fid, "tid": tid,
                    "ts": round(self._ts_us(ce["t"]), 3)})
                per_actor[re_["actor"]].append({
                    "name": "redispatch", "cat": merge_lib.GLOBAL_FLOW_CAT,
                    "ph": "f", "bp": "e", "id": fid, "tid": tid,
                    "ts": round(self._ts_us(re_["t"]), 3)})
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for actor in actors:
            rank = index[actor]
            events = [
                {"name": merge_lib.CLOCK_SYNC, "ph": "i", "s": "g",
                 "ts": 0, "pid": rank, "tid": 0,
                 "args": {"unix_time_us": self._base_unix_us,
                          "rank": rank}},
                {"name": "process_name", "ph": "M", "pid": rank,
                 "args": {"name": f"serve {actor}"}},
                {"name": "process_sort_index", "ph": "M", "pid": rank,
                 "args": {"sort_index": rank}},
            ] + per_actor[actor]
            path = os.path.join(out_dir, f"servetrace.rank{rank}.json")
            with open(path, "w") as fh:
                json.dump(events, fh)
            paths.append(path)
        return paths

    def write_chrome(self, out_path, traces=None):
        """Per-actor files + the telemetry merge -> one Perfetto-loadable
        trace at ``out_path``. Returns the merged event list."""
        out_dir = os.path.dirname(os.path.abspath(out_path)) or "."
        paths = self.chrome_files(out_dir, traces=traces)
        return merge_lib.merge_traces(paths, out_path)
