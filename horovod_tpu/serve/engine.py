"""Continuous-batching inference engine.

The serving analogue of ``training.make_train_step``: two static-shaped
jitted programs — **prefill** (one chunk of one sequence's prompt) and
**decode** (one new token for every running sequence) — driven by an
iteration-level scheduler (Orca's continuous batching): requests join
and leave the running decode batch **between** program dispatches, never
inside one, so a long generation no longer holds the batch hostage and
a short one no longer waits for it.

One scheduler iteration (:meth:`ServeEngine.step`):

1. **weight swap** — staged params from the rolling-reload watcher
   (``serve/loader.py``) replace the live tree; in-flight sequences keep
   their KV and continue under the new weights (docs/SERVING.md,
   "Rolling reload").
2. **admission** — FIFO from the waiting queue into free batch slots,
   all-or-nothing reserving ``ceil((prompt + max_new) / block_size)``
   KV blocks, so a running sequence can never die of pool exhaustion;
   a queue head that cannot get its reservation waits (KV
   backpressure).
3. **prefill** — ONE chunk of the longest-waiting prefilling request.
   Chunked prefill bounds how long a huge prompt can starve the decode
   batch: decode advances every iteration regardless.
4. **decode** — one token for every sequence in the decode state, in
   one batched dispatch; finished sequences (``max_new_tokens`` / EOS)
   are retired and their blocks return to the pool.

Placement rides a :class:`~horovod_tpu.parallel.gspmd.GspmdPlan`
inference mesh: params and the KV pool replicated, the decode batch
sharded over the data axes when the slot count divides the world. Both
programs go through the PR-9 AOT machinery — one ``lower().compile()``
per shape signature, compiled-HLO collective accounting under
``serve_*`` labels, executables called directly.

Sampling defaults to greedy (argmax) — deterministic, which is what
makes "continuous-batched decode is bit-identical to a single-shot
decode" a testable contract (tests/test_serve.py). Real sampling
(``serve/sampling.py``: temperature / top-p / per-request seeds) rides
the SAME batched dispatch: per-slot seed/temperature/top-p arrays are
runtime inputs of the compiled programs, per-slot RNG keys are folded
from ``(seed, absolute token index)`` inside the program, and a slot
at temperature 0 still takes the bitwise argmax lane.

Prefix caching (``kvcache.PrefixCache``) short-circuits prefill:
admission maps a prompt's already-cached full blocks straight into the
sequence's block table (ref-counted shares; the one partially-reused
block is copy-on-write forked) and prefill resumes at the first
uncached token. A shared system prompt then costs one prefill total,
not one per request — the cached-prefill fraction
``bench_serve.py`` scores.
"""

import itertools
import logging
import queue
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import gspmd as gspmd_lib
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.serve import kvcache
from horovod_tpu.serve import sampling as sampling_lib
from horovod_tpu.telemetry import instruments as instruments_lib

logger = logging.getLogger("horovod_tpu")


class RequestError(RuntimeError):
    """A generate request failed (invalid, or the engine stopped)."""


class Request:
    """One generate request and its token stream.

    The engine appends events to a thread-safe queue as it produces
    them; :meth:`stream` (the HTTP handler's read side, and the test
    harness's) yields token ids until the terminal ``done``/``error``
    event. Timing fields (``arrival``, ``first_token_time``,
    ``token_times``) are stamped with the ENGINE's clock so fake-clock
    tests and the bench read one consistent timeline."""

    _ids = itertools.count()

    def __init__(self, tokens, max_new_tokens, eos_id=None,
                 request_id=None, sampling=None, trace=False):
        self.id = (next(self._ids) if request_id is None
                   else request_id)
        self.prompt = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.sampling = (sampling_lib.GREEDY if sampling is None
                         else sampling)
        self.generated = []
        self.state = "new"  # new|queued|prefill|decode|done|failed
        self.finish_reason = None
        self.error = None
        self.slot = None
        self.blocks = None
        self.prefilled = 0  # prompt tokens whose KV is in the pool
        self.cached_prompt_tokens = 0  # of those, served by prefix cache
        self.arrival = None
        self.admitted_at = None  # KV reservation granted (TTFT base 2)
        self.first_token_time = None
        self.token_times = []
        # request-scoped tracing (serve/tracing.py): ``trace`` is the
        # per-request force flag; ``trace`` the attached RequestTrace
        # (the router pre-attaches one for fleet requests). The engine
        # finishes only traces it began itself (_trace_owned).
        self.trace_requested = bool(trace)
        self.trace = None
        self._trace_owned = False
        self._trace_live = False  # counted in the engine's live total
        self._events = queue.Queue()

    def _emit(self, kind, value=None):
        self._events.put((kind, value))

    def stream(self, timeout=120.0):
        """Yield generated token ids as they arrive. Raises
        :class:`RequestError` when the request failed, ``TimeoutError``
        when the engine goes silent for ``timeout`` seconds."""
        while True:
            try:
                kind, value = self._events.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.id}: no event for {timeout:.0f}s "
                    f"(state {self.state})") from None
            if kind == "token":
                yield value
            elif kind == "done":
                return
            else:
                raise RequestError(value)

    def result(self, timeout=120.0):
        """Drain the stream; returns the full generated token list."""
        return list(self.stream(timeout=timeout))


class _AotProgram:
    """One serving program bound to the shared PR-9 AOT machinery
    (``gspmd.CompiledProgramCache``): serving shapes are static, so
    each program is exactly one ``lower().compile()``, its collectives
    accounted once under ``serve_*`` op labels, the executable called
    directly on every iteration."""

    def __init__(self, jitted):
        self._jitted = jitted
        self._cache = gspmd_lib.CompiledProgramCache(prefix="serve")

    def __call__(self, *args):
        return self._cache.executable(self._jitted, args)(*args)


class ServeEngine:
    """Continuous-batching scheduler over one model + paged KV pool.

    ``max_slots`` is the decode batch width (static — inactive slots
    are masked); ``prefill_chunk`` the per-iteration prompt chunk.
    ``clock`` is injectable for deterministic scheduler tests. Drive it
    either with :meth:`start`/:meth:`stop` (background thread — the
    HTTP frontend's mode) or by calling :meth:`step` yourself (the
    bench's and the fake-clock tests' mode)."""

    def __init__(self, model, params, kv_config, mesh=None, max_slots=4,
                 prefill_chunk=16, clock=time.monotonic, registry=None,
                 weights_version=None, prefix_caching=True,
                 name="default", tracer=None):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self._model = model
        self._kv = kv_config
        self._clock = clock
        self.name = str(name)
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        if mesh is None:
            try:
                mesh = mesh_lib.get_mesh()
            except RuntimeError:
                mesh = mesh_lib.build_mesh(jax.devices())
        self.plan = gspmd_lib.derive_plan(mesh)
        world = self.plan.world()
        self._rep = self.plan.sharding(P())
        if self.max_slots % world == 0:
            batch_spec = self.plan.batch_spec
        else:
            # an indivisible slot count replicates the decode batch —
            # correct everywhere, parallel nowhere; say so once
            logger.info(
                "serve: max_slots=%d does not divide the %d-way data "
                "mesh — decode batch replicated (pick a multiple for "
                "batch sharding)", self.max_slots, world)
            batch_spec = P()
        self._batch_sharding = self.plan.sharding(batch_spec)

        self.instruments = instruments_lib.serve_instruments(
            registry, replica=self.name)
        self.allocator = kvcache.BlockAllocator(kv_config.num_blocks)
        self.prefix_cache = (
            kvcache.PrefixCache(self.allocator, kv_config.block_size)
            if prefix_caching else None)
        # cumulative cached-prefill accounting (bench_serve.py's
        # cached-prefill fraction = cached / prompt tokens)
        self.prompt_tokens = 0
        self.cached_prefill_tokens = 0
        # per-slot scheduler state (host): block table rows, cached-token
        # counts, last sampled token, sampling knobs — the mirror of
        # what the device programs consume each iteration
        self._tables = np.zeros(
            (self.max_slots, kv_config.max_blocks_per_seq), np.int32)
        self._lengths = np.zeros((self.max_slots,), np.int32)
        self._last_token = np.zeros((self.max_slots,), np.int32)
        self._seeds = np.zeros((self.max_slots,), np.uint32)
        self._temps = np.zeros((self.max_slots,), np.float32)
        self._top_ps = np.ones((self.max_slots,), np.float32)
        self._slots = [None] * self.max_slots
        self._waiting = deque()
        self.draining = False  # refusing admission (drain / staging)

        # request tracing (serve/tracing.py). _live_traces is the hot-
        # path gate: with no traced request in flight the per-iteration
        # cost of tracing is one int comparison, and with tracer=None
        # (the default) no request ever records — dispatch behavior and
        # compiled programs are byte-identical either way (tracing is
        # pure host bookkeeping; tests assert this).
        self._tracer = tracer
        self._live_traces = 0

        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._staged = None  # (placed params, version) awaiting swap
        self.weights_version = weights_version
        self._stop = threading.Event()
        self._thread = None
        self._broken = None  # fatal engine error (donated pool lost)

        # run-level time attribution (bench_serve.py validates the sum
        # against wall clock, goodput-ledger style)
        self.time_breakdown = {"prefill": 0.0, "decode": 0.0,
                               "overhead": 0.0, "idle": 0.0}
        self._idle_since = None  # run-loop wait in progress since

        self._params = jax.device_put(params, self._rep)
        self._pool = jax.device_put(kvcache.init_pool(kv_config),
                                    self._rep)
        self._build_programs()

    # -- the two compiled programs -----------------------------------------
    def _build_programs(self):
        model, kv = self._model, self._kv
        max_context = kv.max_context

        def decode_fn(params, pool, tokens, lengths, tables,
                      seeds, temps, top_ps):
            # one new token per slot; slots with lengths == 0 are
            # inactive — their writes go to the null block and their
            # sampled token is ignored by the host
            active = lengths > 0
            ctx_k, ctx_v = kvcache.gather_context(pool, tables)
            cpos = kvcache.context_positions(lengths, max_context)
            logits, (nk, nv) = model.apply(
                {"params": params}, tokens[:, None],
                positions=lengths[:, None], train=False,
                kv_cache=(ctx_k, ctx_v, cpos))
            pool2 = kvcache.write_tokens(pool, tables, lengths, nk, nv,
                                         mask=active[:, None])
            # the token being sampled sits at absolute index len+1 (the
            # fed token occupies len) — the index the per-slot RNG key
            # is folded from (serve/sampling.py)
            nxt = sampling_lib.sample_tokens(
                logits[:, -1, :], seeds, lengths + 1, temps, top_ps)
            return nxt, pool2

        def prefill_fn(params, pool, tokens, start, total, table,
                       seed, temp, top_p):
            # one chunk of one sequence: tokens [1, C] (pad past the
            # prompt), absolute positions start..start+C-1; context =
            # the sequence's own already-prefilled tokens. Returns the
            # sampled successor of the LAST PROMPT token (absolute
            # index ``total``) — meaningful only on the final chunk
            # (the host knows which).
            c = tokens.shape[1]
            positions = (start + jnp.arange(c, dtype=jnp.int32))[None, :]
            valid = positions < total
            ctx_k, ctx_v = kvcache.gather_context(pool, table)
            cpos = kvcache.context_positions(
                jnp.reshape(start, (1,)), max_context)
            logits, (nk, nv) = model.apply(
                {"params": params}, tokens, positions=positions,
                train=False, kv_cache=(ctx_k, ctx_v, cpos))
            pool2 = kvcache.write_tokens(pool, table,
                                         jnp.reshape(start, (1,)),
                                         nk, nv, mask=valid)
            last = jnp.clip(total - 1 - start, 0, c - 1)
            last_logits = jax.lax.dynamic_index_in_dim(
                logits[0], last, axis=0, keepdims=False)
            nxt = sampling_lib.sample_tokens(
                last_logits[None, :], jnp.reshape(seed, (1,)),
                jnp.reshape(total, (1,)), jnp.reshape(temp, (1,)),
                jnp.reshape(top_p, (1,)))[0]
            return nxt, pool2

        rep, bsh = self._rep, self._batch_sharding
        # the pool is donated: it is the one big buffer, and decode runs
        # every iteration — without donation the pool would be double-
        # buffered across every dispatch
        self._decode = _AotProgram(jax.jit(
            decode_fn,
            in_shardings=(rep, rep, bsh, bsh, bsh, bsh, bsh, bsh),
            out_shardings=(rep, rep),
            donate_argnums=(1,)))
        self._prefill = _AotProgram(jax.jit(
            prefill_fn,
            in_shardings=(rep, rep, rep, rep, rep, rep, rep, rep, rep),
            out_shardings=(rep, rep),
            donate_argnums=(1,)))
        # the copy-on-write fork (prefix caching): src/dst are runtime
        # scalars, so ONE compile covers every forked pair
        self._fork = _AotProgram(jax.jit(
            kvcache.copy_block,
            in_shardings=(rep, rep, rep),
            out_shardings=rep,
            donate_argnums=(0,)))

    def _place_batch(self, x):
        return jax.device_put(np.asarray(x), self._batch_sharding)

    def _place_rep(self, x):
        return jax.device_put(np.asarray(x), self._rep)

    # -- request intake ------------------------------------------------------
    def submit(self, request):
        """Queue a request; returns it. Invalid requests (empty prompt,
        or a reservation no pool state could ever satisfy) fail
        immediately — loudly to the caller AND on the request's own
        stream."""
        kv = self._kv
        with self._work:
            request.arrival = self._clock()
            err = None
            if self._stop.is_set() or self._broken is not None:
                err = "serve engine is stopped"
            elif self.draining:
                err = "serve engine is draining"
            elif not request.prompt:
                err = "empty prompt"
            elif request.max_new_tokens < 1:
                err = "max_new_tokens must be >= 1"
            else:
                need = kv.blocks_for(len(request.prompt)
                                     + request.max_new_tokens)
                if (need > kv.max_blocks_per_seq
                        or need > self.allocator.capacity):
                    err = (f"request needs {need} KV blocks "
                           f"({len(request.prompt)} prompt + "
                           f"{request.max_new_tokens} new tokens), the "
                           f"pool allows min(max_blocks_per_seq="
                           f"{kv.max_blocks_per_seq}, capacity="
                           f"{self.allocator.capacity})")
            if err is not None:
                self._fail(request, err)
                raise RequestError(err)
            request.state = "queued"
            tr = request.trace  # router-attached (fleet requests)
            if tr is None and self._tracer is not None:
                tr = self._tracer.begin(request.id,
                                        force=request.trace_requested)
                if tr is not None:
                    request.trace = tr
                    request._trace_owned = True
            if tr is not None:
                request._trace_live = True
                self._live_traces += 1
                tr.phase(request.arrival, "queued")
                tr.event("submit", request.arrival, actor=self.name)
            self._waiting.append(request)
            self.instruments.submitted.inc()
            self.instruments.queue_depth.set(len(self._waiting))
            self._work.notify_all()
        return request

    def generate(self, tokens, max_new_tokens, eos_id=None,
                 sampling=None):
        """Convenience: build + submit, returns the :class:`Request`."""
        return self.submit(Request(tokens, max_new_tokens, eos_id=eos_id,
                                   sampling=sampling))

    @property
    def kv_config(self):
        return self._kv

    def blocks_needed(self, prompt_len, max_new_tokens):
        """KV blocks a request of this shape reserves at admission —
        the router's headroom arithmetic (serve/fleet/router.py)."""
        return self._kv.blocks_for(int(prompt_len) + int(max_new_tokens))

    def set_draining(self, flag):
        """Enter/leave the draining state: a draining engine refuses
        NEW admissions (submit fails loudly, queued requests stay
        queued) while in-flight sequences run to completion — the
        preempt-drain and weight-staging window ``/healthz`` reports
        as 503 ``draining`` (docs/SERVING.md, "Spot-drain runbook")."""
        with self._work:
            changed = self.draining != bool(flag)
            self.draining = bool(flag)
            if changed and self._live_traces:
                now = self._clock()
                for r in list(self._slots) + list(self._waiting):
                    if r is not None and r.trace is not None:
                        r.trace.event("drain", now, actor=self.name,
                                      on=self.draining)
            self._work.notify_all()

    # -- rolling weight reload ----------------------------------------------
    def install_weights(self, params, version=None):
        """Stage a new replicated parameter tree; the swap happens at
        the top of the next scheduler iteration — never inside a
        dispatch — so in-flight requests see a clean cut: tokens up to
        the swap from the old weights, tokens after it from the new,
        KV cache carried over (docs/SERVING.md, "Rolling reload")."""
        placed = jax.device_put(params, self._rep)
        with self._work:
            self._staged = (placed, version)
            self._work.notify_all()

    def _apply_staged_weights(self):
        if self._staged is not None:
            self._params, self.weights_version = self._staged
            self._staged = None
            logger.info("serve: weights swapped in (version %s), "
                        "%d request(s) in flight",
                        self.weights_version, self.active_count)
            return True
        return False

    # -- scheduler -----------------------------------------------------------
    def step(self):
        """One scheduler iteration; returns a stats dict (empty/falsy
        when there was nothing to do)."""
        if self._broken is not None:
            raise RuntimeError(
                "serve engine is broken (a dispatch failed after the "
                "pool was donated)") from self._broken
        t0 = self._clock()
        stats = {}
        compute_s = 0.0
        try:
            # admission is inside the failure boundary: the CoW fork it
            # may dispatch donates the pool exactly like the two
            # programs below
            with self._lock:
                swapped = False
                if self._staged is not None:
                    t_sw = self._clock()
                    swapped = self._apply_staged_weights()
                    t_sw_end = self._clock()
                    self.instruments.weight_swap_seconds.observe(
                        t_sw_end - t_sw)
                    if self._live_traces:
                        for r in self._slots:
                            if r is not None and r.trace is not None:
                                r.trace.span(
                                    "weight_swap", t_sw, t_sw_end,
                                    actor=self.name,
                                    version=self.weights_version)
                admitted = self._admit()
                prefill_req = min(
                    (r for r in self._slots
                     if r is not None and r.state == "prefill"),
                    key=lambda r: (r.arrival, r.id), default=None)
                decoding = [i for i, r in enumerate(self._slots)
                            if r is not None and r.state == "decode"]
            if swapped:
                stats["swapped"] = True
            if admitted:
                stats["admitted"] = len(admitted)
            if prefill_req is not None:
                t = self._clock()
                self._prefill_step(prefill_req)
                dt = self._clock() - t
                self.time_breakdown["prefill"] += dt
                compute_s += dt
                stats["prefilled"] = prefill_req.id
            if decoding:
                t = self._clock()
                self._decode_step(decoding)
                dt = self._clock() - t
                self.time_breakdown["decode"] += dt
                compute_s += dt
                stats["decoded"] = len(decoding)
        except Exception as e:
            # the pool was donated into the failed dispatch — the engine
            # cannot continue; fail every live request so clients unblock
            self._broken = e
            with self._lock:
                for r in list(self._slots) + list(self._waiting):
                    if r is not None and r.state not in ("done", "failed"):
                        self._fail(r, f"engine dispatch failed: {e}")
                self._waiting.clear()
            raise
        # whatever the iteration spent outside the two dispatches
        # (admission, bookkeeping, streaming) is scheduler overhead —
        # every second of a serving run lands in exactly one phase
        self.time_breakdown["overhead"] += max(
            0.0, self._clock() - t0 - compute_s)
        return stats

    def note_idle(self, seconds):
        """Attribute wait-for-work time (the run loop's, or the
        bench's open-loop sleeps) to the idle phase."""
        self.time_breakdown["idle"] += max(0.0, float(seconds))

    def _admit(self):
        admitted = []
        while self._waiting and not self.draining:
            req = self._waiting[0]
            free = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if free is None:
                break
            total = self._kv.blocks_for(len(req.prompt)
                                        + req.max_new_tokens)
            # prefix-cache lookup: map already-cached full prompt
            # blocks into this sequence's table instead of allocating
            # + re-prefilling them
            cached_len, shared = 0, []
            if self.prefix_cache is not None:
                cached_len, shared = self.prefix_cache.match(req.prompt)
                # the final prompt token always prefills: its logits
                # produce the first generated token
                cached_len = min(cached_len, len(req.prompt) - 1)
                # pin the match BEFORE any release(): an LRU eviction
                # under pressure may drop a matched entry whose sole
                # holder is the cache — unpinned, its block returns to
                # the free list and the retry alloc can hand it back
                # as a fresh WRITABLE block, duplicating it in this
                # sequence's table (decode writes into cached prefix)
                self.allocator.retain(shared)
            # a shared block the sequence will WRITE INTO (the trailing
            # block when the match is cut mid-block) must be forked —
            # classic copy-on-write
            cow = bool(shared) and \
                cached_len < len(shared) * self._kv.block_size
            n_fresh = total - len(shared) + (1 if cow else 0)
            blocks = self.allocator.alloc(n_fresh)
            if blocks is None and self.prefix_cache is not None:
                # cache-held blocks are reclaimable memory: drop LRU
                # entries until the reservation fits (live sequences'
                # refs — and the pin above — keep their blocks safe)
                dropped = self.prefix_cache.release(n_fresh)
                blocks = self.allocator.alloc(n_fresh)
                if dropped and req.trace is not None:
                    req.trace.event("cache_evict", self._clock(),
                                    actor=self.name, entries=dropped)
            if blocks is None:
                if shared:
                    self.allocator.free(shared)  # drop the pin
                if req.trace is not None:
                    req.trace.phase(self._clock(), "kv_wait")
                break  # FIFO head backpressured on KV blocks
            if cow:
                fork = blocks[0]
                self._pool = self._fork(
                    self._pool, self._place_rep(np.int32(shared[-1])),
                    self._place_rep(np.int32(fork)))
                self.allocator.free([shared[-1]])  # seq's ref only
                seq_blocks = shared[:-1] + [fork] + blocks[1:]
            else:
                seq_blocks = shared + blocks
            self._waiting.popleft()
            req.slot, req.blocks = free, seq_blocks
            req.state = "prefill"
            req.prefilled = cached_len
            req.cached_prompt_tokens = cached_len
            now = self._clock()
            req.admitted_at = now
            if req.trace is not None:
                req.trace.phase(now, "prefilling")
                req.trace.event("admitted", now, actor=self.name,
                                cached_tokens=cached_len,
                                blocks=len(seq_blocks), cow=cow)
            self._slots[free] = req
            row = np.zeros((self._kv.max_blocks_per_seq,), np.int32)
            row[:len(seq_blocks)] = seq_blocks
            self._tables[free] = row
            self._lengths[free] = cached_len
            self._last_token[free] = 0
            sp = req.sampling
            self._seeds[free] = np.uint32(int(sp.seed) & 0xFFFFFFFF)
            self._temps[free] = np.float32(sp.temperature)
            self._top_ps[free] = np.float32(sp.top_p)
            self.prompt_tokens += len(req.prompt)
            if cached_len:
                self.cached_prefill_tokens += cached_len
                self.instruments.cached_prefill_tokens.inc(cached_len)
            admitted.append(req)
        self.instruments.queue_depth.set(len(self._waiting))
        self.instruments.kv_blocks.set(self.allocator.in_use)
        return admitted

    def _prefill_step(self, req):
        start = req.prefilled
        c = self.prefill_chunk
        chunk = req.prompt[start:start + c]
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :len(chunk)] = chunk
        tr = req.trace
        t0 = self._clock() if tr is not None else 0.0
        nxt, self._pool = self._prefill(
            self._params, self._pool, self._place_rep(tokens),
            self._place_rep(np.int32(start)),
            self._place_rep(np.int32(len(req.prompt))),
            self._place_rep(self._tables[req.slot:req.slot + 1]),
            self._place_rep(self._seeds[req.slot]),
            self._place_rep(self._temps[req.slot]),
            self._place_rep(self._top_ps[req.slot]))
        req.prefilled = min(start + c, len(req.prompt))
        self._lengths[req.slot] = req.prefilled
        final = req.prefilled >= len(req.prompt)
        # final chunk: the last prompt token's logits yield the
        # first generated token — TTFT stops here
        tok = int(jax.device_get(nxt)) if final else None
        if tr is not None:
            # recorded here, BEFORE _append_token can retire the
            # request and finish the trace — else the final compute
            # span would be lost
            tr.span("prefill", t0, self._clock(), actor=self.name,
                    chunk=[start, req.prefilled])
        if final:
            req.state = "decode"
            self._last_token[req.slot] = tok
            if self.prefix_cache is not None:
                # every full prompt block is now immutable pool
                # content — index it for later prompts
                n_full = len(req.prompt) // self._kv.block_size
                if n_full:
                    with self._lock:
                        self.prefix_cache.insert(
                            req.prompt,
                            [int(b) for b in
                             self._tables[req.slot][:n_full]])
            now = self._clock()
            if tr is not None:
                tr.phase(now, "decoding")
            self._append_token(req, tok, now)

    def _decode_step(self, decoding):
        active = np.zeros((self.max_slots,), bool)
        active[decoding] = True
        lengths = np.where(active, self._lengths, 0).astype(np.int32)
        traced = self._live_traces > 0  # the one hot-path check
        t0 = self._clock() if traced else 0.0
        nxt, self._pool = self._decode(
            self._params, self._pool,
            self._place_batch(self._last_token),
            self._place_batch(lengths),
            self._place_batch(self._tables),
            self._place_batch(self._seeds),
            self._place_batch(self._temps),
            self._place_batch(self._top_ps))
        nxt = np.asarray(jax.device_get(nxt))
        now = self._clock()
        if traced:
            # before the append loop — _append_token may retire a
            # request and finish its trace
            for i in decoding:
                tr = self._slots[i].trace
                if tr is not None:
                    tr.span("decode", t0, now, actor=self.name,
                            batch=len(decoding))
        for i in decoding:
            req = self._slots[i]
            self._lengths[i] += 1  # the fed token's KV is now cached
            tok = int(nxt[i])
            self._last_token[i] = tok
            self._append_token(req, tok, now)

    def _append_token(self, req, tok, now):
        req.generated.append(tok)
        req.token_times.append(now)
        if req.first_token_time is None:
            req.first_token_time = now
            self.instruments.ttft_seconds.observe(now - req.arrival)
            if req.admitted_at is not None:
                # second TTFT base: admission -> first token isolates
                # prefill; the arrival-based histogram above folds
                # queue wait in (docs/OBSERVABILITY.md)
                self.instruments.ttft_admission_seconds.observe(
                    now - req.admitted_at)
        else:
            self.instruments.inter_token_seconds.observe(
                now - req.token_times[-2])
        self.instruments.tokens.inc()
        req._emit("token", tok)
        if req.eos_id is not None and tok == req.eos_id:
            self._retire(req, "eos")
        elif len(req.generated) >= req.max_new_tokens:
            self._retire(req, "length")

    def _retire(self, req, reason):
        with self._work:
            self.allocator.free(req.blocks)
            self._slots[req.slot] = None
            self._tables[req.slot] = 0
            self._lengths[req.slot] = 0
            self._last_token[req.slot] = 0
            self._seeds[req.slot] = 0
            self._temps[req.slot] = 0.0
            self._top_ps[req.slot] = 1.0
            req.blocks = None
            req.state = "done"
            req.finish_reason = reason
            self.instruments.completed.inc()
            self.instruments.kv_blocks.set(self.allocator.in_use)
            req._emit("done")
            self._finish_trace(req, "done", reason=reason)
            self._work.notify_all()  # blocks freed: admission may proceed

    def _fail(self, req, message):
        req.state = "failed"
        req.error = message
        self.instruments.failed.inc()
        req._emit("error", message)
        self._finish_trace(req, "failed", error=message)

    def _finish_trace(self, req, outcome, **attrs):
        """Close out a request's trace participation. Only a counted
        request decrements the live total (a submit-validation failure
        never incremented), and only a trace this engine began is
        finished here — the router finishes fleet-owned traces (a
        retryable failure is a hop, not the end of the request)."""
        tr = req.trace
        if tr is None:
            return
        now = self._clock()
        tr.event(outcome, now, actor=self.name, **attrs)
        if req._trace_live:
            req._trace_live = False
            self._live_traces = max(0, self._live_traces - 1)
        if req._trace_owned:
            req._trace_owned = False
            if self._tracer is not None:
                self._tracer.finish(tr, end=now)

    # -- run loop -------------------------------------------------------------
    @property
    def active_count(self):
        return sum(1 for r in self._slots if r is not None)

    @property
    def queue_depth(self):
        return len(self._waiting)

    def _has_work_locked(self):
        if self._staged is not None:
            return True
        if any(r is not None for r in self._slots):
            return True
        # a waiting request counts as work only if admission could
        # succeed — a backpressured head must not busy-spin (a draining
        # engine admits nothing, so its queue is not work either)
        if self._waiting and not self.draining:
            req = self._waiting[0]
            need = self._kv.blocks_for(len(req.prompt)
                                       + req.max_new_tokens)
            # sole-reference cache entries count as reclaimable
            # headroom (an entry a live sequence also maps frees no
            # block when released)
            reclaimable = (self.prefix_cache.reclaimable()
                           if self.prefix_cache is not None else 0)
            return (any(r is None for r in self._slots)
                    and need <= self.allocator.available + reclaimable)
        return False

    def _loop(self):
        while not self._stop.is_set():
            stats = self.step()
            if not stats:
                with self._work:
                    if self._stop.is_set() or self._has_work_locked():
                        continue
                    t = self._clock()
                    self._idle_since = t
                    self._work.wait(timeout=0.05)
                    self._idle_since = None
                    self.note_idle(self._clock() - t)

    def attribution_snapshot(self):
        """``time_breakdown`` including the run loop's in-progress idle
        wait, exact as of now — so a measurement window boundary (a
        bench's, a fleet's per-replica window) doesn't mis-charge the
        wait tick it lands inside."""
        snap = dict(self.time_breakdown)
        since = self._idle_since
        if since is not None:
            snap["idle"] += max(0.0, self._clock() - since)
        return snap

    def start(self):
        """Run the scheduler on a background thread (the HTTP
        frontend's mode)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd_serve_engine",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop the scheduler; queued and in-flight requests fail with
        "engine stopped" so no client blocks forever."""
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        with self._work:
            for req in list(self._waiting) + [
                    r for r in self._slots if r is not None]:
                if req.state not in ("done", "failed"):
                    if req.blocks:
                        self.allocator.free(req.blocks)
                        req.blocks = None
                    if req.slot is not None:
                        self._slots[req.slot] = None
                    self._fail(req, "serve engine stopped")
            self._waiting.clear()
