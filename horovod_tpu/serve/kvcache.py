"""Paged KV cache: a block pool + per-sequence block tables.

The serving-memory problem (docs/SERVING.md): a dense per-slot cache
costs ``max_batch × max_seq_len`` KV slots whether or not a sequence
uses them — at production batch sizes HBM fills with padding. The
established fix (vLLM's PagedAttention) is virtual memory for KV: one
global pool of fixed-size **blocks** (``block_size`` tokens each), a
per-sequence **block table** mapping its logical token positions onto
pool blocks, and a host-side allocator handing blocks out on admission
and reclaiming them on eviction. Pool memory then scales with **live
tokens**, not ``max_seq_len × max_batch``; fragmentation is bounded by
one partial block per sequence.

Layout: ``pool["k"]``/``pool["v"]`` are ``[L, N_blocks, block_size, H,
D]`` device arrays (one stacked allocation per tensor — layers index
dim 0, so the whole cache is two arrays however deep the model).
**Block 0 is the null block**: the allocator never hands it out, pad
writes are routed into it, and inactive batch slots' tables point at it
— gathered garbage is masked out by the position sentinel
(:data:`PAD_POSITION`, larger than any real position, so the
absolute-position causal mask in ``models/transformer.py`` zeroes it
exactly).

Everything device-side here is a pure function over arrays —
``serve/engine.py`` composes them inside its jitted prefill/decode
programs; only :class:`BlockAllocator` is host state.
"""

import dataclasses
from collections import deque
from typing import Any

import jax.numpy as jnp
import numpy as np

# larger than any real token position: a context slot carrying this
# position is in every query's "future" and masks to exactly -inf
PAD_POSITION = np.int32(2 ** 30)
NULL_BLOCK = 0


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape of the pool. ``num_blocks`` INCLUDES the reserved
    null block, so usable capacity is ``num_blocks - 1`` blocks."""

    num_blocks: int
    block_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    max_blocks_per_seq: int
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved null block)")
        if self.max_blocks_per_seq < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")

    @property
    def max_context(self):
        """Longest sequence (prompt + generated) a block table can map."""
        return self.max_blocks_per_seq * self.block_size

    def blocks_for(self, num_tokens):
        """Blocks needed to hold ``num_tokens`` cached tokens."""
        return -(-int(num_tokens) // self.block_size)

    def pool_bytes(self):
        """K+V pool bytes — the paged-KV sizing math of docs/SERVING.md."""
        per_slot = self.num_heads * self.head_dim * \
            jnp.dtype(self.dtype).itemsize
        return 2 * self.num_layers * self.num_blocks * self.block_size * \
            per_slot


def init_pool(cfg):
    shape = (cfg.num_layers, cfg.num_blocks, cfg.block_size,
             cfg.num_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def gather_context(pool, block_table):
    """Materialize the cached context of each sequence for attention:
    ``block_table`` ``[B, max_blocks_per_seq]`` int32 →
    ``(k, v)`` each ``[L, B, max_context, H, D]``. Pool slots behind pad
    table entries (the null block) come back as garbage — the position
    sentinel from :func:`context_positions` masks them exactly."""
    k = pool["k"][:, block_table]   # [L, B, mbps, bs, H, D]
    v = pool["v"][:, block_table]
    L, B = k.shape[0], k.shape[1]
    h, d = k.shape[-2], k.shape[-1]
    return k.reshape(L, B, -1, h, d), v.reshape(L, B, -1, h, d)


def context_positions(lengths, max_context):
    """``[B, max_context]`` absolute positions of the gathered context:
    slot ``j`` of a sequence with ``lengths[i]`` cached tokens holds
    token ``j`` (blocks fill in order), so positions are ``0..len-1``
    and :data:`PAD_POSITION` beyond."""
    pos = jnp.arange(max_context, dtype=jnp.int32)[None, :]
    return jnp.where(pos < lengths[:, None], pos,
                     jnp.int32(PAD_POSITION))


def write_tokens(pool, block_table, start, new_k, new_v, mask=None):
    """Scatter freshly computed K/V into the pool.

    ``new_k``/``new_v`` are ``[L, B, S_q, H, D]`` (the transformer's
    incremental-decode output); token ``t`` of sequence ``i`` lands at
    absolute position ``p = start[i] + t`` → pool slot
    ``(block_table[i, p // block_size], p % block_size)``. ``mask``
    ``[B, S_q]`` (False = pad token / inactive slot) routes masked
    writes into the null block — the pool write stays static-shaped and
    the garbage is invisible by construction. Returns the new pool."""
    bs = pool["k"].shape[2]
    mbps = block_table.shape[1]
    S = new_k.shape[2]
    p = start[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B,S]
    # clip before the table lookup: a masked position may point past the
    # table (it is about to be routed to the null block anyway)
    blk = jnp.take_along_axis(block_table,
                              jnp.clip(p // bs, 0, mbps - 1), axis=1)
    off = p % bs
    if mask is not None:
        blk = jnp.where(mask, blk, NULL_BLOCK)
        off = jnp.where(mask, off, 0)
    return {"k": pool["k"].at[:, blk, off].set(new_k),
            "v": pool["v"].at[:, blk, off].set(new_v)}


class BlockAllocator:
    """Host-side free list over pool blocks ``1..num_blocks-1``.

    ``alloc`` is all-or-nothing — a request that cannot get its full
    reservation gets ``None`` and stays queued (the engine's KV
    backpressure); ``free`` returns an eviction's blocks to the pool.
    Not thread-safe by itself: the engine mutates it only under its
    scheduler lock."""

    def __init__(self, num_blocks):
        self.capacity = int(num_blocks) - 1
        self._free = deque(range(1, int(num_blocks)))
        self._out = set()

    @property
    def available(self):
        return len(self._free)

    @property
    def in_use(self):
        return len(self._out)

    def alloc(self, n):
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.popleft() for _ in range(n)]
        self._out.update(blocks)
        return blocks

    def free(self, blocks):
        for b in blocks:
            if b not in self._out:
                raise ValueError(
                    f"double free of KV block {b} (allocated: no)")
            self._out.discard(b)
            self._free.append(b)
