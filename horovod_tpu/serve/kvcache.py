"""Paged KV cache: a block pool + per-sequence block tables.

The serving-memory problem (docs/SERVING.md): a dense per-slot cache
costs ``max_batch × max_seq_len`` KV slots whether or not a sequence
uses them — at production batch sizes HBM fills with padding. The
established fix (vLLM's PagedAttention) is virtual memory for KV: one
global pool of fixed-size **blocks** (``block_size`` tokens each), a
per-sequence **block table** mapping its logical token positions onto
pool blocks, and a host-side allocator handing blocks out on admission
and reclaiming them on eviction. Pool memory then scales with **live
tokens**, not ``max_seq_len × max_batch``; fragmentation is bounded by
one partial block per sequence.

Layout: ``pool["k"]``/``pool["v"]`` are ``[L, N_blocks, block_size, H,
D]`` device arrays (one stacked allocation per tensor — layers index
dim 0, so the whole cache is two arrays however deep the model).
**Block 0 is the null block**: the allocator never hands it out, pad
writes are routed into it, and inactive batch slots' tables point at it
— gathered garbage is masked out by the position sentinel
(:data:`PAD_POSITION`, larger than any real position, so the
absolute-position causal mask in ``models/transformer.py`` zeroes it
exactly).

**Prefix caching** (vLLM-style automatic prompt caching) rides the
same substrate: blocks are REF-COUNTED (:class:`BlockAllocator` keeps
a count per block, not a set), a :class:`PrefixCache` indexes full
prompt blocks by a chained content hash, and a new sequence whose
prompt starts with an already-cached block chain maps those pool
blocks into its own table instead of re-prefilling them. Shared
blocks are read-only by construction — every token position inside
them is already written and never rewritten; the one partial block a
prefix match can touch is forked first (:func:`copy_block`, classic
copy-on-write) so the writer gets a private copy.

Everything device-side here is a pure function over arrays —
``serve/engine.py`` composes them inside its jitted prefill/decode
programs; only :class:`BlockAllocator` and :class:`PrefixCache` are
host state.
"""

import dataclasses
from collections import OrderedDict, deque
from typing import Any

import jax.numpy as jnp
import numpy as np

# larger than any real token position: a context slot carrying this
# position is in every query's "future" and masks to exactly -inf
PAD_POSITION = np.int32(2 ** 30)
NULL_BLOCK = 0


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape of the pool. ``num_blocks`` INCLUDES the reserved
    null block, so usable capacity is ``num_blocks - 1`` blocks."""

    num_blocks: int
    block_size: int
    num_layers: int
    num_heads: int
    head_dim: int
    max_blocks_per_seq: int
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved null block)")
        if self.max_blocks_per_seq < 1:
            raise ValueError("max_blocks_per_seq must be >= 1")

    @property
    def max_context(self):
        """Longest sequence (prompt + generated) a block table can map."""
        return self.max_blocks_per_seq * self.block_size

    def blocks_for(self, num_tokens):
        """Blocks needed to hold ``num_tokens`` cached tokens."""
        return -(-int(num_tokens) // self.block_size)

    def pool_bytes(self):
        """K+V pool bytes — the paged-KV sizing math of docs/SERVING.md."""
        per_slot = self.num_heads * self.head_dim * \
            jnp.dtype(self.dtype).itemsize
        return 2 * self.num_layers * self.num_blocks * self.block_size * \
            per_slot


def init_pool(cfg):
    shape = (cfg.num_layers, cfg.num_blocks, cfg.block_size,
             cfg.num_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def gather_context(pool, block_table):
    """Materialize the cached context of each sequence for attention:
    ``block_table`` ``[B, max_blocks_per_seq]`` int32 →
    ``(k, v)`` each ``[L, B, max_context, H, D]``. Pool slots behind pad
    table entries (the null block) come back as garbage — the position
    sentinel from :func:`context_positions` masks them exactly."""
    k = pool["k"][:, block_table]   # [L, B, mbps, bs, H, D]
    v = pool["v"][:, block_table]
    L, B = k.shape[0], k.shape[1]
    h, d = k.shape[-2], k.shape[-1]
    return k.reshape(L, B, -1, h, d), v.reshape(L, B, -1, h, d)


def context_positions(lengths, max_context):
    """``[B, max_context]`` absolute positions of the gathered context:
    slot ``j`` of a sequence with ``lengths[i]`` cached tokens holds
    token ``j`` (blocks fill in order), so positions are ``0..len-1``
    and :data:`PAD_POSITION` beyond."""
    pos = jnp.arange(max_context, dtype=jnp.int32)[None, :]
    return jnp.where(pos < lengths[:, None], pos,
                     jnp.int32(PAD_POSITION))


def write_tokens(pool, block_table, start, new_k, new_v, mask=None):
    """Scatter freshly computed K/V into the pool.

    ``new_k``/``new_v`` are ``[L, B, S_q, H, D]`` (the transformer's
    incremental-decode output); token ``t`` of sequence ``i`` lands at
    absolute position ``p = start[i] + t`` → pool slot
    ``(block_table[i, p // block_size], p % block_size)``. ``mask``
    ``[B, S_q]`` (False = pad token / inactive slot) routes masked
    writes into the null block — the pool write stays static-shaped and
    the garbage is invisible by construction. Returns the new pool."""
    bs = pool["k"].shape[2]
    mbps = block_table.shape[1]
    S = new_k.shape[2]
    p = start[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B,S]
    # clip before the table lookup: a masked position may point past the
    # table (it is about to be routed to the null block anyway)
    blk = jnp.take_along_axis(block_table,
                              jnp.clip(p // bs, 0, mbps - 1), axis=1)
    off = p % bs
    if mask is not None:
        blk = jnp.where(mask, blk, NULL_BLOCK)
        off = jnp.where(mask, off, 0)
    return {"k": pool["k"].at[:, blk, off].set(new_k),
            "v": pool["v"].at[:, blk, off].set(new_v)}


class BlockAllocator:
    """Host-side REF-COUNTED free list over pool blocks
    ``1..num_blocks-1``.

    ``alloc`` is all-or-nothing — a request that cannot get its full
    reservation gets ``None`` and stays queued (the engine's KV
    backpressure) — and hands out blocks at refcount 1. Prefix sharing
    adds holders via :meth:`retain`; ``free`` drops one reference per
    listed block and returns it to the pool only when the LAST holder
    lets go. Freeing (or retaining) a block that is not allocated
    raises loudly instead of silently corrupting the free list —
    under refcounting a quiet double free would hand the same block to
    two live sequences and cross their caches. Not thread-safe by
    itself: the engine mutates it only under its scheduler lock."""

    def __init__(self, num_blocks):
        self.capacity = int(num_blocks) - 1
        self._free = deque(range(1, int(num_blocks)))
        self._refs = {}  # block id -> reference count (> 0)

    @property
    def available(self):
        return len(self._free)

    @property
    def in_use(self):
        return len(self._refs)

    def alloc(self, n):
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.popleft() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def retain(self, blocks):
        """Add one reference per listed block (a new sequence mapping
        shared prefix blocks, or the prefix cache indexing them)."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(
                    f"retain of KV block {b} (allocated: no)")
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks):
        """Drop one reference per listed block. Validates the WHOLE
        list first — a bad free raises before any block moves, so the
        free list is never half-updated."""
        dropping = {}
        for b in blocks:
            if self._refs.get(b, 0) - dropping.get(b, 0) <= 0:
                raise ValueError(
                    f"double free of KV block {b} (allocated: "
                    f"{'yes' if b in self._refs else 'no'})")
            dropping[b] = dropping.get(b, 0) + 1
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._free.append(b)

    def ref_count(self, block):
        """Current reference count (0 = not allocated)."""
        return self._refs.get(block, 0)

    def is_shared(self, block):
        """True when more than one holder maps this block (a writer
        must copy-on-write before touching it)."""
        return self._refs.get(block, 0) > 1


def copy_block(pool, src, dst):
    """Device-side block copy — the copy-on-write fork. ``src``/``dst``
    are int32 scalars (traced inside the engine's jitted admission
    program: one compile covers every (src, dst) pair). The forked
    writer then owns ``dst`` outright; ``src`` stays shared and
    read-only."""
    return {"k": pool["k"].at[:, dst].set(pool["k"][:, src]),
            "v": pool["v"].at[:, dst].set(pool["v"][:, src])}


class PrefixCache:
    """Content-addressed index of FULL prompt blocks for prefix reuse.

    Keying is vLLM's chained block hash: block ``i`` of a prompt is
    keyed by ``hash((key_{i-1}, tokens[i*bs:(i+1)*bs]))`` — the key
    commits to the whole prefix through this block, so two prompts
    share a cache entry iff they are token-identical up to and
    including it. Only full blocks are indexed (a partial block is
    still being written; full prompt blocks are never rewritten), and
    :meth:`insert` happens after the block's prefill chunk completed,
    so every indexed block is immutable pool content.

    The cache holds its OWN reference on each indexed block — a block
    can outlive the sequence that prefilled it and seed later requests
    (that is the whole point of a system-prompt cache). Memory
    pressure flows the other way through :meth:`release`: when the
    allocator cannot cover an admission, least-recently-matched
    entries are dropped until it can (live sequences' own references
    keep their blocks safe — only the cache's claim is released).

    Host state, engine-lock discipline, like the allocator."""

    def __init__(self, allocator, block_size, capacity_blocks=None):
        self._alloc = allocator
        self._bs = int(block_size)
        self._cap = capacity_blocks
        self._entries = OrderedDict()  # chain key -> block id
        self.hit_tokens = 0   # prompt tokens served from cache
        self.miss_tokens = 0  # prompt tokens that had to prefill

    @property
    def size(self):
        return len(self._entries)

    def reclaimable(self):
        """Blocks :meth:`release` could actually return to the pool
        right now: entries whose block has no holder besides the cache.
        An entry a live sequence also references frees nothing when
        evicted (the sequence's reference keeps the block allocated),
        so it is not headroom."""
        return sum(1 for b in self._entries.values()
                   if self._alloc.ref_count(b) == 1)

    def _keys(self, tokens):
        key, out = None, []
        for i in range(len(tokens) // self._bs):
            key = hash((key, tuple(tokens[i * self._bs:
                                          (i + 1) * self._bs])))
            out.append(key)
        return out

    def match(self, tokens):
        """Longest indexed full-block chain prefixing ``tokens`` →
        ``(cached_token_count, [block ids])``. Takes NO references —
        the caller retains before the engine lock is released."""
        blocks = []
        for key in self._keys(tokens):
            block = self._entries.get(key)
            if block is None:
                break
            self._entries.move_to_end(key)  # LRU touch
            blocks.append(block)
        return len(blocks) * self._bs, blocks

    def insert(self, tokens, table_blocks):
        """Index a freshly prefilled prompt's full blocks
        (``table_blocks`` = the sequence's block-table prefix). Chains
        already present keep their existing block (first writer wins —
        identical content by construction); new tails take a cache
        reference on the sequence's own block."""
        keys = self._keys(tokens)
        for key, block in zip(keys, table_blocks):
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            self._alloc.retain([block])
            self._entries[key] = block
        while self._cap is not None and len(self._entries) > self._cap:
            self._evict_lru()

    def _evict_lru(self):
        key, block = next(iter(self._entries.items()))
        del self._entries[key]
        self._alloc.free([block])

    def release(self, need):
        """Drop LRU entries until the allocator can cover ``need``
        blocks (or the cache is empty). Returns entries dropped."""
        dropped = 0
        while self._alloc.available < need and self._entries:
            self._evict_lru()
            dropped += 1
        return dropped

    def clear(self):
        while self._entries:
            self._evict_lru()
