"""``hvd-serve`` — serve a checkpointed transformer over HTTP.

    hvd-serve --ckpt-dir /ckpts --port 8000 \\
        --num-layers 4 --num-heads 8 --d-model 512 --d-ff 2048

Loads the newest manifest-complete checkpoint's params straight onto
the local inference mesh (N-host training world → M-device serving
mesh, no conversion step), starts the continuous-batching engine and
the streaming frontend, and keeps polling the checkpoint dir for newer
manifests — a training job committing checkpoints into the same
directory rolls new weights into serving without a restart
(docs/SERVING.md).

The model architecture is not recorded in the checkpoint (params are a
plain tree), so the flags must restate it. A manifest whose ``meta``
carries a ``model_config`` dict (anything the trainer chose to record
via ``save_sharded(meta=...)``) is cross-checked against the flags and
mismatches fail loudly instead of serving garbage.
"""

import argparse
import logging
import os
import signal
import sys
import threading

logger = logging.getLogger("horovod_tpu")


def build_parser():
    p = argparse.ArgumentParser(
        prog="hvd-serve",
        description="continuous-batching inference server fed from "
                    "horovod_tpu sharded checkpoints")
    p.add_argument("--ckpt-dir", required=True,
                   help="checkpoint root (ckpt-<step>/ dirs with "
                        "MANIFEST.json)")
    p.add_argument("--step", type=int, default=None,
                   help="serve this exact step (default: newest "
                        "complete, with validation fallback)")
    p.add_argument("--addr", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    # model architecture (must match the checkpoint)
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--d-ff", type=int, default=2048)
    p.add_argument("--dtype", default="bfloat16",
                   choices=("bfloat16", "float32"))
    # serving shape
    p.add_argument("--max-slots", type=int, default=8,
                   help="decode batch width (multiples of the device "
                        "count shard the batch over the mesh)")
    p.add_argument("--prefill-chunk", type=int, default=256)
    p.add_argument("--block-size", type=int, default=16,
                   help="KV tokens per pool block")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="KV pool blocks incl. the null block "
                        "(default: max_slots * max_blocks_per_seq + 1)")
    p.add_argument("--max-seq-len", type=int, default=2048,
                   help="longest prompt+generation a request may map")
    p.add_argument("--reload-poll-seconds", type=float, default=5.0)
    p.add_argument("--no-reload", action="store_true",
                   help="serve the startup checkpoint forever")
    # fleet
    p.add_argument("--fleet", type=int, default=1,
                   help="number of engine replicas; > 1 splits the "
                        "local devices into disjoint submeshes and "
                        "serves them behind the fleet router "
                        "(docs/SERVING.md, 'Serve fleet')")
    p.add_argument("--grace", type=float, default=None,
                   help="preemption drain budget per replica in "
                        "seconds (default: HOROVOD_GRACE_SECONDS); "
                        "notice sources come from the standard "
                        "HOROVOD_PREEMPT_NOTICE_FILE/_URL env knobs")
    p.add_argument("--trace-dir", default=None,
                   help="write per-request trace dumps here on "
                        "shutdown (ndjson for `hvd-doctor serve` plus "
                        "a merged Chrome trace); also arms tracing as "
                        "if HOROVOD_SERVE_TRACE_DIR were set — "
                        "sampling/SLO come from HOROVOD_SERVE_TRACE "
                        "and HOROVOD_SERVE_TRACE_SLO_MS")
    return p


def _check_meta(meta, args):
    """Fail loudly when the manifest records an architecture that
    contradicts the flags (best effort: trainers opt in via meta)."""
    mc = (meta or {}).get("model_config")
    if not isinstance(mc, dict):
        return
    flags = {"vocab_size": args.vocab_size, "num_layers": args.num_layers,
             "num_heads": args.num_heads, "d_model": args.d_model,
             "d_ff": args.d_ff}
    bad = {k: (mc[k], v) for k, v in flags.items()
           if k in mc and int(mc[k]) != int(v)}
    if bad:
        raise SystemExit(
            f"hvd-serve: checkpoint manifest records model_config "
            f"{ {k: a for k, (a, _) in bad.items()} }, flags say "
            f"{ {k: b for k, (_, b) in bad.items()} } — refusing to "
            "serve a mismatched architecture")


def main(argv=None):
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s: %(message)s")

    import jax
    import jax.numpy as jnp

    from horovod_tpu.elastic import preempt as preempt_lib
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.serve import engine as engine_lib
    from horovod_tpu.serve import kvcache, loader
    from horovod_tpu.serve.fleet import FleetRouter, FleetServer
    from horovod_tpu.serve.server import ServeServer
    from horovod_tpu.serve.tracing import ServeTracer

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    cfg = TransformerConfig(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model, d_ff=args.d_ff,
        dtype=dtype, causal=True)
    model = Transformer(cfg)

    target = loader.abstract_params(model, seq_len=8)
    step, params, meta = loader.load_params(args.ckpt_dir, target,
                                            step=args.step)
    _check_meta(meta, args)
    logger.info("hvd-serve: loaded params of ckpt step %d from %s",
                step, args.ckpt_dir)

    mbps = -(-args.max_seq_len // args.block_size)
    num_blocks = (args.num_blocks if args.num_blocks is not None
                  else args.max_slots * mbps + 1)
    kv = kvcache.KVCacheConfig(
        num_blocks=num_blocks, block_size=args.block_size,
        num_layers=args.num_layers, num_heads=args.num_heads,
        head_dim=args.d_model // args.num_heads,
        max_blocks_per_seq=mbps, dtype=dtype)
    logger.info("hvd-serve: KV pool %d blocks x %d tokens (%.1f MiB)",
                num_blocks, args.block_size, kv.pool_bytes() / 2 ** 20)

    # tracing is opt-in (env knobs / --trace-dir); tracer=None keeps
    # the request path byte-identical to an untraced build
    tracer = ServeTracer.from_env(out_dir=args.trace_dir)
    if tracer is not None:
        logger.info("hvd-serve: request tracing armed (sample=%.3g, "
                    "slo_ms=%s, dir=%s)", tracer.sample, tracer.slo_ms,
                    tracer.out_dir)

    devs = jax.devices()
    router = None
    if args.fleet > 1:
        # One replica per disjoint device submesh: concurrent SPMD
        # dispatch over shared devices can deadlock at collectives
        # (docs/SERVING.md, "Serve fleet"). A host-wide spot notice
        # drains every replica — the whole VM is doomed.
        if args.fleet > len(devs):
            raise SystemExit(
                f"hvd-serve: --fleet {args.fleet} needs at least one "
                f"device per replica ({len(devs)} available)")
        per = len(devs) // args.fleet
        notice_file = os.environ.get(preempt_lib.NOTICE_FILE_ENV)
        notice_url = os.environ.get(preempt_lib.NOTICE_URL_ENV)
        router = FleetRouter(grace=args.grace, tracer=tracer)
        engines = []
        for i in range(args.fleet):
            sub = mesh_lib.build_mesh(devs[i * per:(i + 1) * per])
            eng = engine_lib.ServeEngine(
                model, params, kv, mesh=sub, max_slots=args.max_slots,
                prefill_chunk=args.prefill_chunk, weights_version=step,
                name=f"r{i}")
            router.add_replica(f"r{i}", eng, notice_file=notice_file,
                               notice_url=notice_url)
            engines.append(eng)
        router.start()
        target_for_reload, frontend = router, FleetServer(
            router, addr=args.addr, port=args.port)
    else:
        mesh = mesh_lib.build_mesh(devs)
        eng = engine_lib.ServeEngine(
            model, params, kv, mesh=mesh, max_slots=args.max_slots,
            prefill_chunk=args.prefill_chunk, weights_version=step,
            tracer=tracer)
        eng.start()
        target_for_reload, frontend = eng, ServeServer(
            eng, addr=args.addr, port=args.port)

    watcher = None
    if not args.no_reload:
        watcher = loader.ReloadWatcher(args.ckpt_dir, target_for_reload,
                                       target,
                                       poll_s=args.reload_poll_seconds)
        watcher.mark_current(step)
        watcher.start()

    server = frontend
    server.start()  # a taken --port is fatal: let the OSError surface
    logger.info("hvd-serve: ready on http://%s:%d (weights step %d, "
                "%d devices, %d replica%s)", args.addr, server.port,
                step, len(devs), args.fleet,
                "" if args.fleet == 1 else "s")

    done = threading.Event()

    def _sig(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        done.wait()
    finally:
        server.stop()
        if watcher is not None:
            watcher.stop()
        if router is not None:
            router.stop()  # stops every replica engine
        else:
            eng.stop()
        if tracer is not None and tracer.out_dir:
            n = len(tracer.traces())
            if n:
                merged = os.path.join(tracer.out_dir,
                                      "servetrace.merged.json")
                tracer.write_chrome(merged)
                logger.info("hvd-serve: wrote %d request trace(s) to "
                            "%s (ndjson) and %s (Chrome)", n,
                            tracer.out_dir, merged)
            tracer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
