"""The serving plane: continuous-batching inference straight from
sharded checkpoints (docs/SERVING.md).

Ten PRs of this framework train, checkpoint, reshard and attribute —
this package is what finally ANSWERS A REQUEST. The pieces compose from
what already exists rather than duplicating it:

* **weights** (``loader.py``) — a ``ckpt/`` MANIFEST loads params-only
  onto the inference mesh (ZeRO rows skipped; the N→M world-independent
  assembly of PR 9), and a :class:`ReloadWatcher` rolls newer
  checkpoints into the live engine without dropping traffic;
* **memory** (``kvcache.py``) — a paged KV pool (fixed-size blocks,
  per-sequence block tables, host-side allocator): cache memory scales
  with live tokens, not ``max_seq × max_batch``;
* **compute** (``engine.py``) — iteration-level continuous batching
  over two static-shaped AOT-compiled programs (chunked prefill +
  batched decode) on a ``GspmdPlan`` mesh, greedy sampling, per-request
  token streams;
* **frontend** (``server.py`` + ``cli.py``/``bin/hvd-serve``) — a
  streaming ``/generate`` endpoint on the shared stdlib HTTP
  scaffolding, ``/healthz`` + ``/metrics`` alongside, with the
  ``hvd_serve_*`` instrument family in the standard registry;
* **sampling** (``sampling.py``) — temperature / top-p with
  per-request seeds, keyed on (seed, absolute position) so streams
  are deterministic across replicas, batch composition, and
  mid-flight continuation (greedy stays the default and the
  ``temperature=0`` lane is bitwise the greedy argmax);
* **fleet** (``fleet/``) — N engine replicas behind one routing
  frontend: queue-depth/KV-headroom dispatch, rolling weight reload,
  and spot-preemption drains that re-dispatch cut-off streams to a
  survivor with zero dropped requests;
* **tracing** (``tracing.py``) — request-scoped span recording across
  router, engines and frontends: sampling-controlled, zero-cost when
  off, exported as ndjson for ``hvd-doctor serve`` and as merged
  Chrome traces (docs/OBSERVABILITY.md, "Debugging a slow request").

``bench_serve.py`` (repo root) is the load harness: p50/p99
time-to-first-token, inter-token latency, tokens/sec/chip under an
open-loop arrival schedule, with a goodput-style prefill/decode/idle
time-attribution block.
"""

from horovod_tpu.serve.engine import (  # noqa: F401
    Request,
    RequestError,
    ServeEngine,
)
from horovod_tpu.serve.fleet import (  # noqa: F401
    FleetRequest,
    FleetRouter,
    FleetServer,
    Replica,
)
from horovod_tpu.serve.kvcache import (  # noqa: F401
    BlockAllocator,
    KVCacheConfig,
    PrefixCache,
    init_pool,
)
from horovod_tpu.serve.loader import (  # noqa: F401
    ReloadWatcher,
    abstract_params,
    load_params,
)
from horovod_tpu.serve.sampling import (  # noqa: F401
    GREEDY,
    SamplingParams,
)
from horovod_tpu.serve.server import ServeServer  # noqa: F401
from horovod_tpu.serve.tracing import (  # noqa: F401
    SPAN_KINDS,
    RequestTrace,
    ServeTracer,
)

__all__ = [
    "ServeEngine", "Request", "RequestError",
    "KVCacheConfig", "BlockAllocator", "PrefixCache", "init_pool",
    "load_params", "abstract_params", "ReloadWatcher",
    "ServeServer", "SamplingParams", "GREEDY",
    "Replica", "FleetRouter", "FleetRequest", "FleetServer",
    "ServeTracer", "RequestTrace", "SPAN_KINDS",
]
